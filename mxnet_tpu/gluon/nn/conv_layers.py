"""Convolution and pooling layers.

Reference: python/mxnet/gluon/nn/conv_layers.py (1,811 LoC — _Conv base,
Conv1D/2D/3D(+Transpose), Max/Avg pooling, global pooling, reflection pad).
Layouts default to the reference's NCHW family; XLA:TPU's layout assignment
re-tiles internally so NCHW runs at full MXU rate.
"""
from __future__ import annotations

import numpy as _np

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock
from ..parameter import Parameter
from .basic_layers import _Resolving

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tuple(x, n):
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,) * n


class _Conv(_Resolving):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", transpose=False,
                 output_padding=0, dtype="float32"):
        super().__init__()
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._strides = _tuple(strides, ndim)
        self._padding = _tuple(padding, ndim)
        self._dilation = _tuple(dilation, ndim)
        self._groups = groups
        self._layout = layout
        self._activation = activation
        self._transpose = transpose
        self._output_padding = _tuple(output_padding, ndim)
        if transpose:
            wshape = (in_channels, channels // groups) + kernel_size
        else:
            wshape = (channels, in_channels // groups if in_channels else 0) \
                + kernel_size
        self.weight = Parameter("weight", shape=wshape, dtype=dtype,
                                init=weight_initializer,
                                allow_deferred_init=True,
                                sharding=("tp",) + (None,) * (ndim + 1))
        self.bias = (Parameter("bias", shape=(channels,), dtype=dtype,
                               init=bias_initializer,
                               allow_deferred_init=True)
                     if use_bias else None)

    def infer_shape(self, x, *args):
        c_axis = self._layout.index("C")
        in_c = x.shape[c_axis]
        if self._transpose:
            self.weight.shape = (in_c, self._channels // self._groups) + \
                self._kernel
        else:
            self.weight.shape = (self._channels, in_c // self._groups) + \
                self._kernel
        if self.bias is not None:
            self.bias.shape = (self._channels,)

    def forward(self, x):
        self._resolve(x)
        bias = self.bias.data() if self.bias is not None else None
        if self._transpose:
            out = nd.deconvolution(
                x, self.weight.data(), bias, kernel=self._kernel,
                stride=self._strides, dilate=self._dilation,
                pad=self._padding, adj=self._output_padding,
                num_filter=self._channels, num_group=self._groups,
                no_bias=bias is None, layout=self._layout)
        else:
            out = nd.convolution(
                x, self.weight.data(), bias, kernel=self._kernel,
                stride=self._strides, dilate=self._dilation,
                pad=self._padding, num_filter=self._channels,
                num_group=self._groups, no_bias=bias is None,
                layout=self._layout)
        if self._activation:
            out = nd.Activation(out, act_type=self._activation)
        return out

    def __repr__(self):
        return "%s(%s, kernel_size=%s, stride=%s)" % (
            type(self).__name__, self._channels, self._kernel, self._strides)


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         transpose=True, output_padding=output_padding)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         transpose=True, output_padding=output_padding)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         transpose=True, output_padding=output_padding)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, global_pool, pool_type,
                 layout, count_include_pad=True, ceil_mode=False):
        super().__init__()
        self._kernel = pool_size
        self._stride = strides if strides is not None else pool_size
        self._pad = padding
        self._global = global_pool
        self._type = pool_type
        self._layout = layout
        self._count_include_pad = count_include_pad

    def forward(self, x):
        return nd.pooling(
            x, kernel=self._kernel, pool_type=self._type,
            stride=_tuple(self._stride, len(self._kernel)),
            pad=_tuple(self._pad, len(self._kernel)),
            global_pool=self._global,
            count_include_pad=self._count_include_pad, layout=self._layout)

    def __repr__(self):
        return "%s(size=%s, stride=%s)" % (type(self).__name__,
                                           self._kernel, self._stride)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 1), strides, padding, False,
                         "max", layout, ceil_mode=ceil_mode)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 2), strides, padding, False,
                         "max", layout, ceil_mode=ceil_mode)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 3), strides, padding, False,
                         "max", layout, ceil_mode=ceil_mode)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuple(pool_size, 1), strides, padding, False,
                         "avg", layout, count_include_pad, ceil_mode)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuple(pool_size, 2), strides, padding, False,
                         "avg", layout, count_include_pad, ceil_mode)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuple(pool_size, 3), strides, padding, False,
                         "avg", layout, count_include_pad, ceil_mode)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, "max", layout)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, "max", layout)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "max", layout)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, "avg", layout)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, "avg", layout)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "avg", layout)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__()
        self._padding = padding

    def forward(self, x):
        p = self._padding
        return x.pad(((0, 0), (0, 0), (p, p), (p, p)), mode="reflect")


class _PixelShuffle(HybridBlock):
    """Base pixel-shuffle: regroup channel blocks into spatial blocks
    (reference conv_layers.py PixelShuffle1D/2D/3D; Shi et al. 2016).
    Channel layout matches the reference: (N, f1*..*fk*C, D1..Dk) ->
    (N, C, f1*D1, .., fk*Dk)."""

    def __init__(self, factor, ndim):
        super().__init__()
        self._f = (factor,) * ndim if isinstance(factor, int) \
            else tuple(factor)
        if len(self._f) != ndim:
            raise MXNetError("factor must have %d elements" % ndim)
        self._ndim = ndim

    def forward(self, x):
        f = self._f
        k = self._ndim
        N, C_in = x.shape[0], x.shape[1]
        spatial = x.shape[2:]
        prod_f = 1
        for fi in f:
            prod_f *= fi
        C = C_in // prod_f
        # C-major channel split like the reference's reshape(0, -4, -1,
        # f1*..*fk, 0, 0): channel index = c*prod(f) + (f1-major tap).
        # Built from the registered reshape/transpose ops so autograd
        # records the layout chain.
        xr = x.reshape((N, C) + f + tuple(spatial))
        perm = [0, 1]  # N, C
        for i in range(k):
            perm += [2 + k + i, 2 + i]  # Di, fi
        from ...ndarray import transpose as _transpose

        xt = _transpose(xr, axes=tuple(perm))
        out_spatial = tuple(spatial[i] * f[i] for i in range(k))
        return xt.reshape((N, C) + out_spatial)


class PixelShuffle1D(_PixelShuffle):
    """(N, f*C, W) -> (N, C, f*W) [reference conv_layers.py]."""

    def __init__(self, factor):
        super().__init__(factor, 1)


class PixelShuffle2D(_PixelShuffle):
    """(N, f1*f2*C, H, W) -> (N, C, f1*H, f2*W)."""

    def __init__(self, factor):
        super().__init__(factor, 2)


class PixelShuffle3D(_PixelShuffle):
    """(N, f1*f2*f3*C, D, H, W) -> (N, C, f1*D, f2*H, f3*W)."""

    def __init__(self, factor):
        super().__init__(factor, 3)


class DeformableConvolution(_Resolving):
    """Deformable conv v1/v2 (reference contrib deformable_convolution.cc /
    modulated_deformable_convolution.cc; Dai et al. 2017, Zhu et al. 2019).

    Two branches like the reference block: a regular conv producing the
    per-tap (dy, dx) offsets (and modulation mask for v2), and the
    deformable sampling conv itself.  The TPU rendering gathers each
    kernel tap with bilinear interpolation (one fused gather/einsum chain
    — no im2col buffer) and contracts taps x channels on the MXU.
    """

    def __init__(self, channels, kernel_size=(3, 3), strides=(1, 1),
                 padding=(1, 1), in_channels=0, num_deformable_group=1,
                 use_bias=True, modulated=False, weight_initializer=None,
                 prefix=None):
        super().__init__()
        from ... import initializer as init
        from ..parameter import Parameter

        self._kernel = (kernel_size,) * 2 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._strides = (strides,) * 2 if isinstance(strides, int) \
            else tuple(strides)
        self._padding = (padding,) * 2 if isinstance(padding, int) \
            else tuple(padding)
        self._channels = channels
        self._in_channels = in_channels
        self._dg = num_deformable_group
        self._modulated = modulated
        kh, kw = self._kernel
        n_off = self._dg * kh * kw * (3 if modulated else 2)
        self.offset_weight = Parameter(
            "offset_weight", shape=(n_off, in_channels, kh, kw),
            init=init.Zero(), allow_deferred_init=True)
        self.offset_bias = Parameter("offset_bias", shape=(n_off,),
                                     init=init.Zero())
        self.weight = Parameter(
            "weight", shape=(channels, in_channels, kh, kw),
            init=weight_initializer or init.Xavier(),
            allow_deferred_init=True)
        self.bias = Parameter("bias", shape=(channels,),
                              init=init.Zero()) if use_bias else None

    def infer_shape(self, x, *args):
        in_c = x.shape[1]
        kh, kw = self._kernel
        self.weight.shape = (self._channels, in_c, kh, kw)
        self.offset_weight.shape = (self.offset_weight.shape[0], in_c,
                                    kh, kw)

    def forward(self, x):
        from ...ops.registry import apply_op

        self._resolve(x)

        def full(data, w_off, b_off, w, bias):
            """Pure fn (offset conv + deformable sampling) run through the
            one-off invoke path so autograd records it like any op."""
            import jax
            import jax.numpy as jnp

            from ...ops.contrib_tail import deformable_convolution as dc

            sh, sw = self._strides
            ph, pw = self._padding
            kh, kw = self._kernel
            off = jax.lax.conv_general_dilated(
                data, w_off, (sh, sw), [(ph, ph), (pw, pw)]) + \
                b_off[None, :, None, None]
            mask = None
            if self._modulated:
                n2 = self._dg * kh * kw * 2
                off, mask = off[:, :n2], jax.nn.sigmoid(off[:, n2:])
            return dc.fn(data, off, w, bias, kernel=self._kernel,
                         stride=self._strides, pad=self._padding,
                         num_deformable_group=self._dg, mask=mask)

        bias = self.bias.data() if self.bias is not None else None
        args = [x, self.offset_weight.data(), self.offset_bias.data(),
                self.weight.data()]
        if bias is not None:
            return apply_op(full, *args, bias)
        return apply_op(lambda d, wo, bo, w: full(d, wo, bo, w, None),
                        *args)


class ModulatedDeformableConvolution(DeformableConvolution):
    """DCNv2: deformable conv with per-tap modulation mask (reference
    modulated_deformable_convolution.cc)."""

    def __init__(self, *args, **kwargs):
        kwargs["modulated"] = True
        super().__init__(*args, **kwargs)


__all__ += ["PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D",
            "DeformableConvolution", "ModulatedDeformableConvolution"]
