"""Transformer layers.

Reference coverage: the reference's transformer support is only the fused
attention GEMM ops ``_contrib_interleaved_matmul_selfatt_qk/valatt`` and
encdec variants (src/operator/contrib/transformer.cc:650-826) plus masking
utilities — users assembled blocks by hand (gluon-nlp did it downstream).
Here the block layer is first-class and TPU-native:

- the attention core is one fused einsum chain on the MXU
  (ops/nn.py multi_head_attention), with a Pallas flash-attention kernel
  for long sequences; for sequence-parallel long-context training use
  mxnet_tpu.parallel.ring_attention / ulysses_attention directly inside a
  pjit'd step (SURVEY §5.7);
- Dense weights carry tensor-parallel sharding hints (Megatron layout:
  qkv/ffn-in column-parallel over 'tp', out/ffn-out row-parallel) so a
  pjit'd trainer shards the whole block with zero user code.
"""
from __future__ import annotations

import math

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock
from .basic_layers import Dense, Dropout, Embedding, HybridSequential, \
    LayerNorm

__all__ = ["MultiHeadAttention", "PositionwiseFFN",
           "TransformerEncoderCell", "TransformerEncoder",
           "PositionalEmbedding", "SinusoidalPositionalEmbedding"]


class MultiHeadAttention(HybridBlock):
    """Multi-head (self/cross) attention with TP-sharded projections.

    forward(query, key=None, value=None, mask=None): key/value default to
    query (self-attention).  mask broadcasts against (B, H, Tq, Tk).
    ``dropout`` drops attention *probabilities* (the BERT recipe), active
    only in training mode; it forces the dense attention path.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 causal=False, attention_impl="auto", in_units=0, **kwargs):
        super().__init__()
        if units % num_heads:
            raise MXNetError("units %d not divisible by num_heads %d"
                             % (units, num_heads))
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        self._impl = attention_impl
        self._dropout = dropout
        # column-parallel in-projections, row-parallel out-projection.
        # in_units (when the caller knows the input dim) skips deferred
        # shape resolution — no eager probe pass is needed before jit.
        self.query_proj = Dense(units, use_bias=use_bias, flatten=False,
                                in_units=in_units)
        self.key_proj = Dense(units, use_bias=use_bias, flatten=False,
                              in_units=in_units)
        self.value_proj = Dense(units, use_bias=use_bias, flatten=False,
                                in_units=in_units)
        self.out_proj = Dense(units, use_bias=use_bias, flatten=False,
                              in_units=units)
        self.out_proj.weight.sharding = (None, "tp")
        if self.out_proj.bias is not None:
            self.out_proj.bias.sharding = (None,)

    def forward(self, query, key=None, value=None, mask=None):
        from ... import autograd, random as mxrandom

        key = query if key is None else key
        value = key if value is None else value
        q = self.query_proj(query)
        k = self.key_proj(key)
        v = self.value_proj(value)
        if self._dropout > 0.0 and autograd.is_training():
            # auto-dispatch handles dropout now: long sequences ride the
            # blockwise flash path (per-block mask, no (T,T) buffer)
            attn_kwargs = dict(attn_dropout=self._dropout,
                               dropout_key=mxrandom.take_key(),
                               impl=self._impl)
        else:
            attn_kwargs = dict(impl=self._impl)
        out = nd.multi_head_attention(
            q, k, v, num_heads=self._num_heads, mask=mask,
            causal=self._causal, **attn_kwargs)
        return self.out_proj(out)


class PositionwiseFFN(HybridBlock):
    """Transformer FFN: dense -> activation -> dense (+dropout), Megatron
    TP layout (ffn-in column-parallel, ffn-out row-parallel)."""

    def __init__(self, units, hidden_size, activation="gelu", dropout=0.0,
                 use_bias=True, in_units=0, **kwargs):
        super().__init__()
        self.ffn_1 = Dense(hidden_size, use_bias=use_bias, flatten=False,
                           activation=activation, in_units=in_units)
        self.ffn_2 = Dense(units, use_bias=use_bias, flatten=False,
                           in_units=hidden_size)
        self.ffn_2.weight.sharding = (None, "tp")
        if self.ffn_2.bias is not None:
            self.ffn_2.bias.sharding = (None,)
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.ffn_2(self.ffn_1(x))
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class TransformerEncoderCell(HybridBlock):
    """Pre/post-LN encoder block: MHA + FFN with residuals."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 attention_dropout=0.0, activation="gelu", pre_norm=False,
                 layer_norm_eps=1e-12, causal=False, **kwargs):
        super().__init__()
        self._pre_norm = pre_norm
        # the residual (x + h) pins the cell's input dim to units, so all
        # in_units are static — no deferred-shape probe needed
        self.attention = MultiHeadAttention(units, num_heads,
                                            dropout=attention_dropout,
                                            causal=causal, in_units=units)
        self.attn_ln = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, activation=activation,
                                   dropout=dropout, in_units=units)
        self.ffn_ln = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x, mask=None):
        if self._pre_norm:
            h = self.attention(self.attn_ln(x), mask=mask)
            x = x + (self.dropout(h) if self.dropout is not None else h)
            h = self.ffn(self.ffn_ln(x))
            return x + h
        h = self.attention(x, mask=mask)
        if self.dropout is not None:
            h = self.dropout(h)
        x = self.attn_ln(x + h)
        h = self.ffn(x)
        return self.ffn_ln(x + h)


class TransformerEncoder(HybridBlock):
    """Stack of encoder cells."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, attention_dropout=0.0, activation="gelu",
                 pre_norm=False, layer_norm_eps=1e-12, causal=False,
                 **kwargs):
        super().__init__()
        self._num_layers = num_layers
        self.layers = HybridSequential()
        for _ in range(num_layers):
            self.layers.add(TransformerEncoderCell(
                units, hidden_size, num_heads, dropout=dropout,
                attention_dropout=attention_dropout, activation=activation,
                pre_norm=pre_norm, layer_norm_eps=layer_norm_eps,
                causal=causal))

    def forward(self, x, mask=None):
        for cell in self.layers:
            x = cell(x, mask=mask)
        return x


class PositionalEmbedding(HybridBlock):
    """Learned positional embedding (BERT-style)."""

    def __init__(self, max_length, units, **kwargs):
        super().__init__()
        self.embed = Embedding(max_length, units)
        self._max_length = max_length

    def forward(self, x):
        """x: (B, T, C) token embeddings -> x + pos[:T]."""
        T = x.shape[1]
        if T > self._max_length:
            raise MXNetError(
                "sequence length %d exceeds max_length %d of the learned "
                "positional table" % (T, self._max_length))
        pos = nd.arange(T)
        return x + self.embed(pos).reshape((1, T, -1))


class SinusoidalPositionalEmbedding(HybridBlock):
    """Fixed sin/cos positional encoding (Vaswani et al.)."""

    def __init__(self, units, **kwargs):
        super().__init__()
        self._units = units

    def forward(self, x):
        import jax.numpy as jnp

        from ...ops.registry import apply_op

        T, C = x.shape[1], self._units

        def add_pe(data):
            pos = jnp.arange(T, dtype=jnp.float32)[:, None]
            dim = jnp.arange(0, C, 2, dtype=jnp.float32)[None, :]
            angle = pos / jnp.power(10000.0, dim / C)
            n_cos = C // 2  # odd units: one fewer cos slot than sin
            pe = jnp.zeros((T, C), data.dtype)
            pe = pe.at[:, 0::2].set(jnp.sin(angle).astype(data.dtype))
            pe = pe.at[:, 1::2].set(
                jnp.cos(angle[:, :n_cos]).astype(data.dtype))
            return data + pe[None]

        add_pe.__name__ = "sinusoidal_pe"
        return apply_op(add_pe, x)
