"""Basic neural network layers.

Reference: python/mxnet/gluon/nn/basic_layers.py (1,116 LoC — Dense,
Dropout, BatchNorm, LayerNorm, GroupNorm, InstanceNorm, Embedding, Flatten,
activations, Sequential containers).
"""
from __future__ import annotations

import numpy as _np

from ... import autograd
from ... import ndarray as nd
from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
           "Embedding", "Flatten", "Lambda", "HybridLambda", "Activation",
           "LeakyReLU", "PReLU", "ELU", "SELU", "GELU", "SiLU", "Swish",
           "Mish", "RMSNorm", "Identity", "Concatenate", "HybridConcatenate"]


class Sequential(Block):
    """Stack of blocks (reference basic_layers.py Sequential)."""

    def __init__(self, *blocks):
        super().__init__()
        for b in blocks:
            self.add(b)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __iter__(self):
        return iter(self._children.values())

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        vals = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*vals[key])
            return net
        return vals[key]


class HybridSequential(Sequential, HybridBlock):
    def __init__(self, *blocks):
        HybridBlock.__init__(self)
        for b in blocks:
            self.add(b)


class _Resolving(HybridBlock):
    """Leaf-layer base: resolves deferred parameter shapes on first call
    (the TPU stand-in for the deferred-compute shape-inference pass)."""

    def _resolve(self, *args):
        need = [p for p in self._reg_params.values() if p._data is None]
        if need:
            self.infer_shape(*args)
            for p in need:
                p._finish_deferred_init()


class Dense(_Resolving):
    """Fully-connected layer (reference basic_layers.py Dense →
    nn/fully_connected.cc).  Weight layout (units, in_units)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__()
        self._units = units
        self._flatten = flatten
        self._activation = activation
        self.weight = Parameter("weight", shape=(units, in_units),
                                dtype=dtype, init=weight_initializer,
                                allow_deferred_init=True,
                                sharding=("tp", None))
        self.bias = (Parameter("bias", shape=(units,), dtype=dtype,
                               init=bias_initializer,
                               allow_deferred_init=True,
                               sharding=("tp",))
                     if use_bias else None)

    def infer_shape(self, x, *args):
        in_units = (int(_np.prod(x.shape[1:])) if self._flatten
                    else x.shape[-1])
        self.weight.shape = (self._units, in_units)
        if self.bias is not None:
            self.bias.shape = (self._units,)

    def forward(self, x):
        self._resolve(x)
        out = nd.fully_connected(
            x, self.weight.data(),
            self.bias.data() if self.bias is not None else None,
            num_hidden=self._units, flatten=self._flatten,
            no_bias=self.bias is None)
        if self._activation:
            out = nd.Activation(out, act_type=self._activation)
        return out

    def __repr__(self):
        return "Dense(%s -> %d)" % (self.weight.shape[1] if self.weight.shape
                                    else None, self._units)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        return nd.dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(_Resolving):
    """Reference basic_layers.py BatchNorm → nn/batch_norm.cc.  Running
    stats are functionalized state (see ops/nn.py batch_norm docstring)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__()
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape,
                               init=gamma_initializer,
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True)
        self.beta = Parameter("beta", shape=shape, init=beta_initializer,
                              grad_req="write" if center else "null",
                              allow_deferred_init=True)
        self.running_mean = Parameter("running_mean", shape=shape,
                                      init=running_mean_initializer,
                                      grad_req="null",
                                      allow_deferred_init=True)
        self.running_var = Parameter("running_var", shape=shape,
                                     init=running_variance_initializer,
                                     grad_req="null",
                                     allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (c,)

    def forward(self, x):
        self._resolve(x)
        training = autograd.is_training() and not self._use_global_stats
        out, new_mean, new_var = nd.batch_norm(
            x, self.gamma.data(), self.beta.data(),
            self.running_mean.data(), self.running_var.data(),
            eps=self._eps, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats,
            axis=self._axis, training=training)
        if training:
            with autograd.pause():
                self.running_mean.set_data(new_mean.detach())
                self.running_var.set_data(new_var.detach())
        return out


class SyncBatchNorm(BatchNorm):
    """Cross-device BN (reference contrib sync_batch_norm-inl.h).  Under
    pjit/shard_map the batch axis is sharded and XLA turns the mean/var
    reductions into cross-replica collectives automatically, so this is
    BatchNorm; kept as a distinct class for API parity."""

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)


class LayerNorm(_Resolving):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__()
        self._axis = axis
        self._eps = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=gamma_initializer,
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True)
        self.beta = Parameter("beta", shape=shape, init=beta_initializer,
                              grad_req="write" if center else "null",
                              allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def forward(self, x):
        self._resolve(x)
        return nd.layer_norm(x, self.gamma.data(), self.beta.data(),
                             axis=self._axis, eps=self._eps)


class RMSNorm(_Resolving):
    """TPU-era extra (no reference equivalent; transformer staple)."""

    def __init__(self, axis=-1, epsilon=1e-6, in_channels=0, **kwargs):
        super().__init__()
        self._axis = axis
        self._eps = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,) if in_channels
                               else (0,), init="ones",
                               allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[self._axis],)

    def forward(self, x):
        self._resolve(x)
        return nd.rms_norm(x, self.gamma.data(), axis=self._axis,
                           eps=self._eps)


class GroupNorm(_Resolving):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__()
        self._num_groups = num_groups
        self._eps = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=gamma_initializer,
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True)
        self.beta = Parameter("beta", shape=shape, init=beta_initializer,
                              grad_req="write" if center else "null",
                              allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def forward(self, x):
        self._resolve(x)
        return nd.group_norm(x, self.gamma.data(), self.beta.data(),
                             num_groups=self._num_groups, eps=self._eps)


class InstanceNorm(_Resolving):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__()
        self._eps = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=gamma_initializer,
                               allow_deferred_init=True)
        self.beta = Parameter("beta", shape=shape, init=beta_initializer,
                              allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def forward(self, x):
        self._resolve(x)
        return nd.instance_norm(x, self.gamma.data(), self.beta.data(),
                                eps=self._eps)


class Embedding(_Resolving):
    """Reference basic_layers.py Embedding → tensor/indexing_op.cc.
    ``sparse_grad`` maps to a row_sparse gradient in the reference; on TPU
    the gather's gradient is a scatter-add XLA fuses well, so dense grads
    are kept (SURVEY §7 sparse decision)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__()
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = Parameter("weight", shape=(input_dim, output_dim),
                                dtype=dtype, init=weight_initializer,
                                grad_stype="row_sparse" if sparse_grad
                                else "default",
                                sharding=(None, "tp"))

    def forward(self, x):
        self._resolve(x)
        return nd.embedding(x, self.weight.data())

    def __repr__(self):
        return "Embedding(%d -> %d)" % (self._input_dim, self._output_dim)


class Flatten(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__()

    def forward(self, x):
        return x.reshape((x.shape[0], -1))


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Lambda(Block):
    def __init__(self, function):
        super().__init__()
        if isinstance(function, str):
            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function):
        super().__init__()
        if isinstance(function, str):
            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__()
        self._act_type = activation

    def forward(self, x):
        return nd.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "Activation(%s)" % self._act_type


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return nd.leaky_relu(x, slope=self._alpha)


class PReLU(_Resolving):
    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__()
        from ...initializer import Constant

        self.alpha = Parameter("alpha", shape=(in_channels,),
                               init=alpha_initializer or Constant(0.25))

    def forward(self, x):
        self._resolve(x)
        a = self.alpha.data()
        shape = [1] * x.ndim
        if x.ndim > 1:
            shape[1] = a.shape[0]
        return nd.prelu(x, a.reshape(shape))


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return nd.elu(x, alpha=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return nd.selu(x)


class GELU(HybridBlock):
    def __init__(self, approximation="erf", **kwargs):
        super().__init__()
        self._approx = approximation != "erf"

    def forward(self, x):
        return nd.gelu(x, approximate=self._approx)


class SiLU(HybridBlock):
    def forward(self, x):
        return nd.silu(x)


Swish = SiLU


class Mish(HybridBlock):
    def forward(self, x):
        return nd.mish(x)


class Concatenate(Sequential):
    """Run children on the same input, concat outputs (reference
    gluon/contrib Concurrent)."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        return nd.concat(*outs, dim=self._axis)


class HybridConcatenate(Concatenate, HybridBlock):
    def __init__(self, axis=-1):
        HybridBlock.__init__(self)
        self._axis = axis


class Swish(HybridBlock):
    """x * sigmoid(beta * x) (reference nn/activations.py Swish;
    Ramachandran et al. 2017)."""

    def __init__(self, beta=1.0):
        super().__init__()
        self._beta = beta

    def forward(self, x):
        from ...ops import nn as _opsnn

        return x * _opsnn.sigmoid(self._beta * x)


class BatchNormReLU(BatchNorm):
    """Fused BatchNorm + ReLU (reference nn/basic_layers.py BatchNormReLU
    — a cuDNN fusion; under XLA the relu fuses into the BN kernel
    automatically, so this is the same one compiled kernel)."""

    def forward(self, x):
        from ...ops import nn as _opsnn

        return _opsnn.relu(super().forward(x))


__all__ += ["Swish", "BatchNormReLU"]
