"""Gluon neural-net layers (reference python/mxnet/gluon/nn/)."""
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
from .moe import *  # noqa: F401,F403
from . import basic_layers, conv_layers, moe, transformer

__all__ = basic_layers.__all__ + conv_layers.__all__ + \
    transformer.__all__ + moe.__all__
