"""Gluon API (reference python/mxnet/gluon/)."""
from . import data, loss, metric, model_zoo, nn, rnn, utils
from .block import Block, HybridBlock, SymbolBlock
from .parameter import Constant, Parameter
from .trainer import Trainer

__all__ = ["Block", "HybridBlock", "SymbolBlock", "Parameter", "Constant",
           "Trainer", "nn", "rnn", "loss", "metric", "data", "utils",
           "model_zoo", "contrib", "probability"]

from . import contrib  # noqa: E402
from . import probability  # noqa: E402
