"""Evaluation metrics (reference python/mxnet/gluon/metric.py, 1,856 LoC —
EvalMetric base + registry, Accuracy/TopK/F1/MCC/MAE/MSE/RMSE/CE/Perplexity/
PearsonCorrelation/CompositeEvalMetric...)."""
from __future__ import annotations

import math

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "BinaryAccuracy", "MCC", "MAE", "MSE", "RMSE",
           "CrossEntropy", "Perplexity", "NegativeLogLikelihood",
           "PearsonCorrelation", "PCC", "Loss", "Torch", "Caffe",
           "CustomMetric", "create", "np"]

_METRIC_REGISTRY = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        out = CompositeEvalMetric()
        for m in metric:
            out.add(create(m))
        return out
    key = str(metric).lower()
    if key not in _METRIC_REGISTRY:
        raise MXNetError("unknown metric %r" % metric)
    return _METRIC_REGISTRY[key](*args, **kwargs)


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def _as_lists(labels, preds):
    if isinstance(labels, (NDArray, _np.ndarray)):
        labels = [labels]
    if isinstance(preds, (NDArray, _np.ndarray)):
        preds = [preds]
    return labels, preds


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label)
            pred = _to_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_np.int64).flatten()
            label = label.astype(_np.int64).flatten()
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__("%s_%d" % (name, top_k), **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).astype(_np.int64)
            pred = _to_np(pred)
            topk = _np.argsort(-pred, axis=-1)[..., :self.top_k]
            hit = (topk == label[..., None]).any(axis=-1)
            self.sum_metric += float(hit.sum())
            self.num_inst += hit.size


@register
class BinaryAccuracy(EvalMetric):
    def __init__(self, name="binary_accuracy", threshold=0.5, **kwargs):
        super().__init__(name, **kwargs)
        self.threshold = threshold

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            pred = (_to_np(pred) > self.threshold).astype(_np.int64).flatten()
            label = _to_np(label).astype(_np.int64).flatten()
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


class _BinaryStats:
    def __init__(self):
        self.tp = self.fp = self.tn = self.fn = 0

    def update(self, label, pred):
        pred = pred.argmax(axis=-1) if pred.ndim > 1 else (pred > 0.5)
        pred = pred.astype(_np.int64).flatten()
        label = label.astype(_np.int64).flatten()
        self.tp += int(((pred == 1) & (label == 1)).sum())
        self.fp += int(((pred == 1) & (label == 0)).sum())
        self.tn += int(((pred == 0) & (label == 0)).sum())
        self.fn += int(((pred == 0) & (label == 1)).sum())

    @property
    def precision(self):
        return self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0

    @property
    def recall(self):
        return self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0

    @property
    def f1(self):
        p, r = self.precision, self.recall
        return 2 * p * r / (p + r) if p + r else 0.0

    @property
    def total(self):
        return self.tp + self.fp + self.tn + self.fn

    @property
    def mcc(self):
        denom = math.sqrt((self.tp + self.fp) * (self.tp + self.fn) *
                          (self.tn + self.fp) * (self.tn + self.fn))
        if denom == 0:
            return 0.0
        return (self.tp * self.tn - self.fp * self.fn) / denom


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.stats = _BinaryStats()

    def reset(self):
        self.stats = _BinaryStats()
        super().reset()

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            self.stats.update(_to_np(label), _to_np(pred))

    def get(self):
        return (self.name, self.stats.f1)


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self.stats = _BinaryStats()

    def reset(self):
        self.stats = _BinaryStats()
        super().reset()

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            self.stats.update(_to_np(label), _to_np(pred))

    def get(self):
        return (self.name, self.stats.mcc)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += float(_np.abs(label - pred).mean()) * \
                label.shape[0]
            self.num_inst += label.shape[0]


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += float(((label - pred) ** 2).mean()) * \
                label.shape[0]
            self.num_inst += label.shape[0]


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        EvalMetric.__init__(self, name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).astype(_np.int64).flatten()
            pred = _to_np(pred).reshape(len(label), -1)
            prob = pred[_np.arange(len(label)), label]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += len(label)


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _to_np(label).astype(_np.int64).flatten()
            pred = _to_np(pred).reshape(len(label), -1)
            prob = pred[_np.arange(len(label)), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = prob[~ignore]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += len(prob)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)
        self._labels = []
        self._preds = []

    def reset(self):
        self._labels, self._preds = [], []
        super().reset()

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            self._labels.append(_to_np(label).flatten())
            self._preds.append(_to_np(pred).flatten())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return (self.name, float("nan"))
        l = _np.concatenate(self._labels)
        p = _np.concatenate(self._preds)
        return (self.name, float(_np.corrcoef(l, p)[0, 1]))


PCC = PearsonCorrelation


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        _, preds = _as_lists(_, preds)
        for pred in preds:
            pred = _to_np(pred)
            self.sum_metric += float(pred.sum())
            self.num_inst += pred.size


class Torch(Loss):
    def __init__(self, name="torch", **kwargs):
        super().__init__(name, **kwargs)


class Caffe(Loss):
    def __init__(self, name="caffe", **kwargs):
        super().__init__(name, **kwargs)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__("custom(%s)" % name, **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            val = self._feval(_to_np(label), _to_np(pred))
            if isinstance(val, tuple):
                s, n = val
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += val
                self.num_inst += 1


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = getattr(numpy_feval, "__name__", "feval")
    return CustomMetric(feval, name, allow_extra_outputs)


@register
class Fbeta(F1):
    """F-beta score (reference metric.py:815): weighted harmonic mean of
    precision/recall; beta > 1 favors recall."""

    def __init__(self, name="fbeta", beta=1, average="macro", **kwargs):
        super().__init__(name=name, average=average, **kwargs)
        self.beta = beta

    def get(self):
        p, r = self.stats.precision, self.stats.recall
        b2 = self.beta * self.beta
        denom = b2 * p + r
        return (self.name,
                (1 + b2) * p * r / denom if denom > 0 else 0.0)


@register
class MeanPairwiseDistance(EvalMetric):
    """Mean p-norm distance between pred and label vectors (reference
    metric.py:1197)."""

    def __init__(self, name="mpd", p=2, **kwargs):
        super().__init__(name, **kwargs)
        self.p = p

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            l_ = _to_np(label).reshape(_to_np(label).shape[0], -1)
            p_ = _to_np(pred).reshape(_to_np(pred).shape[0], -1)
            d = (_np.abs(p_ - l_) ** self.p).sum(axis=1) ** (1.0 / self.p)
            self.sum_metric += float(d.sum())
            self.num_inst += d.shape[0]


@register
class MeanCosineSimilarity(EvalMetric):
    """Mean cosine similarity along the last axis (reference
    metric.py:1263)."""

    def __init__(self, name="cos_sim", eps=1e-12, **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            l_, p_ = _to_np(label), _to_np(pred)
            num = (l_ * p_).sum(axis=-1)
            den = _np.sqrt((l_ * l_).sum(axis=-1)) * \
                _np.sqrt((p_ * p_).sum(axis=-1))
            sim = num / _np.maximum(den, self.eps)
            self.sum_metric += float(sim.sum())
            self.num_inst += sim.size


@register
class PCC(EvalMetric):
    """Multiclass Pearson correlation coefficient over the confusion
    matrix (reference metric.py:1586 — the k-category generalization of
    MCC, Gorodkin 2004)."""

    def __init__(self, name="pcc", **kwargs):
        super().__init__(name, **kwargs)
        self._conf = None

    def reset(self):
        self._conf = None
        super().reset()

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            l_ = _to_np(label).astype(_np.int64).flatten()
            p_ = _to_np(pred)
            p_ = p_.argmax(axis=-1) if p_.ndim > 1 else (p_ > 0.5)
            p_ = p_.astype(_np.int64).flatten()
            k = int(max(l_.max(), p_.max())) + 1
            if self._conf is None:
                self._conf = _np.zeros((k, k), _np.float64)
            elif self._conf.shape[0] < k:
                grown = _np.zeros((k, k), _np.float64)
                grown[:self._conf.shape[0], :self._conf.shape[1]] = \
                    self._conf
                self._conf = grown
            for li, pi in zip(l_, p_):
                self._conf[pi, li] += 1
            self.num_inst += l_.shape[0]

    def get(self):
        if self._conf is None:
            return (self.name, 0.0)
        c = self._conf
        n = c.sum()
        t = c.sum(axis=1)  # predicted-class totals
        s = c.sum(axis=0)  # true-class totals
        cov_xy = c.trace() * n - (t * s).sum()
        cov_xx = n * n - (t * t).sum()
        cov_yy = n * n - (s * s).sum()
        denom = _np.sqrt(cov_xx * cov_yy)
        return (self.name, float(cov_xy / denom) if denom > 0 else 0.0)
