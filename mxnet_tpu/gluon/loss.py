"""Loss blocks (reference python/mxnet/gluon/loss.py, 1,113 LoC — 15 loss
classes with sample_weight/batch_axis semantics)."""
from __future__ import annotations

import numpy as _np

from .. import ndarray as nd
from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss", "PoissonNLLLoss",
           "CosineEmbeddingLoss", "SDMLLoss"]


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(pred, label):
    if isinstance(label, NDArray) and label.shape != pred.shape:
        label = label.reshape(pred.shape)
    return label


class Loss(HybridBlock):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__()
        self._weight = weight
        self._batch_axis = batch_axis

    def _mean_nonbatch(self, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss

    def __repr__(self):
        return "%s(batch_axis=%s, w=%s)" % (type(self).__name__,
                                            self._batch_axis, self._weight)


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.square(label - pred)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return self._mean_nonbatch(loss)


class L1Loss(Loss):
    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.abs(label - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            # log(1+exp(-|x|)) + max(x,0) - x*y form, numerically stable
            softplus_neg = nd.log(1.0 + nd.exp(-nd.abs(pred))) + \
                nd.relu(-pred)  # = log(1+exp(-x)) = -log(sigmoid(x))
            if pos_weight is None:
                loss = nd.relu(pred) - pred * label + \
                    nd.log(1.0 + nd.exp(-nd.abs(pred)))
            else:
                # weighted: (1-y)*x + (1 + (pw-1)*y) * (-log(sigmoid(x)))
                log_weight = 1 + (pos_weight - 1) * label
                loss = (1 - label) * pred + log_weight * softplus_neg
        else:
            eps = 1e-12
            loss = -(nd.log(pred + eps) * label +
                     nd.log(1 - pred + eps) * (1 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Reference loss.py SoftmaxCrossEntropyLoss (sparse_label etc.)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = nd.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -nd.pick(pred, label, axis=self._axis, keepdims=False)
        else:
            label = _reshape_like(pred, label)
            loss = -(pred * label).sum(axis=self._axis, keepdims=False)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = nd.log_softmax(pred, axis=self._axis)
        loss = label * (nd.log(label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class CTCLoss(Loss):
    """Reference loss.py CTCLoss → nn/ctc_loss.cc (layouts TNC/NTC)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        loss = nd.ctc_loss(pred, label, pred_lengths, label_lengths)
        return _apply_weighting(loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        err = nd.abs(label - pred)
        loss = nd.where(err > self._rho,
                        err - 0.5 * self._rho,
                        (0.5 / self._rho) * nd.square(err))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.relu(self._margin - pred * label)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.square(nd.relu(self._margin - pred * label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = nd.relu(pred) - pred * label + \
            nd.log(1.0 + nd.exp(-nd.abs(pred)))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(pred, positive)
        negative = _reshape_like(pred, negative)
        loss = (nd.square(pred - positive) -
                nd.square(pred - negative)).sum(
                    axis=tuple(range(1, pred.ndim)))
        loss = nd.relu(loss + self._margin)
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(pred, target)
        if self._from_logits:
            loss = nd.exp(pred) - target * pred
        else:
            loss = pred - target * nd.log(pred + epsilon)
        if self._compute_full:
            stirling = target * nd.log(target + 1e-12) - target + \
                0.5 * nd.log(2 * _np.pi * (target + 1e-12))
            stirling = nd.where(target <= 1, nd.zeros_like(stirling),
                                stirling)
            loss = loss + stirling
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean()


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        cos = (input1 * input2).sum(axis=-1) / (
            nd.sqrt(nd.square(input1).sum(axis=-1)) *
            nd.sqrt(nd.square(input2).sum(axis=-1)) + 1e-12)
        label = label.reshape(cos.shape)
        loss = nd.where(label == 1, 1 - cos,
                        nd.relu(cos - self._margin))
        return _apply_weighting(loss, self._weight, sample_weight)


class SDMLLoss(Loss):
    """Smoothed deep metric learning (reference loss.py SDMLLoss)."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._sp = smoothing_parameter

    def forward(self, x1, x2):
        batch = x1.shape[0]
        # pairwise negative euclidean distance as logits
        d = nd.sqrt(nd.square(
            x1.expand_dims(1) - x2.expand_dims(0)).sum(axis=-1) + 1e-12)
        logits = -d
        labels = nd.one_hot(nd.arange(batch), batch) * \
            (1 - self._sp - self._sp / (batch - 1)) + self._sp / (batch - 1)
        logp = nd.log_softmax(logits, axis=-1)
        return -(labels * logp).sum(axis=-1)
