"""Gluon Parameter.

Reference: python/mxnet/gluon/parameter.py:47 — deferred init, per-context
replicas (_init_grad:379), grad_req, row_sparse grad support.

TPU-native changes:
- A parameter owns ONE logical array (a jax.Array), not per-GPU replicas;
  multi-device is expressed by a `sharding` hint consumed by
  mxnet_tpu.parallel when the enclosing computation is pjit-ed over a Mesh
  (this is the TP/ZeRO hook the reference never had — SURVEY §2.3).
  Per-context replica API (list_data/list_grad) is kept for compat and
  returns views on the single array.
- During hybridize tracing, ``data()`` returns the traced stand-in so the
  whole block lowers to one XLA computation (see block.py).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError, _as_np_dtype
from ..context import current_context
from ..ndarray.ndarray import NDArray
from .. import initializer as init_mod

__all__ = ["Parameter", "Constant", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before shape is known (reference parameter.py)."""


# active trace contexts (stack) — block.py pushes/pops
_trace_stack = []


class Parameter:
    def __init__(self, name="weight", grad_req="write", shape=None,
                 dtype="float32", lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default", sharding=None):
        self._name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = _as_np_dtype(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self.grad_req = grad_req if differentiable else "null"
        self._differentiable = differentiable
        self.stype = stype
        self.grad_stype = grad_stype
        # TP/FSDP sharding hint: a jax PartitionSpec-like tuple of axis names
        self.sharding = sharding
        self._data = None            # NDArray
        self._ctx = None
        self._deferred_init = None   # (init, ctx, default_init)
        self.attrs = {}

    # ---- identity ---------------------------------------------------------
    @property
    def name(self):
        return self._name

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self._name, self._shape, self.dtype)

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if new_shape is None:
            return
        if self._shape is not None:
            matched = len(self._shape) == len(new_shape) and all(
                s in (0, n) or s == n or n in (0, -1)
                for s, n in zip(self._shape, new_shape))
            if not matched and self._data is not None:
                raise MXNetError(
                    "cannot reset shape of initialized Parameter %s from %s "
                    "to %s" % (self._name, self._shape, new_shape))
        self._shape = tuple(int(s) for s in new_shape)

    def _needs_shape(self):
        return self._shape is None or any(s in (0, -1) for s in self._shape)

    # ---- init -------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        self._ctx = ctx
        if self._needs_shape():
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise DeferredInitializationError(
                "Parameter %s has unknown shape %s and allow_deferred_init "
                "is False" % (self._name, self._shape))
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        import jax.numpy as jnp

        arr = NDArray(jnp.zeros(self._shape, self.dtype), ctx=ctx)
        initializer = init or self.init or default_init
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        initializer(init_mod.InitDesc(self._name, self.attrs), arr)
        if arr.dtype != self.dtype:
            arr = arr.astype(self.dtype)
        self._data = arr
        self._deferred_init = None
        if self.grad_req != "null":
            self._data.attach_grad(self.grad_req)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if self._needs_shape():
            raise DeferredInitializationError(
                "Parameter %s still has unknown shape %s" %
                (self._name, self._shape))
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    # ---- access -----------------------------------------------------------
    def _check_initialized(self):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "Parameter %s was not initialized yet: shape unknown. "
                    "Run a forward pass or call infer_shape first."
                    % self._name)
            raise MXNetError(
                "Parameter %s has not been initialized; call .initialize()"
                % self._name)

    def data(self, ctx=None):
        # during hybridize tracing, hand out the traced stand-in
        for tctx in reversed(_trace_stack):
            sub = tctx.substitution.get(id(self))
            if sub is not None:
                return sub
        self._check_initialized()
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._data._grad is None:
            raise MXNetError("Parameter %s has grad_req='null'" % self._name)
        return self._data._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        return [self._data.context]

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            self._data.zero_grad()

    def set_data(self, data):
        if _trace_stack:
            tctx = _trace_stack[-1]
            if id(self) in tctx.substitution:
                tctx.record_state_update(self, data)
                return
        self.shape = data.shape
        if self._data is None:
            if self._deferred_init is not None:
                self._finish_deferred_init()
            else:
                self._data = NDArray(data._data if isinstance(data, NDArray)
                                     else data)
                if self.grad_req != "null":
                    self._data.attach_grad(self.grad_req)
                return
        d = data._data if isinstance(data, NDArray) else data
        import jax.numpy as jnp

        self._data._data = jnp.asarray(d, dtype=self.dtype)

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data = self._data.as_in_context(ctx)
            if self.grad_req != "null":
                self._data.attach_grad(self.grad_req)

    def cast(self, dtype):
        self.dtype = _as_np_dtype(dtype)
        if self._data is not None:
            self._data = self._data.astype(self.dtype)
            if self.grad_req != "null":
                self._data.attach_grad(self.grad_req)

    def var(self):
        from ..symbol import Symbol

        return Symbol.var(self._name, shape=self._shape)

    def __copy__(self):
        return self

    def __deepcopy__(self, memo):
        return self


class Constant(Parameter):
    """Non-learnable parameter (reference gluon/parameter.py Constant)."""

    def __init__(self, value, name="const"):
        if isinstance(value, NDArray):
            value_np = value.asnumpy()
        else:
            value_np = _np.asarray(value, dtype=_np.float32)
        super().__init__(name=name, grad_req="null",
                         shape=value_np.shape, dtype=value_np.dtype,
                         init=init_mod.Constant(0.0))
        self._value = value_np

    def _finish_init(self, init, ctx, default_init):
        import jax.numpy as jnp

        self._data = NDArray(jnp.asarray(self._value), ctx=ctx)
        self._deferred_init = None
