"""Fused multi-layer RNN/LSTM/GRU layers.

Reference: the monolithic fused RNN op (NNVM_REGISTER_OP(RNN),
src/operator/rnn.cc:295 — cuDNN descriptors on GPU, rnn_impl.h on CPU)
wrapped by python/mxnet/gluon/rnn/rnn_layer.py.

TPU-native: the recurrence is a single ``lax.scan`` over time with all
layers' gate GEMMs batched — XLA compiles the whole sequence loop into one
program (the cuDNN-RNN equivalent on TPU).  Weight layout follows the
reference's flat i2h/h2h per layer/direction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ... import ndarray as nd
from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ...ops.registry import apply_op
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _cell_step(mode, x_gates, h_gates, h, c):
    """One timestep given precomputed input gates + hidden gates."""
    H = h.shape[-1]
    g = x_gates + h_gates
    if mode == "rnn_relu":
        nh = jnp.maximum(g, 0)
        return nh, c
    if mode == "rnn_tanh":
        nh = jnp.tanh(g)
        return nh, c
    if mode == "lstm":
        i = jax.nn.sigmoid(g[..., :H])
        f = jax.nn.sigmoid(g[..., H:2 * H])
        gg = jnp.tanh(g[..., 2 * H:3 * H])
        o = jax.nn.sigmoid(g[..., 3 * H:])
        nc = f * c + i * gg
        nh = o * jnp.tanh(nc)
        return nh, nc
    if mode == "gru":
        # gru mixes r into h2h new-gate term: need separate handling
        raise AssertionError("gru handled in _layer_scan")
    raise MXNetError("unknown mode %s" % mode)


def _layer_scan(mode, x, h0, c0, wi, wh, bi, bh):
    """Scan one direction of one layer.  x: (T, B, I) -> (T, B, H)."""
    H = h0.shape[-1]
    # batch the input GEMM over all timesteps at once (MXU-friendly)
    x_gates = jnp.einsum("tbi,gi->tbg", x, wi) + bi

    if mode == "gru":
        def step(carry, xg):
            h, _ = carry
            hg = jnp.einsum("bh,gh->bg", h, wh) + bh
            r = jax.nn.sigmoid(xg[..., :H] + hg[..., :H])
            z = jax.nn.sigmoid(xg[..., H:2 * H] + hg[..., H:2 * H])
            n = jnp.tanh(xg[..., 2 * H:] + r * hg[..., 2 * H:])
            nh = (1 - z) * n + z * h
            return (nh, nh), nh
    else:
        def step(carry, xg):
            h, c = carry
            hg = jnp.einsum("bh,gh->bg", h, wh) + bh
            nh, nc = _cell_step(mode, xg, hg, h, c)
            return (nh, nc), nh

    (hT, cT), outs = lax.scan(step, (h0, c0), x_gates)
    return outs, hT, cT


def _rnn_forward(x, h0, c0, mode, num_layers, bidirectional, dropout, key,
                 *weights):
    """Full fused RNN: x (T, B, I); weights flat list per (layer, dir):
    wi, wh, bi, bh."""
    ndir = 2 if bidirectional else 1
    idx = 0
    hs, cs = [], []
    for layer in range(num_layers):
        outs_dir = []
        for d in range(ndir):
            wi, wh, bi, bh = weights[idx:idx + 4]
            idx += 4
            xd = x if d == 0 else jnp.flip(x, axis=0)
            li = layer * ndir + d
            outs, hT, cT = _layer_scan(mode, xd, h0[li], c0[li], wi, wh,
                                       bi, bh)
            if d == 1:
                outs = jnp.flip(outs, axis=0)
            outs_dir.append(outs)
            hs.append(hT)
            cs.append(cT)
        x = outs_dir[0] if ndir == 1 else jnp.concatenate(outs_dir, axis=-1)
        if dropout > 0 and layer < num_layers - 1 and key is not None:
            keep = 1.0 - dropout
            mask = jax.random.bernoulli(
                jax.random.fold_in(key, layer), keep, x.shape)
            x = x * mask.astype(x.dtype) / keep
    return x, jnp.stack(hs), jnp.stack(cs)


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, dtype="float32",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__()
        assert layout in ("TNC", "NTC")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._dtype = dtype
        ng = _GATES[mode]
        self._gates = ng
        for layer in range(num_layers):
            for d in range(self._dir):
                suffix = "l%d%s" % (layer, "_r" if d else "")
                isz = input_size if layer == 0 else \
                    hidden_size * self._dir
                setattr(self, "%s_i2h_weight" % suffix,
                        Parameter("%s_i2h_weight" % suffix,
                                  shape=(ng * hidden_size, isz or 0),
                                  init=i2h_weight_initializer, dtype=dtype,
                                  allow_deferred_init=True))
                setattr(self, "%s_h2h_weight" % suffix,
                        Parameter("%s_h2h_weight" % suffix,
                                  shape=(ng * hidden_size, hidden_size),
                                  init=h2h_weight_initializer, dtype=dtype))
                setattr(self, "%s_i2h_bias" % suffix,
                        Parameter("%s_i2h_bias" % suffix,
                                  shape=(ng * hidden_size,),
                                  init=i2h_bias_initializer, dtype=dtype))
                setattr(self, "%s_h2h_bias" % suffix,
                        Parameter("%s_h2h_bias" % suffix,
                                  shape=(ng * hidden_size,),
                                  init=h2h_bias_initializer, dtype=dtype))

    def infer_shape(self, x, *args):
        isz = x.shape[-1]
        for layer in range(self._num_layers):
            for d in range(self._dir):
                suffix = "l%d%s" % (layer, "_r" if d else "")
                p = self._reg_params["%s_i2h_weight" % suffix]
                layer_in = isz if layer == 0 else \
                    self._hidden_size * self._dir
                p.shape = (self._gates * self._hidden_size, layer_in)

    def _resolve(self, x):
        need = [p for p in self._reg_params.values() if p._data is None]
        if need:
            self.infer_shape(x)
            for p in need:
                p._finish_deferred_init()

    def state_info(self, batch_size=0):
        num = self._num_layers * self._dir
        shapes = [{"shape": (num, batch_size, self._hidden_size)}]
        if self._mode == "lstm":
            shapes.append({"shape": (num, batch_size, self._hidden_size)})
        return shapes

    def begin_state(self, batch_size=0, func=None, **kwargs):
        func = func or nd.zeros
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def forward(self, inputs, states=None):
        self._resolve(inputs if self._layout == "TNC"
                      else inputs.swapaxes(0, 1))
        x = inputs if self._layout == "TNC" else inputs.swapaxes(0, 1)
        batch = x.shape[1]
        return_states = states is not None
        if states is None:
            states = self.begin_state(batch, dtype=str(self._dtype))
        if self._mode == "lstm":
            h0, c0 = states
        else:
            h0 = states[0] if isinstance(states, (list, tuple)) else states
            c0 = nd.zeros_like(h0)
        weights = []
        for layer in range(self._num_layers):
            for d in range(self._dir):
                suffix = "l%d%s" % (layer, "_r" if d else "")
                for part in ("i2h_weight", "h2h_weight", "i2h_bias",
                             "h2h_bias"):
                    weights.append(
                        self._reg_params["%s_%s" % (suffix, part)].data())
        from ... import autograd, random as mxrandom

        drop = self._dropout if autograd.is_training() else 0.0
        key = mxrandom.take_key() if drop > 0 else None

        def fused(x_, h0_, c0_, *ws):
            return _rnn_forward(x_, h0_, c0_, self._mode, self._num_layers,
                                self._dir == 2, drop, key, *ws)

        fused.__name__ = "rnn_%s" % self._mode
        out, hT, cT = apply_op(fused, x, h0, c0, *weights)
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        if not return_states:
            return out
        if self._mode == "lstm":
            return out, [hT, cT]
        return out, [hT]


class RNN(_RNNLayer):
    """Vanilla RNN (reference rnn_layer.py RNN; activation relu/tanh)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 input_size=0, **kwargs):
        super().__init__("rnn_" + activation, hidden_size, num_layers,
                         layout, dropout, bidirectional, input_size,
                         **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)
