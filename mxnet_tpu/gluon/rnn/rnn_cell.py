"""RNN cells (reference python/mxnet/gluon/rnn/rnn_cell.py, 1,493 LoC)."""
from __future__ import annotations

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell", "HybridSequentialRNNCell"]


class RecurrentCell(HybridBlock):
    def __init__(self):
        super().__init__()
        self._modified = False

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], **kwargs)
                          if "shape" in info else func(**kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unrolled application (reference rnn_cell.py unroll).  Python loop
        at eager level; under hybridize the loop is traced once and XLA
        compiles the unrolled graph."""
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        outputs = []
        for t in range(length):
            step = nd.take(inputs, nd.array([t], dtype="int32"),
                           axis=axis).squeeze(axis=axis)
            out, states = self(step, states)
            outputs.append(out)
        if valid_length is not None:
            stacked = nd.stack(*outputs, axis=0)
            stacked = nd.sequence_mask(stacked, valid_length,
                                       use_sequence_length=True, axis=0)
            outputs = stacked.swapaxes(0, 1) if axis == 1 else stacked
        elif merge_outputs is None or merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def _get_activation(self, x, activation):
        if callable(activation):
            return activation(x)
        return nd.Activation(x, act_type=activation)


class _BaseRNNCell(RecurrentCell):
    def __init__(self, hidden_size, num_gates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32"):
        super().__init__()
        self._hidden_size = hidden_size
        self._input_size = input_size
        ng = num_gates
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(ng * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    dtype=dtype, allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(ng * hidden_size, hidden_size),
                                    init=h2h_weight_initializer, dtype=dtype)
        self.i2h_bias = Parameter("i2h_bias", shape=(ng * hidden_size,),
                                  init=i2h_bias_initializer, dtype=dtype)
        self.h2h_bias = Parameter("h2h_bias", shape=(ng * hidden_size,),
                                  init=h2h_bias_initializer, dtype=dtype)
        self._ng = ng

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._ng * self._hidden_size, x.shape[-1])

    def _resolve(self, x):
        need = [p for p in self._reg_params.values() if p._data is None]
        if need:
            self.infer_shape(x)
            for p in need:
                p._finish_deferred_init()


class RNNCell(_BaseRNNCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def forward(self, inputs, states):
        self._resolve(inputs)
        i2h = nd.fully_connected(inputs, self.i2h_weight.data(),
                                 self.i2h_bias.data(),
                                 num_hidden=self._hidden_size, flatten=False)
        h2h = nd.fully_connected(states[0], self.h2h_weight.data(),
                                 self.h2h_bias.data(),
                                 num_hidden=self._hidden_size, flatten=False)
        out = self._get_activation(i2h + h2h, self._activation)
        return out, [out]


class LSTMCell(_BaseRNNCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def forward(self, inputs, states):
        self._resolve(inputs)
        H = self._hidden_size
        gates = nd.fully_connected(
            inputs, self.i2h_weight.data(), self.i2h_bias.data(),
            num_hidden=4 * H, flatten=False) + nd.fully_connected(
            states[0], self.h2h_weight.data(), self.h2h_bias.data(),
            num_hidden=4 * H, flatten=False)
        i = nd.sigmoid(gates[..., :H])
        f = nd.sigmoid(gates[..., H:2 * H])
        g = nd.tanh(gates[..., 2 * H:3 * H])
        o = nd.sigmoid(gates[..., 3 * H:])
        c = f * states[1] + i * g
        h = o * nd.tanh(c)
        return h, [h, c]


class GRUCell(_BaseRNNCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def forward(self, inputs, states):
        self._resolve(inputs)
        H = self._hidden_size
        prev = states[0]
        i2h = nd.fully_connected(inputs, self.i2h_weight.data(),
                                 self.i2h_bias.data(), num_hidden=3 * H,
                                 flatten=False)
        h2h = nd.fully_connected(prev, self.h2h_weight.data(),
                                 self.h2h_bias.data(), num_hidden=3 * H,
                                 flatten=False)
        r = nd.sigmoid(i2h[..., :H] + h2h[..., :H])
        z = nd.sigmoid(i2h[..., H:2 * H] + h2h[..., H:2 * H])
        n = nd.tanh(i2h[..., 2 * H:] + r * h2h[..., 2 * H:])
        h = (1 - z) * n + z * prev
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self):
        super().__init__()

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        out = []
        for cell in self._children.values():
            out.extend(cell.state_info(batch_size))
        return out

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for cell in self._children.values():
            states.append(cell.begin_state(batch_size, **kwargs))
        return states

    def forward(self, inputs, states):
        next_states = []
        for cell, state in zip(self._children.values(), states):
            inputs, new_state = cell(inputs, state)
            next_states.append(new_state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)


HybridSequentialRNNCell = SequentialRNNCell


class _ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__()
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        if self._rate > 0:
            inputs = nd.dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ZoneoutCell(_ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def forward(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)
        from ... import autograd, random as mxrandom

        if autograd.is_training():
            if self._zo > 0:
                mask = mxrandom.bernoulli(1 - self._zo, shape=out.shape)
                prev = self._prev_output if self._prev_output is not None \
                    else nd.zeros_like(out)
                out = mask * out + (1 - mask) * prev
            if self._zs > 0:
                next_states = [
                    mxrandom.bernoulli(1 - self._zs, shape=ns.shape) * ns +
                    (1 - mxrandom.bernoulli(1 - self._zs, shape=ns.shape))
                    * s for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states

    def reset(self):
        self._prev_output = None


class ResidualCell(_ModifierCell):
    def forward(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell):
        super().__init__()
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        return (self._children["l_cell"].state_info(batch_size) +
                self._children["r_cell"].state_info(batch_size))

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        axis = layout.find("T")
        l_out, l_states = l_cell.unroll(length, inputs, None, layout, True,
                                        valid_length)
        rev = nd.flip(inputs, axis=axis) if valid_length is None else \
            nd.sequence_reverse(inputs.swapaxes(0, axis), valid_length,
                                True).swapaxes(0, axis)
        r_out, r_states = r_cell.unroll(length, rev, None, layout, True,
                                        valid_length)
        r_out = nd.flip(r_out, axis=axis) if valid_length is None else \
            nd.sequence_reverse(r_out.swapaxes(0, axis), valid_length,
                                True).swapaxes(0, axis)
        out = nd.concat(l_out, r_out, dim=2)
        return out, l_states + r_states

    def forward(self, inputs, states):
        raise MXNetError("BidirectionalCell must be used with unroll()")


class VariationalDropoutCell(_ModifierCell):
    """Variational (locked) dropout (reference rnn_cell.py:1090, Gal &
    Ghahramani 2016): ONE dropout mask per sequence, reused at every time
    step, separately for inputs/states/outputs.  Masks are drawn lazily on
    the first step after ``reset()``."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self._di = drop_inputs
        self._ds = drop_states
        self._do = drop_outputs
        self.reset()

    def reset(self):
        self._mask_i = self._mask_s = self._mask_o = None
        if hasattr(self.base_cell, "reset"):
            self.base_cell.reset()

    @staticmethod
    def _mask(p, arr):
        from ... import random as mxrandom

        keep = 1.0 - p
        return mxrandom.bernoulli(keep, shape=arr.shape) / keep

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, **kwargs):
        """Fresh masks per sequence (reference rnn_cell.py:1141 — its
        unroll also resets before the time loop)."""
        self.reset()
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs,
                              **kwargs)

    def forward(self, inputs, states):
        from ... import autograd

        if autograd.is_training():
            if self._di > 0:
                if self._mask_i is None or \
                        self._mask_i.shape != inputs.shape:
                    self._mask_i = self._mask(self._di, inputs)
                inputs = inputs * self._mask_i
            if self._ds > 0 and states:
                if self._mask_s is None or \
                        self._mask_s.shape != states[0].shape:
                    self._mask_s = self._mask(self._ds, states[0])
                states = [states[0] * self._mask_s] + list(states[1:])
        out, next_states = self.base_cell(inputs, states)
        if autograd.is_training() and self._do > 0:
            if self._mask_o is None or self._mask_o.shape != out.shape:
                self._mask_o = self._mask(self._do, out)
            out = out * self._mask_o
        return out, next_states

    def __repr__(self):
        return "VariationalDropoutCell(%r)" % (self.base_cell,)


__all__.append("VariationalDropoutCell")
