"""Sparse NDArray: row_sparse + CSR.

Reference: kRowSparseStorage / kCSRStorage (include/mxnet/ndarray.h:61-66),
src/operator/tensor/cast_storage, sparse dot (tensor/dot-inl.h).

TPU-native design decision (SURVEY §7 hard part 2): XLA is dense-only, so
sparse storage is a *format* held as dense index/value buffers on device;
ops that have efficient gather/scatter/segment-sum lowerings run on TPU
(row_sparse dot, sparse grads for embeddings), everything else falls back by
densifying — the same philosophy as the reference's storage-fallback
executor (src/imperative/attach_op_execs_pass.cc:50), with the fallback
being "densify" instead of "copy to CPU".
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError, _as_np_dtype
from .ndarray import NDArray

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros"]


def _jnp():
    import jax.numpy as jnp

    return jnp


class BaseSparseNDArray(NDArray):
    __slots__ = ("indices_", "indptr_", "_shape")

    @property
    def shape(self):
        return self._shape

    def asnumpy(self):
        return self.tostype("default").asnumpy()


class RowSparseNDArray(BaseSparseNDArray):
    """(data[K, ...], indices[K]) — K stored rows of a larger array."""

    def __init__(self, data, indices, shape):
        super().__init__(data)
        self.indices_ = indices
        self.indptr_ = None
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self):
        return NDArray(self._data)

    @property
    def indices(self):
        return NDArray(self.indices_)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype != "default":
            raise MXNetError("cast_storage row_sparse->%s unsupported" % stype)
        jnp = _jnp()
        dense = jnp.zeros(self._shape, self._data.dtype)
        idx = self.indices_.astype(jnp.int32)
        return NDArray(dense.at[idx].add(self._data))

    def __repr__(self):
        return "<RowSparseNDArray %s>" % (self._shape,)


class CSRNDArray(BaseSparseNDArray):
    def __init__(self, data, indices, indptr, shape):
        super().__init__(data)
        self.indices_ = indices
        self.indptr_ = indptr
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return NDArray(self._data)

    @property
    def indices(self):
        return NDArray(self.indices_)

    @property
    def indptr(self):
        return NDArray(self.indptr_)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype != "default":
            raise MXNetError("cast_storage csr->%s unsupported" % stype)
        jnp = _jnp()
        m, n = self._shape
        indptr = _np.asarray(self.indptr_)
        rows = _np.repeat(_np.arange(m), _np.diff(indptr))
        dense = jnp.zeros((m, n), self._data.dtype)
        return NDArray(dense.at[rows, self.indices_.astype(_jnp().int32)]
                       .add(self._data))

    def __repr__(self):
        return "<CSRNDArray %s>" % (self._shape,)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    jnp = _jnp()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = jnp.asarray(_np.asarray(data, dtype=_as_np_dtype(dtype)
                                       if dtype else _np.float32))
        indices = jnp.asarray(_np.asarray(indices, dtype=_np.int64))
        return RowSparseNDArray(data, indices, shape)
    arr = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    nz = _np.where(_np.any(arr.reshape(arr.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(jnp.asarray(arr[nz]), jnp.asarray(nz),
                            shape or arr.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    jnp = _jnp()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(jnp.asarray(_np.asarray(data)),
                          jnp.asarray(_np.asarray(indices, _np.int64)),
                          jnp.asarray(_np.asarray(indptr, _np.int64)), shape)
    arr = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    m, n = arr.shape
    indptr = [0]
    indices = []
    data = []
    for i in range(m):
        nz = _np.where(arr[i] != 0)[0]
        indices.extend(nz.tolist())
        data.extend(arr[i, nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(jnp.asarray(_np.asarray(data, arr.dtype)),
                      jnp.asarray(_np.asarray(indices, _np.int64)),
                      jnp.asarray(_np.asarray(indptr, _np.int64)),
                      shape or arr.shape)


def zeros(stype, shape, ctx=None, dtype="float32"):
    jnp = _jnp()
    dt = _as_np_dtype(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dt),
                                jnp.zeros((0,), jnp.int64), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt), jnp.zeros((0,), jnp.int64),
                          jnp.zeros((shape[0] + 1,), jnp.int64), shape)
    from . import zeros as dense_zeros

    return dense_zeros(shape, ctx=ctx, dtype=dtype)
