"""Sparse NDArray: row_sparse + CSR.

Reference: kRowSparseStorage / kCSRStorage (include/mxnet/ndarray.h:61-66),
src/operator/tensor/cast_storage, sparse dot (tensor/dot-inl.h).

TPU-native design decision (SURVEY §7 hard part 2): XLA is dense-only, so
sparse storage is a *format* held as dense index/value buffers on device;
ops that have efficient gather/scatter/segment-sum lowerings run on TPU
(row_sparse dot, sparse grads for embeddings), everything else falls back by
densifying — the same philosophy as the reference's storage-fallback
executor (src/imperative/attach_op_execs_pass.cc:50), with the fallback
being "densify" instead of "copy to CPU".
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError, _as_np_dtype
from .ndarray import NDArray

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "row_sparse_from_dense",
           "zeros", "dot", "add", "retain", "cast_storage", "where_nonzero",
           "sparse_embedding_grad"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _log_storage_fallback(stype, shape):
    """MXNET_STORAGE_FALLBACK_LOG_VERBOSE (reference env_var.md): announce
    sparse->dense fallbacks so silent densification is debuggable."""
    from ..base import get_env

    if get_env("MXNET_STORAGE_FALLBACK_LOG_VERBOSE", bool, False):
        import logging

        logging.getLogger("mxnet_tpu").warning(
            "storage fallback: densifying %s array of shape %s", stype,
            shape)


class BaseSparseNDArray(NDArray):
    __slots__ = ("indices_", "indptr_", "_shape")

    @property
    def shape(self):
        return self._shape

    def asnumpy(self):
        return self.tostype("default").asnumpy()


class RowSparseNDArray(BaseSparseNDArray):
    """(data[K, ...], indices[K]) — K stored rows of a larger array."""

    def __init__(self, data, indices, shape):
        super().__init__(data)
        self.indices_ = indices
        self.indptr_ = None
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self):
        return NDArray(self._data)

    @property
    def indices(self):
        return NDArray(self.indices_)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype != "default":
            raise MXNetError("cast_storage row_sparse->%s unsupported" % stype)
        _log_storage_fallback("row_sparse", self._shape)
        jnp = _jnp()
        dense = jnp.zeros(self._shape, self._data.dtype)
        idx = self.indices_.astype(jnp.int32)
        return NDArray(dense.at[idx].add(self._data))

    def retain(self, indices):
        """Keep only the given rows (reference sparse retain op) — the
        kvstore row_sparse-pull primitive."""
        jnp = _jnp()
        keep = jnp.asarray(
            indices._data if isinstance(indices, NDArray) else indices
        ).astype(jnp.int32)
        # membership of each stored row in `keep`
        mask = (self.indices_[:, None] == keep[None, :]).any(axis=1)
        sel = _np.where(_np.asarray(mask))[0]
        return RowSparseNDArray(self._data[sel], self.indices_[sel],
                                self._shape)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            jnp = _jnp()
            return RowSparseNDArray(
                jnp.concatenate([self._data, other._data]),
                jnp.concatenate([self.indices_, other.indices_]),
                self._shape)
        return self.tostype("default") + other

    def __repr__(self):
        return "<RowSparseNDArray %s>" % (self._shape,)


class CSRNDArray(BaseSparseNDArray):
    def __init__(self, data, indices, indptr, shape):
        super().__init__(data)
        self.indices_ = indices
        self.indptr_ = indptr
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return NDArray(self._data)

    @property
    def indices(self):
        return NDArray(self.indices_)

    @property
    def indptr(self):
        return NDArray(self.indptr_)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype != "default":
            raise MXNetError("cast_storage csr->%s unsupported" % stype)
        _log_storage_fallback("csr", self._shape)
        jnp = _jnp()
        m, n = self._shape
        indptr = _np.asarray(self.indptr_)
        rows = _np.repeat(_np.arange(m), _np.diff(indptr))
        dense = jnp.zeros((m, n), self._data.dtype)
        return NDArray(dense.at[rows, self.indices_.astype(_jnp().int32)]
                       .add(self._data))

    def check_format(self, full_check=True):
        """Validate csr invariants (reference NDArray::SyncCheckFormat /
        python sparse.py check_format): monotone indptr starting at 0 and
        closing at nnz, in-range column indices."""
        indptr = _np.asarray(self.indptr_)
        indices = _np.asarray(self.indices_)
        if indptr.ndim != 1 or len(indptr) != self._shape[0] + 1:
            raise MXNetError("csr indptr length %d != rows+1 (%d)"
                             % (len(indptr), self._shape[0] + 1))
        if int(indptr[0]) != 0 or _np.any(_np.diff(indptr) < 0):
            raise MXNetError("csr indptr must be non-decreasing from 0")
        if int(indptr[-1]) != len(indices):
            raise MXNetError("csr indptr[-1] (%d) != nnz (%d)"
                             % (int(indptr[-1]), len(indices)))
        if full_check and len(indices) and (
                int(indices.min()) < 0
                or int(indices.max()) >= self._shape[1]):
            raise MXNetError("csr column index out of range")

    def asscipy(self):
        import scipy.sparse as _sp

        return _sp.csr_matrix(
            (_np.asarray(self._data), _np.asarray(self.indices_),
             _np.asarray(self.indptr_)), shape=self._shape)

    def astype(self, dtype):
        jnp = _jnp()
        return CSRNDArray(self._data.astype(_as_np_dtype(dtype)),
                          jnp.asarray(self.indices_),
                          jnp.asarray(self.indptr_), self._shape)

    def __repr__(self):
        return "<CSRNDArray %s>" % (self._shape,)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    jnp = _jnp()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = jnp.asarray(_np.asarray(data, dtype=_as_np_dtype(dtype)
                                       if dtype else _np.float32))
        indices = jnp.asarray(_np.asarray(indices, dtype=_np.int32))
        return RowSparseNDArray(data, indices, shape)
    arr = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    nz = _np.where(_np.any(arr.reshape(arr.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(jnp.asarray(arr[nz]), jnp.asarray(nz),
                            shape or arr.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    jnp = _jnp()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(jnp.asarray(_np.asarray(data)),
                          jnp.asarray(_np.asarray(indices, _np.int32)),
                          jnp.asarray(_np.asarray(indptr, _np.int32)), shape)
    arr = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    m, n = arr.shape
    indptr = [0]
    indices = []
    data = []
    for i in range(m):
        nz = _np.where(arr[i] != 0)[0]
        indices.extend(nz.tolist())
        data.extend(arr[i, nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(jnp.asarray(_np.asarray(data, arr.dtype)),
                      jnp.asarray(_np.asarray(indices, _np.int32)),
                      jnp.asarray(_np.asarray(indptr, _np.int32)),
                      shape or arr.shape)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (reference tensor/dot-inl.h sparse kernels):

    - CSR × dense  → dense (BCOO dot_general, the TPU gather/segment path)
    - CSR.T × dense → dense
    - row_sparse.T × dense → row-scattered dense (embedding-grad pattern)
    - dense falls through to the dense dot op.
    """
    import jax.numpy as jnp

    if isinstance(lhs, CSRNDArray):
        from jax.experimental import sparse as jsparse

        m, n = lhs._shape
        indptr = _np.asarray(lhs.indptr_)
        rows = jnp.asarray(_np.repeat(_np.arange(m), _np.diff(indptr)))
        coo = jsparse.BCOO(
            (lhs._data, jnp.stack([rows, lhs.indices_.astype(jnp.int32)],
                                  axis=1)),
            shape=(m, n))
        if transpose_a:
            coo = coo.T
        r = rhs._data if isinstance(rhs, NDArray) else rhs
        if transpose_b:
            r = r.T
        return NDArray(coo @ r)
    if isinstance(lhs, RowSparseNDArray):
        if not transpose_a:
            return NDArray(
                lhs.tostype("default")._data @ (
                    rhs._data.T if transpose_b else rhs._data))
        # lhs.T @ rhs with lhs row-sparse: only stored rows contribute —
        # gather the matching rhs rows and contract over them
        jnp = _jnp()
        r = rhs._data if isinstance(rhs, NDArray) else rhs
        sel = r[lhs.indices_.astype(jnp.int32)]
        return NDArray(jnp.einsum("kr,kc->rc", lhs._data, sel))
    from . import dot as dense_dot

    return dense_dot(lhs, rhs, transpose_a=transpose_a,
                     transpose_b=transpose_b)


def add(lhs, rhs):
    """Sparse-aware add: same-stype sparse stays sparse, else densify."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs,
                                                        RowSparseNDArray):
        return lhs + rhs
    a = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
    b = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
    return a + b


def cast_storage(arr, stype):
    """reference tensor/cast_storage op."""
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if stype == "default":
        return arr
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise MXNetError("unknown stype %r" % stype)


def where_nonzero(arr):
    """Row indices with any nonzero (helper for building row_sparse)."""
    a = arr.asnumpy()
    return _np.where(_np.any(a.reshape(a.shape[0], -1) != 0, axis=1))[0]


def row_sparse_from_dense(arr):
    """Dense NDArray -> RowSparseNDArray with the mask/gather computed ON
    DEVICE (the Trainer hot-loop path: only the small index vector syncs
    to host, not the whole (vocab, dim) gradient)."""
    jnp = _jnp()
    data = arr._data if isinstance(arr, NDArray) else jnp.asarray(arr)
    flat = data.reshape(data.shape[0], -1)
    mask = jnp.any(flat != 0, axis=1)
    idx = jnp.nonzero(mask)[0].astype(jnp.int32)  # eager: concrete size
    return RowSparseNDArray(data[idx], idx, data.shape)


def sparse_embedding_grad(grad_out, token_ids, vocab_size):
    """Build the row_sparse gradient of an embedding lookup (reference:
    Embedding with grad_stype='row_sparse', the big-vocab memory saver).

    grad_out: (..., dim) cotangent of the lookup; token_ids: (...) int ids.
    Returns RowSparseNDArray of shape (vocab_size, dim) holding one stored
    row per *unique* token (segment-sum over duplicate tokens — the
    XLA-friendly scatter-add form).
    """
    import jax
    import jax.numpy as jnp

    g = grad_out._data if isinstance(grad_out, NDArray) else grad_out
    ids = token_ids._data if isinstance(token_ids, NDArray) else token_ids
    flat_g = g.reshape(-1, g.shape[-1])
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    uniq, inverse = _np.unique(_np.asarray(flat_ids), return_inverse=True)
    seg = jnp.asarray(inverse.astype(_np.int32))
    summed = jax.ops.segment_sum(flat_g, seg, num_segments=len(uniq))
    return RowSparseNDArray(summed, jnp.asarray(uniq.astype(_np.int32)),
                            (vocab_size, g.shape[-1]))


def zeros(stype, shape, ctx=None, dtype="float32"):
    jnp = _jnp()
    dt = _as_np_dtype(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dt),
                                jnp.zeros((0,), jnp.int32), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt), jnp.zeros((0,), jnp.int32),
                          jnp.zeros((shape[0] + 1,), jnp.int32), shape)
    from . import zeros as dense_zeros

    return dense_zeros(shape, ctx=ctx, dtype=dtype)
