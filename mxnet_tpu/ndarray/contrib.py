"""``mx.nd.contrib`` — control-flow operators (and contrib helpers).

Reference: src/operator/control_flow.cc:1096,1157,1218 — ``_foreach``,
``_while_loop``, ``_cond`` stateful ops executing subgraph attributes, the
reference's mechanism for RNN-style loops over symbolic subgraphs, exposed
in python as ``mx.nd.contrib.foreach/while_loop/cond``.

TPU-native redesign: the subgraph machinery collapses into XLA structured
control flow — ``foreach`` is ``lax.scan`` (one compiled body, static trip
count, differentiable), ``while_loop`` is a ``lax.scan`` of at most
``max_iterations`` steps with a done-mask (keeps reverse-mode autodiff and
static shapes, which raw ``lax.while_loop`` would lose), and ``cond``
evaluates eagerly when the predicate is concrete (the reference's
imperative path) or lowers to ``lax.cond`` under a trace.  The python body
functions run on NDArray-wrapped tracers, so every framework op works
unchanged inside a loop body.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ops.registry import apply_op
from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond", "isfinite", "isnan", "isinf",
           "arange_like", "index_copy", "index_array", "getnnz",
           "boolean_mask", "box_iou", "box_nms", "box_encode", "box_decode",
           "bipartite_matching", "ROIAlign", "MultiBoxPrior",
           "MultiBoxDetection", "dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample", "dgl_subgraph",
           "dgl_graph_compact", "dgl_adjacency", "edge_id"]

# DGL graph-sampling family (reference src/operator/contrib/dgl_graph.cc —
# host CSR kernels there too; see ndarray/dgl.py for the TPU rationale)
from .dgl import (dgl_adjacency, dgl_csr_neighbor_non_uniform_sample,  # noqa: E402
                  dgl_csr_neighbor_uniform_sample, dgl_graph_compact,
                  dgl_subgraph, edge_id)

# detection family (reference src/operator/contrib/bounding_box.cc,
# roi_align.cc, multibox_*.cc — surfaced as mx.nd.contrib.* there too)
from ..ops.registry import get_op as _get_op  # noqa: E402

box_iou = _get_op("box_iou")
box_nms = _get_op("box_nms")
box_encode = _get_op("box_encode")
box_decode = _get_op("box_decode")
bipartite_matching = _get_op("bipartite_matching")
ROIAlign = _get_op("roi_align")
MultiBoxPrior = _get_op("multibox_prior")
MultiBoxDetection = _get_op("multibox_detection")


def _flatten(x, out):
    """Flatten nested lists/tuples of NDArray into out; return spec."""
    if isinstance(x, NDArray):
        out.append(x)
        return "_"
    if isinstance(x, (list, tuple)):
        return [_flatten(v, out) for v in x]
    if x is None:
        return None
    raise MXNetError("control flow states must be NDArray or nested "
                     "lists, got %r" % (type(x),))


def _unflatten(spec, it, wrap):
    if spec == "_":
        return wrap(next(it))
    if spec is None:
        return None
    return [_unflatten(s, it, wrap) for s in spec]


def _is_traced(arrays):
    return any(isinstance(a._data, jax.core.Tracer) for a in arrays)


# body callables whose deferred Gluon parameters have been resolved by a
# pre-flight step.  Keyed weakly on the FUNCTION OBJECT, not its code
# object: two closures sharing one code object (a second model instance, or
# cells created in a loop) must each preflight, since each closes over its
# own possibly-deferred parameters.  A fresh closure per call re-pays one
# eager body execution — correct over fast.
import weakref as _weakref  # noqa: E402

_PREFLIGHTED = _weakref.WeakSet()


def _needs_preflight(body):
    try:
        if body in _PREFLIGHTED:
            return False
        _PREFLIGHTED.add(body)
        return True
    except TypeError:  # non-weakrefable callable (e.g. some builtins)
        return True


def _recording():
    from ..base import thread_state

    return thread_state.is_recording


def foreach(body, data, init_states, name="foreach"):
    """Run ``body`` over axis 0 of ``data``, threading states.

    ``body(data_slice, states) -> (outputs, new_states)``.
    Returns ``(outputs, final_states)`` with per-step outputs stacked on
    axis 0 (reference foreach semantics, control_flow.cc:1096).

    Execution strategy mirrors the reference: under eager autograd
    recording the loop runs imperatively step-by-step so gradients flow to
    every array the body touches (including closure captures / cell
    parameters — reference imperative mode); under a jit/hybridize trace
    or plain inference it lowers to one ``lax.scan``.
    """
    flat_data = []
    data_spec = _flatten(data, flat_data)
    flat_states = []
    state_spec = _flatten(init_states, flat_states)
    if not flat_data:
        raise MXNetError("foreach needs at least one data array")
    n_data = len(flat_data)
    length = flat_data[0].shape[0]
    for d in flat_data:
        if d.shape[0] != length:
            raise MXNetError("foreach data arrays must share axis-0 length")

    if _recording() and not _is_traced(flat_data + flat_states):
        # eager tape path: per-op recording, full closure-capture gradients
        from . import stack as _stack

        states = init_states
        flat_outs = None
        out_spec = None
        for t in range(length):
            x_t = _unflatten(data_spec, iter([d[t] for d in flat_data]),
                             lambda x: x)
            outs, states = body(x_t, states)
            step_flat = []
            out_spec = _flatten(outs, step_flat)
            if flat_outs is None:
                flat_outs = [[] for _ in step_flat]
            for i, o in enumerate(step_flat):
                flat_outs[i].append(o)
        stacked = [_stack(*os, axis=0) for os in flat_outs]
        return (_unflatten(out_spec, iter(stacked), lambda x: x), states)

    if not _is_traced(flat_data + flat_states) and _needs_preflight(body):
        # pre-flight one eager step (first call per body only): resolves
        # deferred parameter shapes (Gluon cells) OUTSIDE the scan trace —
        # otherwise their init would be staged into the trace and leak
        # tracers into Parameter._data
        from .. import autograd

        with autograd.pause():
            body(_unflatten(data_spec, iter([d[0] for d in flat_data]),
                            lambda x: x), init_states)

    def pure(*arrs):
        xs = tuple(a for a in arrs[:n_data])
        carry0 = tuple(a for a in arrs[n_data:])

        def step(carry, x):
            x_nd = _unflatten(data_spec, iter(x), NDArray)
            s_nd = _unflatten(state_spec, iter(carry), NDArray)
            outs, new_states = body(x_nd, s_nd)
            flat_out = []
            _flatten(outs, flat_out)
            flat_new = []
            new_spec = _flatten(new_states, flat_new)
            if len(flat_new) != len(carry):
                raise MXNetError("foreach body changed the number of states")
            pure.out_spec = _flatten(outs, [])
            pure.new_spec = new_spec
            return (tuple(s._data for s in flat_new),
                    tuple(o._data for o in flat_out))

        carry, ys = lax.scan(step, carry0, xs)
        return tuple(ys) + tuple(carry)

    pure.__name__ = name
    pure.out_spec = None
    res = apply_op(pure, *flat_data, *flat_states)
    if not isinstance(res, tuple):
        res = (res,)
    n_out = len(res) - len(flat_states)
    outs = _unflatten(pure.out_spec, iter(res[:n_out]), lambda x: x)
    states = _unflatten(state_spec, iter(res[n_out:]), lambda x: x)
    return outs, states


def while_loop(cond, func, loop_vars, max_iterations=None, name="while_loop"):
    """Reference while_loop (control_flow.cc:1157): run ``func`` while
    ``cond(*loop_vars)`` holds, up to ``max_iterations``.

    Returns ``(outputs, states)``: per-step outputs stacked over a
    ``max_iterations``-long axis 0 (steps after termination hold zeros —
    the reference pads undefined memory; zeros keep gradients clean), and
    the final loop states.  Implemented as a masked ``lax.scan`` so the
    trip count is static (TPU/XLA-friendly) and reverse-mode autodiff
    works through the loop.
    """
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    max_iterations = int(max_iterations)
    flat_vars = []
    var_spec = _flatten(loop_vars, flat_vars)

    if _recording() and not _is_traced(flat_vars):
        # eager tape path (reference imperative while_loop): python loop,
        # gradients flow to closure captures; outputs zero-padded to
        # max_iterations for shape parity with the compiled path.
        from . import stack as _stack, zeros_like as _zeros_like

        cur = loop_vars
        step_outs = []
        out_spec = None
        steps = 0
        while steps < max_iterations:
            pred = cond(*cur) if isinstance(cur, list) else cond(cur)
            if not bool(jnp.asarray(
                    pred._data if isinstance(pred, NDArray) else pred
                    ).reshape(())):
                break
            outs, cur = func(*cur) if isinstance(cur, list) else func(cur)
            flat_out = []
            out_spec = _flatten(outs, flat_out)
            step_outs.append(flat_out)
            steps += 1
        if out_spec is None:
            # zero live iterations: probe func once (no tape) to learn the
            # output template, then emit all-zero padded outputs — matching
            # the compiled path's semantics for an initially-false condition
            from .. import autograd

            with autograd.pause():
                outs, _ = func(*cur) if isinstance(cur, list) else func(cur)
            flat_out = []
            out_spec = _flatten(outs, flat_out)
            step_outs.append([_zeros_like(o) for o in flat_out])
            steps = 1  # one all-zero row; padding below fills the rest
        cols = list(zip(*step_outs))
        stacked = []
        for col in cols:
            pads = [_zeros_like(col[0])] * (max_iterations - steps)
            stacked.append(_stack(*(list(col) + pads), axis=0))
        return (_unflatten(out_spec, iter(stacked), lambda x: x), cur)

    if not _is_traced(flat_vars) and _needs_preflight(func):
        # pre-flight (see foreach): resolve deferred params outside the trace
        from .. import autograd

        with autograd.pause():
            cur0 = _unflatten(var_spec, iter(flat_vars), lambda x: x)
            func(*cur0) if isinstance(cur0, list) else func(cur0)

    def pure(*arrs):
        meta = {"out_spec": None, "n_out": 0}

        def step(carry, _):
            done, cur = carry
            cur_nd = _unflatten(var_spec, iter(cur), NDArray)
            pred = cond(*cur_nd) if isinstance(cur_nd, list) else cond(cur_nd)
            pred_val = jnp.logical_and(
                jnp.asarray(pred._data if isinstance(pred, NDArray)
                            else pred).reshape(()).astype(bool),
                jnp.logical_not(done))
            step_out = func(*cur_nd) if isinstance(cur_nd, list) \
                else func(cur_nd)
            if not (isinstance(step_out, tuple) and len(step_out) == 2):
                raise MXNetError("while_loop func must return "
                                 "(outputs, new_loop_vars)")
            outs, new_vars = step_out
            flat_out = []
            meta["out_spec"] = _flatten(outs, flat_out)
            meta["n_out"] = len(flat_out)
            flat_new = []
            _flatten(new_vars, flat_new)
            if len(flat_new) != len(cur):
                raise MXNetError("while_loop func changed loop var count")
            # keep old vars where the loop has terminated
            kept = tuple(jnp.where(pred_val, n._data, c)
                         for n, c in zip(flat_new, cur))
            emitted = tuple(jnp.where(pred_val, o._data,
                                      jnp.zeros_like(o._data))
                            for o in flat_out)
            new_done = jnp.logical_or(done, jnp.logical_not(pred_val))
            return (new_done, kept), emitted + (pred_val,)

        init = (jnp.asarray(False), tuple(arrs))
        (done, final), ys = lax.scan(step, init,
                                     jnp.arange(max_iterations))
        pure.out_spec = meta["out_spec"]
        *outs, steps_mask = ys
        n_steps = steps_mask.sum().astype(jnp.int32)
        return tuple(outs) + tuple(final) + (n_steps,)

    pure.__name__ = name
    pure.out_spec = None
    res = apply_op(pure, *flat_vars)
    if not isinstance(res, tuple):
        res = (res,)
    res, _n_steps = res[:-1], res[-1]
    n_out = len(res) - len(flat_vars)
    outs = _unflatten(pure.out_spec, iter(res[:n_out]), lambda x: x)
    states = _unflatten(var_spec, iter(res[n_out:]), lambda x: x)
    return outs, states


def cond(pred, then_func, else_func, name="cond"):
    """Reference cond (control_flow.cc:1218).  Imperative path: evaluate the
    predicate and run one branch eagerly (what the reference's imperative
    mode does) — both branches stay differentiable.  Under a jit/hybridize
    trace the predicate is abstract, so lower to ``lax.cond``."""
    p = pred._data if isinstance(pred, NDArray) else pred
    if isinstance(p, jax.core.Tracer):
        spec_holder = {}  # per-call: reentrant under nested/threaded traces

        def _then(_):
            out = then_func()
            flat = []
            spec_holder["spec"] = _flatten(out, flat)
            return tuple(o._data for o in flat)

        def _else(_):
            out = else_func()
            flat = []
            _flatten(out, flat)
            return tuple(o._data for o in flat)

        res = lax.cond(jnp.asarray(p).reshape(()).astype(bool),
                       _then, _else, None)
        return _unflatten(spec_holder["spec"], (NDArray(r) for r in res),
                          lambda x: x)
    taken = bool(jnp.asarray(p).reshape(()))
    return then_func() if taken else else_func()


# ---- small contrib helpers (reference contrib op surface) -----------------

def isfinite(data):
    return apply_op(lambda x: jnp.isfinite(x).astype(jnp.float32), data)


def isnan(data):
    return apply_op(lambda x: jnp.isnan(x).astype(jnp.float32), data)


def isinf(data):
    return apply_op(lambda x: jnp.isinf(x).astype(jnp.float32), data)


def arange_like(data, start=0.0, step=1.0, axis=None):
    """reference _contrib_arange_like"""

    def fn(x):
        if axis is None:
            n = x.size
            return (start + step * jnp.arange(n)).reshape(x.shape)
        return start + step * jnp.arange(x.shape[axis])

    fn.__name__ = "arange_like"
    return apply_op(fn, data)


def index_copy(old_tensor, index_vector, new_tensor):
    """reference _contrib_index_copy"""

    def fn(old, idx, new):
        return old.at[idx.astype(jnp.int32)].set(new)

    fn.__name__ = "index_copy"
    return apply_op(fn, old_tensor, index_vector, new_tensor)


def index_array(data, axes=None):
    """reference _contrib_index_array: element coordinates."""

    def fn(x):
        idx = jnp.stack(jnp.meshgrid(*[jnp.arange(s) for s in x.shape],
                                     indexing="ij"), axis=-1)
        if axes is not None:
            idx = idx[..., list(axes)]
        return idx.astype(jnp.int32)

    fn.__name__ = "index_array"
    return apply_op(fn, data)


def getnnz(data, axis=None):
    def fn(x):
        return (x != 0).sum(axis=axis).astype(jnp.int32)

    fn.__name__ = "getnnz"
    return apply_op(fn, data)


def boolean_mask(data, index, axis=0):
    """reference _contrib_boolean_mask.  Note: output size is
    data-dependent; eager-only (not jit-traceable), like the reference's
    dynamic-shape ops.  The mask is a static selector; gradients flow to
    ``data`` (scatter of zeros into dropped rows)."""
    i = index._data if isinstance(index, NDArray) else index
    keep = jnp.asarray(i).astype(bool)

    def fn(x):
        return jnp.compress(keep, x, axis=axis)

    fn.__name__ = "boolean_mask"
    return apply_op(fn, data)


# ops that the reference registers under _contrib_ but this registry holds
# under plain names (the _contrib_-prefixed aliases also resolve)
_CONTRIB_PLAIN = frozenset([
    "quantize", "quantize_v2", "dequantize", "requantize",
    "quantized_conv", "quantized_fully_connected",
    "roi_align", "box_iou", "box_nms", "box_encode", "box_decode",
    "bipartite_matching", "multibox_prior", "multibox_detection",
    "count_sketch", "fft", "ifft", "index_copy", "index_add",
    "sync_batch_norm", "adaptive_avg_pooling", "bilinear_resize",
    "multi_sum_sq", "multi_lars", "multi_all_finite", "all_finite",
    "multi_lamb_update", "multi_lans_update", "adamw_update",
    "mp_adamw_update", "deformable_convolution", "boolean_mask",
])


def __getattr__(name):
    """Resolve ``mx.nd.contrib.<op>`` from the registry — ONLY names the
    reference's contrib surface carries: ``_contrib_``-prefixed
    registrations (hawkesll, interleaved matmuls, div_sqrt_dim,
    SyncBatchNorm...) and the curated plain-name set above.  A stray
    non-contrib name (``mx.nd.contrib.add``) raises, so typos in ported
    1.x code fail loudly instead of aliasing the whole op namespace."""
    from ..ops.registry import _OP_REGISTRY

    if "_contrib_" + name in _OP_REGISTRY:
        return _OP_REGISTRY["_contrib_" + name]
    if name in _CONTRIB_PLAIN and name in _OP_REGISTRY:
        return _OP_REGISTRY[name]
    raise AttributeError("mx.nd.contrib has no attribute %r" % (name,))
