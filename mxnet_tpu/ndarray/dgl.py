"""DGL graph-sampling operator family (``mx.nd.contrib.dgl_*``).

Reference: src/operator/contrib/dgl_graph.cc (1,649 LoC) — CSR neighbor
sampling (uniform :762 / non-uniform :867), node-induced subgraphs
(_contrib_dgl_subgraph :1008), graph compaction (:1583), adjacency (:1408)
and _contrib_edge_id (:1332).

TPU-native rendering: these kernels are irregular pointer-chasing graph
walks over host CSR structures — the reference itself runs them CPU-only
(FComputeEx with kCSRStorage, no .cu file).  Graph sampling is data-pipeline
work that PREPARES mini-batches for the device, so the right TPU design is
host numpy kernels producing CSRNDArray handles, exactly like the
reference's CPU path; the sampled sub-batches then flow to XLA as dense
gathers.  Sampling draws come from the framework RNG stream
(mxnet_tpu.random) for seed-reproducibility parity.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .sparse import CSRNDArray, csr_matrix
from .ndarray import NDArray

__all__ = ["dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample",
           "dgl_subgraph", "dgl_graph_compact", "dgl_adjacency", "edge_id"]

_ID_DT = _np.int64


def _csr_parts(csr):
    if not isinstance(csr, CSRNDArray):
        raise MXNetError("expected a CSRNDArray graph, got %r" % (type(csr),))
    data = _np.asarray(csr.data.asnumpy(), dtype=_ID_DT)
    indices = _np.asarray(csr.indices.asnumpy(), dtype=_ID_DT)
    indptr = _np.asarray(csr.indptr.asnumpy(), dtype=_ID_DT)
    return data, indices, indptr, csr.shape


def _rng():
    from .. import random as _random

    # derive a numpy generator from the framework key stream so mx.random
    # .seed() reproduces sampling (reference: ParallelRandom resource)
    key = _np.asarray(_random.take_key(), dtype=_np.uint32)
    return _np.random.default_rng(int(key[0]) << 32 | int(key[-1]))


def _sample_neighbors(col, eid, num_neighbor, rng, prob_row=None):
    """Sample ``num_neighbor`` of this row's (col, eid) pairs.

    Uniform keeps the whole row when it is short (dgl_graph.cc:448
    GetUniformSample); non-uniform draws without replacement weighted by
    per-VERTEX probability (GetNonUniformSample:489, ArrayHeap)."""
    n = len(col)
    if n <= num_neighbor:
        return col, eid
    if prob_row is None:
        pick = rng.choice(n, size=num_neighbor, replace=False)
        pick.sort()
    else:
        w = prob_row.astype(_np.float64)
        s = w.sum()
        if not s > 0:
            raise MXNetError("non_uniform_sample: zero total probability "
                             "over a sampled row")
        # without-replacement draws can cover at most the positive-weight
        # candidates; clamp like the reference's ArrayHeap, which can only
        # ever return entries that still carry weight
        k = min(num_neighbor, int((w > 0).sum()))
        pick = rng.choice(n, size=k, replace=False, p=w / s)
        pick.sort()
    return col[pick], eid[pick]


def _neighbor_sample_one(data, indices, indptr, seeds, num_hops,
                         num_neighbor, max_num_vertices, rng, n_cols,
                         prob=None):
    """BFS sampling core (dgl_graph.cc:540 SampleSubgraph)."""
    max_num_vertices = int(max_num_vertices)
    seeds = _np.asarray(seeds, dtype=_ID_DT)
    if max_num_vertices < len(seeds):
        raise MXNetError("max_num_vertices (%d) < number of seeds (%d)"
                         % (max_num_vertices, len(seeds)))
    sub_ver = {}           # vertex -> layer
    queue = []             # (vertex, layer) in discovery order
    for s in seeds:
        s = int(s)
        if s not in sub_ver:
            sub_ver[s] = 0
            queue.append((s, 0))
    neigh = {}             # dst vertex -> (cols, eids) sampled for its row
    idx = 0
    while idx < len(queue) and len(sub_ver) < max_num_vertices:
        dst, level = queue[idx]
        idx += 1
        if level >= num_hops:
            continue
        lo, hi = int(indptr[dst]), int(indptr[dst + 1])
        cols, eids = indices[lo:hi], data[lo:hi]
        prow = prob[cols] if prob is not None else None
        cols, eids = _sample_neighbors(cols, eids, num_neighbor, rng, prow)
        neigh[dst] = (cols, eids)
        for v in cols:
            v = int(v)
            if len(sub_ver) >= max_num_vertices:
                break
            if v not in sub_ver:
                sub_ver[v] = level + 1
                queue.append((v, level + 1))

    verts = _np.array(sorted(sub_ver), dtype=_ID_DT)
    num_vertices = len(verts)
    sampled_ids = _np.zeros(max_num_vertices + 1, dtype=_ID_DT)
    sampled_ids[:num_vertices] = verts
    sampled_ids[max_num_vertices] = num_vertices
    layer = _np.zeros(max_num_vertices, dtype=_ID_DT)
    layer[:num_vertices] = [sub_ver[int(v)] for v in verts]

    # sub-csr: row i = i-th smallest sampled vertex; indices stay GLOBAL
    # ids, data carries the sampled edge ids (dgl_graph.cc:700-760)
    sub_indptr = _np.zeros(max_num_vertices + 1, dtype=_ID_DT)
    sub_cols, sub_eids = [], []
    for i, v in enumerate(verts):
        pair = neigh.get(int(v))
        if pair is not None:
            sub_cols.append(pair[0])
            sub_eids.append(pair[1])
            sub_indptr[i + 1] = sub_indptr[i] + len(pair[0])
        else:
            sub_indptr[i + 1] = sub_indptr[i]
    sub_indptr[num_vertices + 1:] = sub_indptr[num_vertices]
    sub_cols = (_np.concatenate(sub_cols) if sub_cols
                else _np.zeros(0, dtype=_ID_DT))
    sub_eids = (_np.concatenate(sub_eids) if sub_eids
                else _np.zeros(0, dtype=_ID_DT))
    # column space stays the PARENT graph's width: indices are global ids
    # (CSRNeighborUniformSampleShape, dgl_graph.cc:281)
    sub_csr = csr_matrix((sub_eids, sub_cols, sub_indptr),
                         shape=(max_num_vertices, n_cols))
    out = [NDArray._from_np(sampled_ids), sub_csr]
    if prob is not None:
        sub_prob = _np.zeros(max_num_vertices, dtype=_np.float32)
        sub_prob[:num_vertices] = prob[verts]
        out.append(NDArray._from_np(sub_prob))
    out.append(NDArray._from_np(layer))
    return out


def dgl_csr_neighbor_uniform_sample(csr, *seed_arrays, num_args=None,
                                    num_hops=1, num_neighbor=2,
                                    max_num_vertices=100):
    """Uniform neighborhood sampling (dgl_graph.cc:762).

    For each seed array returns (sampled_vertex_ids, sub_csr, layer);
    ``sampled_vertex_ids`` has length max_num_vertices+1 with the true
    vertex count in its last slot."""
    data, indices, indptr, shape = _csr_parts(csr)
    rng = _rng()
    outs = []
    for seeds in seed_arrays:
        seeds = seeds.asnumpy() if isinstance(seeds, NDArray) else seeds
        outs.extend(_neighbor_sample_one(
            data, indices, indptr, seeds, int(num_hops), int(num_neighbor),
            max_num_vertices, rng, shape[1]))
    return outs[0] if len(outs) == 1 else tuple(outs)


def dgl_csr_neighbor_non_uniform_sample(csr, probability, *seed_arrays,
                                        num_args=None, num_hops=1,
                                        num_neighbor=2,
                                        max_num_vertices=100):
    """Probability-weighted neighborhood sampling (dgl_graph.cc:867).

    Returns per seed array (sampled_vertex_ids, sub_csr, prob, layer)."""
    data, indices, indptr, shape = _csr_parts(csr)
    prob = _np.asarray(probability.asnumpy()
                       if isinstance(probability, NDArray) else probability,
                       dtype=_np.float32)
    rng = _rng()
    outs = []
    for seeds in seed_arrays:
        seeds = seeds.asnumpy() if isinstance(seeds, NDArray) else seeds
        outs.extend(_neighbor_sample_one(
            data, indices, indptr, seeds, int(num_hops), int(num_neighbor),
            max_num_vertices, rng, shape[1], prob=prob))
    return tuple(outs)


def dgl_subgraph(graph, *vertex_arrays, num_args=None, return_mapping=False):
    """Node-induced subgraph(s) (dgl_graph.cc:1008 GetSubgraph).

    Vertex lists must be sorted.  Each subgraph csr uses LOCAL vertex ids
    and new edge ids 0..nnz-1; with return_mapping=True a second csr per
    input carries the ORIGINAL edge ids as data."""
    eids, indices, indptr, shape = _csr_parts(graph)
    subs, mappings = [], []
    for varr in vertex_arrays:
        vids = _np.asarray(varr.asnumpy() if isinstance(varr, NDArray)
                           else varr, dtype=_ID_DT)
        if not _np.all(_np.diff(vids) >= 0):
            raise MXNetError("dgl_subgraph: the vertex list must be sorted")
        old2new = {int(v): i for i, v in enumerate(vids)}
        n = len(vids)
        sub_indptr = _np.zeros(n + 1, dtype=_ID_DT)
        cols, oeids = [], []
        for i, v in enumerate(vids):
            lo, hi = int(indptr[v]), int(indptr[v + 1])
            for c, e in zip(indices[lo:hi], eids[lo:hi]):
                new = old2new.get(int(c))
                if new is not None:
                    cols.append(new)
                    oeids.append(int(e))
            sub_indptr[i + 1] = len(cols)
        cols = _np.asarray(cols, dtype=_ID_DT)
        oeids = _np.asarray(oeids, dtype=_ID_DT)
        new_eids = _np.arange(len(cols), dtype=_ID_DT)
        subs.append(csr_matrix((new_eids, cols, sub_indptr), shape=(n, n)))
        if return_mapping:
            mappings.append(csr_matrix((oeids, cols.copy(),
                                        sub_indptr.copy()), shape=(n, n)))
    outs = subs + mappings
    return outs[0] if len(outs) == 1 else tuple(outs)


def dgl_graph_compact(*args, graph_sizes=None, return_mapping=False,
                      num_args=None):
    """Compact sampled subgraphs to local vertex ids (dgl_graph.cc:1583).

    Input pairs: N csr graphs (global col ids, rows already sorted-sampled
    order) + N vertex-id arrays mapping local row -> global id;
    ``graph_sizes`` gives each graph's true vertex count."""
    n = len(args) // 2
    csrs, id_arrs = args[:n], args[n:]
    sizes = graph_sizes if isinstance(graph_sizes, (list, tuple)) \
        else [graph_sizes] * n
    subs, mappings = [], []
    for csr, id_arr, size in zip(csrs, id_arrs, sizes):
        eids, indices, indptr, _shape = _csr_parts(csr)
        ids = _np.asarray(id_arr.asnumpy() if isinstance(id_arr, NDArray)
                          else id_arr, dtype=_ID_DT)
        size = int(size)
        old2new = {int(v): i for i, v in enumerate(ids[:size])}
        new_indptr = indptr[:size + 1].copy()
        keep_cols, keep_eids = [], []
        for i in range(size):
            lo, hi = int(indptr[i]), int(indptr[i + 1])
            for c, e in zip(indices[lo:hi], eids[lo:hi]):
                new = old2new.get(int(c))
                if new is None:
                    raise MXNetError(
                        "dgl_graph_compact: column %d not in id map" % c)
                keep_cols.append(new)
                keep_eids.append(int(e))
        cols = _np.asarray(keep_cols, dtype=_ID_DT)
        oeids = _np.asarray(keep_eids, dtype=_ID_DT)
        subs.append(csr_matrix((_np.arange(len(cols), dtype=_ID_DT), cols,
                                new_indptr), shape=(size, size)))
        if return_mapping:
            mappings.append(csr_matrix((oeids, cols.copy(),
                                        new_indptr.copy()),
                                       shape=(size, size)))
    outs = subs + mappings
    return outs[0] if len(outs) == 1 else tuple(outs)


def dgl_adjacency(graph):
    """CSR graph (int64 edge ids) -> float32 adjacency with unit weights,
    same sparsity structure (dgl_graph.cc:1408)."""
    _eids, indices, indptr, shape = _csr_parts(graph)
    return csr_matrix((_np.ones(len(indices), dtype=_np.float32),
                       indices.copy(), indptr.copy()), shape=shape)


def edge_id(graph, u, v):
    """Edge-id lookup: out[i] = data[u[i], v[i]] or -1 when the edge is
    absent (dgl_graph.cc:1332; output keeps the CSR data dtype, matching
    EdgeIDForwardCsrImpl's MSHADOW_TYPE_SWITCH on the data type — float32
    would corrupt int64 edge ids above 2**24)."""
    data = _np.asarray(graph.data.asnumpy())
    indices = _np.asarray(graph.indices.asnumpy(), dtype=_ID_DT)
    indptr = _np.asarray(graph.indptr.asnumpy(), dtype=_ID_DT)
    uu = _np.asarray(u.asnumpy() if isinstance(u, NDArray) else u,
                     dtype=_ID_DT)
    vv = _np.asarray(v.asnumpy() if isinstance(v, NDArray) else v,
                     dtype=_ID_DT)
    out = _np.full(uu.shape, -1, dtype=data.dtype)
    for i, (a, b) in enumerate(zip(uu, vv)):
        lo, hi = int(indptr[a]), int(indptr[a + 1])
        hit = _np.where(indices[lo:hi] == b)[0]
        if len(hit):
            out[i] = data[lo + int(hit[-1])]
    return NDArray._from_np(out)
