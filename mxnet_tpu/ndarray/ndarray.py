"""NDArray: the imperative tensor handle.

Reference: ``class NDArray`` include/mxnet/ndarray.h:82 — shape/dtype/context
plus a shared Chunk holding a Storage::Handle and an engine var; lazy alloc;
WaitToRead/WaitToWrite; autograd_entry_ linking into the recorded graph.

TPU-native redesign: the storage chunk *is* a ``jax.Array`` (PJRT buffer in
HBM).  The engine var is the buffer's future: JAX dispatch is already async,
so every op returns immediately and ``wait_to_read`` maps to
``block_until_ready`` — the same contract as Engine::WaitForVar
(src/engine/threaded_engine.cc:379) with zero scheduler code.  Exceptions
raised by deferred computations surface at sync points exactly like the
reference's ExceptionRef path (threaded_engine.h:64).
"""
from __future__ import annotations

import numpy as _np

from .. import telemetry as _tel
from ..base import MXNetError, _as_np_dtype, integer_types, numeric_types
from ..context import Context, cpu, current_context

__all__ = ["NDArray", "waitall", "from_jax", "concatenate"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _ctx_of(data):
    try:
        dev = list(data.devices())[0]
    except Exception:  # tracer or uncommitted
        return current_context()
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    return Context("tpu", dev.id)


class NDArray:
    """An n-dimensional array on a device, with async semantics and autograd
    hooks.  Wraps exactly one ``jax.Array`` (or tracer, during hybridize)."""

    __slots__ = ("_data", "_grad", "_grad_req", "_entry", "_marked",
                 "__weakref__")
    # numpy interop priority
    __array_priority__ = 100.0

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._data
        if ctx is not None:
            import jax

            data = jax.device_put(data, ctx.jax_device)
        self._data = data
        self._grad = None
        self._grad_req = "null"
        self._entry = None
        self._marked = False

    @classmethod
    def _from_np(cls, arr, ctx=None):
        """Wrap a host numpy array (device transfer deferred to jnp)."""
        import jax.numpy as jnp

        if _tel.ENABLED and isinstance(arr, _np.ndarray):
            _tel.TRANSFER_H2D.inc(arr.nbytes)
        return cls(jnp.asarray(arr), ctx=ctx)

    # ---- basic properties -------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        sz = 1
        for s in self.shape:
            sz *= s
        return sz

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def context(self):
        return _ctx_of(self._data)

    ctx = context

    @property
    def device(self):
        return self.context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        from . import transpose

        return transpose(self)

    @property
    def grad(self):
        return self._grad

    # ---- sync / transfer --------------------------------------------------
    def wait_to_read(self):
        """Block until pending computation lands (Engine::WaitForVar)."""
        self._data.block_until_ready()

    wait_to_write = wait_to_read

    def asnumpy(self):
        import jax

        arr = _np.asarray(jax.device_get(self._data))
        if _tel.ENABLED:
            _tel.TRANSFER_D2H.inc(arr.nbytes)
        return arr

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def tolist(self):
        return self.asnumpy().tolist()

    def as_in_context(self, ctx):
        if ctx == self.context:
            return self
        return NDArray(self._data, ctx=ctx)

    as_in_ctx = as_in_context
    as_nd_ndarray = lambda self: self
    as_np_ndarray = lambda self: self

    def to_device(self, ctx):
        return self.as_in_context(ctx)

    def copyto(self, other):
        if isinstance(other, Context):
            return NDArray(self._data, ctx=other)
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise MXNetError("copyto shape mismatch %s vs %s"
                                 % (self.shape, other.shape))
            other._data = _jnp().asarray(self._data, dtype=other.dtype)
            if other.context != self.context:
                import jax

                other._data = jax.device_put(other._data,
                                             other.context.jax_device)
            return other
        raise TypeError("copyto: unsupported target %r" % (other,))

    def copy(self):
        return NDArray(self._data + 0 if self.dtype != _np.bool_
                       else self._data)

    def astype(self, dtype, copy=True):
        np_dtype = _as_np_dtype(dtype)
        if not copy and self.dtype == np_dtype:
            return self
        from ..ops.registry import apply_op

        return apply_op(lambda x: _jnp().asarray(x, dtype=np_dtype), self)

    def detach(self):
        out = NDArray(self._data)
        return out

    # ---- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer (reference ndarray.py attach_grad)."""
        self._grad = NDArray(_jnp().zeros(self.shape, self.dtype))
        self._grad_req = grad_req
        self._marked = grad_req != "null"
        self._entry = None

    def zero_grad(self):
        if self._grad is not None:
            self._grad._data = _jnp().zeros(self.shape, self.dtype)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ---- indexing ---------------------------------------------------------
    def __getitem__(self, key):
        from ..ops.registry import apply_op

        key = _clean_key(key)

        def _slice(x):
            return x[key]

        _slice.__name__ = "getitem"
        return apply_op(_slice, self)

    def __setitem__(self, key, value):
        from ..base import thread_state

        if thread_state.is_recording and (self._marked or self._entry):
            raise MXNetError("in-place write to an array on the autograd tape "
                             "inside record() is not supported; use pause()")
        key = _clean_key(key)
        if isinstance(value, NDArray):
            value = value._data
        self._data = self._data.at[key].set(value)

    def slice(self, begin, end, step=None):
        key = tuple(slice(b, e, s) for b, e, s in
                    zip(begin, end, step or [None] * len(begin)))
        return self[key]

    def take(self, indices, axis=0):
        from . import take

        return take(self, indices, axis=axis)

    # ---- shape manipulation ----------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        from ..ops.registry import apply_op

        size = self.size
        # reference reshape specials: -1 infer, 0 copy-dim (ndarray.py)
        out_shape = []
        for i, s in enumerate(shape):
            if s == 0:
                out_shape.append(self.shape[i])
            else:
                out_shape.append(int(s))
        def _reshape(x):
            return x.reshape(tuple(out_shape))
        _reshape.__name__ = "reshape"
        return apply_op(_reshape, self)

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def expand_dims(self, axis):
        from . import expand_dims

        return expand_dims(self, axis=axis)

    def squeeze(self, axis=None):
        from . import squeeze

        return squeeze(self, axis=axis)

    def flatten(self):
        return self.reshape((self.shape[0], -1)) if self.ndim > 1 else self

    def transpose(self, *axes):
        from . import transpose

        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return transpose(self, axes=axes if axes else None)

    def swapaxes(self, dim1, dim2):
        from . import swapaxes

        return swapaxes(self, dim1, dim2)

    def broadcast_to(self, shape):
        from . import broadcast_to

        return broadcast_to(self, shape=shape)

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def tile(self, reps):
        from . import tile

        return tile(self, reps=reps)

    def repeat(self, repeats, axis=None):
        from . import repeat

        return repeat(self, repeats=repeats, axis=axis)

    def pad(self, pad_width, mode="constant", constant_value=0):
        from . import pad

        return pad(self, pad_width, mode=mode, constant_value=constant_value)

    def split(self, num_outputs, axis=0):
        from . import split

        return split(self, num_outputs=num_outputs, axis=axis)

    # ---- reductions / math methods ---------------------------------------
    def _reduce(self, name, axis=None, keepdims=False):
        from .. import ndarray as nd

        return getattr(nd, name)(self, axis=axis, keepdims=keepdims)

    def sum(self, axis=None, keepdims=False, **kw):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False, **kw):
        return self._reduce("mean", axis, keepdims)

    def max(self, axis=None, keepdims=False, **kw):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False, **kw):
        return self._reduce("min", axis, keepdims)

    def prod(self, axis=None, keepdims=False, **kw):
        return self._reduce("prod", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        from . import norm

        return norm(self, ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        from . import argmax

        return argmax(self, axis=axis)

    def argmin(self, axis=None, keepdims=False):
        from . import argmin

        return argmin(self, axis=axis)

    def clip(self, a_min=None, a_max=None):
        from . import clip

        return clip(self, a_min, a_max)

    def abs(self):
        from . import abs as _abs

        return _abs(self)

    def sqrt(self):
        from . import sqrt

        return sqrt(self)

    def exp(self):
        from . import exp

        return exp(self)

    def log(self):
        from . import log

        return log(self)

    def sigmoid(self):
        from . import sigmoid

        return sigmoid(self)

    def relu(self):
        from . import relu

        return relu(self)

    def tanh(self):
        from . import tanh

        return tanh(self)

    def softmax(self, axis=-1):
        from . import softmax

        return softmax(self, axis=axis)

    def log_softmax(self, axis=-1):
        from . import log_softmax

        return log_softmax(self, axis=axis)

    def round(self):
        from . import round as _round

        return _round(self)

    def floor(self):
        from . import floor

        return floor(self)

    def ceil(self):
        from . import ceil

        return ceil(self)

    def sign(self):
        from . import sign

        return sign(self)

    def square(self):
        from . import square

        return square(self)

    def expm1(self):
        from . import expm1

        return expm1(self)

    def log1p(self):
        from . import log1p

        return log1p(self)

    def dot(self, other):
        from . import dot

        return dot(self, other)

    def topk(self, k=1, axis=-1, ret_typ="indices", is_ascend=False):
        from . import topk

        return topk(self, k=k, axis=axis, ret_typ=ret_typ,
                    is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        from . import sort

        return sort(self, axis=axis, is_ascend=is_ascend)

    def argsort(self, axis=-1, is_ascend=True):
        from . import argsort

        return argsort(self, axis=axis, is_ascend=is_ascend)

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        from . import one_hot

        return one_hot(self, depth, on_value=on_value, off_value=off_value)

    def tostype(self, stype):
        if stype != "default":
            raise MXNetError("sparse storage handled by mxnet_tpu.ndarray."
                             "sparse wrappers")
        return self

    # ---- operators --------------------------------------------------------
    def _binop(self, other, name, reverse=False):
        from .. import ndarray as nd

        fn = getattr(nd, name)
        if reverse:
            return fn(other, self)
        return fn(self, other)

    def __add__(self, o):
        return self._binop(o, "add")

    def __radd__(self, o):
        return self._binop(o, "add", True)

    def __iadd__(self, o):
        return self._binop(o, "add")

    def __sub__(self, o):
        return self._binop(o, "subtract")

    def __rsub__(self, o):
        return self._binop(o, "subtract", True)

    def __isub__(self, o):
        return self._binop(o, "subtract")

    def __mul__(self, o):
        return self._binop(o, "multiply")

    def __rmul__(self, o):
        return self._binop(o, "multiply", True)

    def __imul__(self, o):
        return self._binop(o, "multiply")

    def __truediv__(self, o):
        return self._binop(o, "divide")

    def __rtruediv__(self, o):
        return self._binop(o, "divide", True)

    def __itruediv__(self, o):
        return self._binop(o, "divide")

    def __floordiv__(self, o):
        return self._binop(o, "floor_divide")

    def __rfloordiv__(self, o):
        return self._binop(o, "floor_divide", True)

    def __mod__(self, o):
        return self._binop(o, "mod")

    def __rmod__(self, o):
        return self._binop(o, "mod", True)

    def __pow__(self, o):
        return self._binop(o, "power")

    def __rpow__(self, o):
        return self._binop(o, "power", True)

    def __matmul__(self, o):
        from . import dot

        return dot(self, o)

    def __neg__(self):
        return self._binop(-1, "multiply")

    def __abs__(self):
        return self.abs()

    def __eq__(self, o):
        return self._binop(o, "equal")

    def __ne__(self, o):
        return self._binop(o, "not_equal")

    def __lt__(self, o):
        return self._binop(o, "lesser")

    def __le__(self, o):
        return self._binop(o, "lesser_equal")

    def __gt__(self, o):
        return self._binop(o, "greater")

    def __ge__(self, o):
        return self._binop(o, "greater_equal")

    def __hash__(self):
        return id(self)

    def __repr__(self):
        try:
            arr = self.asnumpy()
            return "%s\n<NDArray %s @%s>" % (
                str(arr), "x".join(map(str, self.shape)), self.context)
        except Exception:
            return "<NDArray %s (pending/traced)>" % (
                "x".join(map(str, self.shape)),)

    # numpy interop
    def __array__(self, dtype=None):
        arr = self.asnumpy()
        return arr.astype(dtype) if dtype is not None else arr

    # NEP-18/NEP-13 dispatch (reference numpy/multiarray.py:367 +
    # numpy_dispatch_protocol.py): numpy API calls on NDArray operands
    # route through mx.np — so np.mean(mx_arr) stays on-device and on the
    # autograd tape instead of silently densifying to host numpy
    def __array_function__(self, func, types, args, kwargs):
        from .. import numpy as _mxnp

        target = _mxnp
        mod = getattr(func, "__module__", "") or ""
        for part in mod.split(".")[1:]:  # e.g. numpy.linalg -> .linalg
            target = getattr(target, part, None)
            if target is None:
                return NotImplemented
        f = getattr(target, func.__name__, None)
        if f is None:
            return NotImplemented
        return f(*args, **kwargs)

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if method != "__call__" or kwargs.get("out") is not None:
            return NotImplemented
        from .. import numpy as _mxnp

        f = getattr(_mxnp, ufunc.__name__, None)
        if f is None:
            return NotImplemented
        return f(*inputs, **kwargs)

    def __dlpack__(self, stream=None):
        return self._data.__dlpack__(stream=stream)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()


def _clean_key(key):
    if isinstance(key, NDArray):
        return key._data
    if isinstance(key, tuple):
        return tuple(k._data if isinstance(k, NDArray) else k for k in key)
    return key


def waitall():
    """Block on every pending computation (reference ndarray.py:231 waitall →
    Engine::WaitForAll).

    Guarantee: PJRT executes programs in enqueue order per device, so a
    host fetch of a freshly enqueued trivial program on EACH local device
    completes only after everything enqueued before it on that device —
    the same fence Engine::WaitForAll provided.  The fetch goes through a
    device->host transfer because ``block_until_ready`` alone is not
    reliable on tunneled backends (axon)."""
    import jax
    import numpy as _np_

    jax.effects_barrier()
    for d in jax.local_devices():
        # the +0 matters: a bare transfer is not ordered after enqueued
        # programs, but an enqueued trivial PROGRAM is — fetching its
        # result to host is the fence
        _np_.asarray(jax.device_put(0, d) + 0)


def from_jax(x):
    return NDArray(x)


def concatenate(arrays, axis=0):
    from . import concat

    return concat(*arrays, dim=axis)
