"""``mx.nd.linalg`` — LAPACK-family namespace.

Reference: python/mxnet/ndarray/linalg.py (generated wrappers over the
``_linalg_*`` ops, src/operator/tensor/la_op.cc) plus the numpy-linalg
front-end (src/operator/numpy/linalg/).  Short names here map onto the
registered ``linalg_*`` operators.
"""
from __future__ import annotations

from ..ops.registry import get_op as _get_op

_SHORT = [
    "gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "syrk", "gelqf",
    "syevd", "sumlogdiag", "extractdiag", "makediag", "extracttrian",
    "maketrian", "inverse", "det", "slogdet", "cholesky", "qr", "svd",
    "svdvals", "eig", "eigh", "eigvals", "eigvalsh", "solve", "lstsq",
    "pinv", "matrix_rank", "matrix_power", "norm", "cond", "multi_dot",
    "tensorinv", "tensorsolve",
]

for _name in _SHORT:
    globals()[_name] = _get_op("linalg_" + _name)

__all__ = list(_SHORT)
