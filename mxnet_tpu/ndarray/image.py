"""``mx.nd.image`` — the image op namespace.

Reference: python/mxnet/ndarray/image.py (generated wrappers over the
``_image_*`` registrations, src/operator/image/).  Each public name strips
the ``image_`` prefix of the registry op: ``nd.image.to_tensor(x)`` invokes
the ``image_to_tensor`` op through the standard invoke/record path.
"""
from __future__ import annotations

from ..ops import image_ops as _image_ops  # noqa: F401  (registration)
from ..ops.registry import get_op as _get_op

_NAMES = [
    "to_tensor", "normalize", "resize", "crop", "random_crop",
    "random_resized_crop", "flip_left_right", "flip_top_bottom",
    "random_flip_left_right", "random_flip_top_bottom",
    "random_brightness", "random_contrast", "random_saturation",
    "random_hue", "random_color_jitter", "adjust_lighting",
    "random_lighting",
]

__all__ = list(_NAMES)

for _n in _NAMES:
    globals()[_n] = _get_op("image_" + _n)
del _n
