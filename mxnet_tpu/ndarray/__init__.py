"""``mx.nd`` — the imperative NDArray API.

Reference: python/mxnet/ndarray/ — op wrappers are code-generated at import
from the C++ op registry (register.py:265 _make_ndarray_function).  Here the
registry is Python-native (mxnet_tpu/ops/registry.py) so the "generated
wrapper" is simply the registered Operator object exposed under its name;
every call flows through the same invoke() path the reference routes through
MXImperativeInvoke.
"""
# pylint: disable=redefined-builtin,wildcard-import
from __future__ import annotations

import pickle

import numpy as _np

from ..base import MXNetError, _as_np_dtype
from ..context import current_context
from ..ops import core as _core
from ..ops import nn as _nn
from ..ops.registry import get_op, list_ops
from .ndarray import NDArray, concatenate, from_jax, waitall

# ---- re-export every registered op under its MXNet name -------------------
_namespace = globals()
for _name in list_ops():
    _namespace.setdefault(_name, get_op(_name))

# broadcast_add/sub/mul/div and elemwise_* come from the registry alias
# table (ops/legacy.py) via the re-export loop above — ONE source of truth
broadcast_power = _core.power
broadcast_maximum = _core.maximum
broadcast_minimum = _core.minimum
broadcast_equal = _core.equal
broadcast_not_equal = _core.not_equal
broadcast_greater = _core.greater
broadcast_greater_equal = _core.greater_equal
broadcast_lesser = _core.lesser
broadcast_lesser_equal = _core.lesser_equal
# broadcast_like / Embedding / Activation resolve from the registry
# (ops/tensor_tail.py, ops/legacy.py) — 1.x signatures incl. the
# input_dim/output_dim declarative attrs
FullyConnected = _nn.fully_connected
Convolution = _nn.convolution
Deconvolution = _nn.deconvolution
Pooling = _nn.pooling
BatchNorm = _nn.batch_norm
LayerNorm = _nn.layer_norm
GroupNorm = _nn.group_norm
InstanceNorm = _nn.instance_norm
LRN = _nn.lrn
SequenceMask = _core.sequence_mask
SequenceLast = _core.sequence_last
SequenceReverse = _core.sequence_reverse
Cast = _core.cast
Concat = _core.concat
SoftmaxActivation = _nn.softmax
L2Normalization = _nn.l2_normalization
UpSampling = _nn.upsampling
BlockGrad = stop_gradient = _core.stop_gradient


from . import image  # noqa: E402,F401  (mx.nd.image op namespace)

# Activation / LeakyReLU / Dropout resolve from the registry (ops/legacy.py)
# — one act_type dispatcher for nd AND sym, stochastic rrelu in training,
# implicit-RNG train-gated dropout.
def dropout(data, p=0.5, mode="training", axes=None):
    """Keyless imperative dropout — delegates to the legacy Dropout op
    (ops/legacy.py; reference nn/dropout.cc)."""
    from ..ops.registry import get_op

    return get_op("Dropout")(data, p=p, mode=mode,
                             axes=tuple(axes) if axes else None)


# ---- creation -------------------------------------------------------------

def _jnp():
    import jax.numpy as jnp

    return jnp


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        source_array = source_array.asnumpy()
    arr = _np.asarray(source_array, dtype=_as_np_dtype(dtype) if dtype
                      else None)
    if arr.dtype == _np.float64 and dtype is None:
        arr = arr.astype(_np.float32)
    return NDArray._from_np(arr, ctx=ctx or current_context())


def zeros(shape, ctx=None, dtype="float32", **kw):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_jnp().zeros(shape, _as_np_dtype(dtype)),
                   ctx=ctx or current_context())


def ones(shape, ctx=None, dtype="float32", **kw):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_jnp().ones(shape, _as_np_dtype(dtype)),
                   ctx=ctx or current_context())


def full(shape, val, ctx=None, dtype="float32", **kw):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_jnp().full(shape, val, _as_np_dtype(dtype)),
                   ctx=ctx or current_context())


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    data = _jnp().arange(start, stop, step, _as_np_dtype(dtype))
    if repeat > 1:
        data = _jnp().repeat(data, repeat)
    return NDArray(data, ctx=ctx or current_context())


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    return NDArray(_jnp().linspace(start, stop, num, endpoint=endpoint,
                                   dtype=_as_np_dtype(dtype)),
                   ctx=ctx or current_context())


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return NDArray(_jnp().eye(N, M if M else None, k,
                              dtype=_as_np_dtype(dtype)),
                   ctx=ctx or current_context())


def zeros_like(other, **kw):
    return _core.zeros_like(other)


def ones_like(other, **kw):
    return _core.ones_like(other)


def moveaxis(a, source, destination):
    from ..ops.registry import apply_op

    return apply_op(lambda x: _jnp().moveaxis(x, source, destination), a)


# ---- serialization (reference MXNDArraySave/Load, ndarray/utils.py) -------


def save(fname, data, format="npz"):
    """Save NDArray / list / dict of NDArray (reference ndarray/utils.py:149).

    Default format: numpy .npz (TPU-native: the reference's custom binary
    chunk format served its C++ loader; npz keeps numpy interop),
    committed via the mx.checkpoint atomic-file path so a crash mid-save
    never truncates an existing file at ``fname``.
    ``format="reference"`` writes the incumbent's binary NDArray-list
    format instead, loadable by the reference's mx.nd.load."""
    if format == "reference":
        from .. import legacy_io

        return legacy_io.save(fname, data)
    if isinstance(data, NDArray):
        payload = {"__mx_single__": data.asnumpy()}
    elif isinstance(data, dict):
        payload = {k: v.asnumpy() for k, v in data.items()}
    elif isinstance(data, (list, tuple)):
        payload = {"__mx_list_%d__" % i: v.asnumpy()
                   for i, v in enumerate(data)}
    else:
        raise MXNetError("save: unsupported data type %r" % type(data))
    from ..checkpoint.layout import atomic_file

    # streamed into the temp file — no full in-memory .npz copy
    atomic_file(fname, lambda f: _np.savez(f, **payload))


def load(fname):
    # reference-format interop: the incumbent's .params files open with
    # kMXAPINDArrayListMagic — route them through the binary codec
    # (mxnet_tpu/legacy_io.py; reference src/ndarray/ndarray.cc:1930)
    with open(fname, "rb") as f:
        head = f.read(8)
    from .. import legacy_io

    if legacy_io.is_reference_format(head):
        return legacy_io.load(fname)
    with _np.load(fname, allow_pickle=False) as npz:
        keys = list(npz.keys())
        if keys == ["__mx_single__"]:
            return array(npz["__mx_single__"])
        if all(k.startswith("__mx_list_") for k in keys):
            out = [None] * len(keys)
            for k in keys:
                out[int(k[len("__mx_list_"):-2])] = array(npz[k])
            return out
        return {k: array(npz[k]) for k in keys}


# submodules / namespaces
from .. import random  # noqa: E402  (mx.nd.random mirror)
from . import sparse  # noqa: E402
from . import contrib  # noqa: E402
from . import linalg  # noqa: E402  (mx.nd.linalg, reference la_op family)
from ..operator import Custom  # noqa: E402  (mx.nd.Custom, reference name)

__all__ = ["NDArray", "waitall", "array", "zeros", "ones", "full", "empty",
           "arange", "linspace", "eye", "save", "load", "concatenate",
           "random", "sparse", "contrib", "Custom"] + list_ops()
