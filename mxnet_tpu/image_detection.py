"""Detection image augmenters (reference python/mxnet/image/detection.py —
DetBorrowAug, DetRandomSelectAug, DetHorizontalFlipAug, DetRandomCropAug,
DetRandomPadAug, CreateDetAugmenter, ImageDetIter).

Labels are (N, 5+) rows [cls, x1, y1, x2, y2, ...] with coordinates
NORMALIZED to [0, 1] of the image (the reference convention), so every
geometric augmenter transforms image and boxes together.
"""
from __future__ import annotations

import numpy as _np

from . import image as _img
from . import ndarray as nd
from .base import MXNetError

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Base detection augmenter: __call__(src, label) -> (src, label)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a plain image augmenter that does not move pixels relative to
    boxes (color jitter etc.) — reference detection.py DetBorrowAug."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick ONE of the given augmenters (or skip) per sample."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = float(skip_prob)

    def __call__(self, src, label):
        if _np.random.rand() < self.skip_prob or not self.aug_list:
            return src, label
        aug = self.aug_list[_np.random.randint(len(self.aug_list))]
        return aug(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image + x-coordinates (reference DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = float(p)

    def __call__(self, src, label):
        if _np.random.rand() >= self.p:
            return src, label
        arr = src.asnumpy()[:, ::-1]
        lab = _np.array(label.asnumpy() if isinstance(label, nd.NDArray)
                        else label, copy=True)
        x1 = lab[:, 1].copy()
        lab[:, 1] = 1.0 - lab[:, 3]
        lab[:, 3] = 1.0 - x1
        return nd.array(arr.copy(), dtype=src.dtype), nd.array(lab)


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained by box overlap (reference
    DetRandomCropAug): sample a crop with area in [min_object_covered
    -respecting] range; boxes are clipped to the crop, and boxes whose
    center falls outside are dropped (marked cls=-1, shape-stable)."""

    def __init__(self, min_object_covered=0.3, min_crop_scale=0.3,
                 max_crop_scale=1.0, max_attempts=20,
                 aspect_ratio_range=(0.75, 1.33), area_range=None,
                 min_eject_coverage=0.3):
        self.min_object_covered = float(min_object_covered)
        if area_range is not None:
            self.area_range = (float(area_range[0]), float(area_range[1]))
        else:
            # back-compat: scale range on the side length
            self.area_range = (float(min_crop_scale) ** 2,
                               float(max_crop_scale) ** 2)
        self.aspect_ratio_range = (float(aspect_ratio_range[0]),
                                   float(aspect_ratio_range[1]))
        self.min_eject_coverage = float(min_eject_coverage)
        self.max_attempts = int(max_attempts)

    def __call__(self, src, label):
        arr = src.asnumpy()
        H, W = arr.shape[:2]
        lab = _np.array(label.asnumpy() if isinstance(label, nd.NDArray)
                        else label, copy=True)
        valid = lab[:, 0] >= 0
        for _ in range(self.max_attempts):
            area = _np.random.uniform(*self.area_range)
            ratio = _np.random.uniform(*self.aspect_ratio_range)
            cw = min(_np.sqrt(area * ratio), 1.0)
            ch = min(_np.sqrt(area / ratio), 1.0)
            cx = _np.random.uniform(0, 1 - cw)
            cy = _np.random.uniform(0, 1 - ch)
            # fraction of each box covered by the crop
            ix1 = _np.maximum(lab[:, 1], cx)
            iy1 = _np.maximum(lab[:, 2], cy)
            ix2 = _np.minimum(lab[:, 3], cx + cw)
            iy2 = _np.minimum(lab[:, 4], cy + ch)
            inter = _np.maximum(ix2 - ix1, 0) * _np.maximum(iy2 - iy1, 0)
            area = _np.maximum((lab[:, 3] - lab[:, 1]) *
                               (lab[:, 4] - lab[:, 2]), 1e-12)
            cover = inter / area
            if not _np.any(valid) or \
                    cover[valid].max() >= self.min_object_covered:
                px1, py1 = int(cx * W), int(cy * H)
                px2, py2 = int((cx + cw) * W), int((cy + ch) * H)
                out = arr[py1:py2, px1:px2]
                # re-normalize boxes into crop coords
                nl = lab.copy()
                nl[:, 1] = (lab[:, 1] - cx) / cw
                nl[:, 2] = (lab[:, 2] - cy) / ch
                nl[:, 3] = (lab[:, 3] - cx) / cw
                nl[:, 4] = (lab[:, 4] - cy) / ch
                centers_x = (nl[:, 1] + nl[:, 3]) / 2
                centers_y = (nl[:, 2] + nl[:, 4]) / 2
                keep = ((centers_x > 0) & (centers_x < 1) &
                        (centers_y > 0) & (centers_y < 1) & valid &
                        (cover >= self.min_eject_coverage))
                nl[:, 1:5] = _np.clip(nl[:, 1:5], 0.0, 1.0)
                nl[~keep, 0] = -1  # invalid marker, shape-stable
                return nd.array(out.copy(), dtype=src.dtype), nd.array(nl)
        return src, nd.array(lab)


class DetRandomPadAug(DetAugmenter):
    """Random expand/pad (reference DetRandomPadAug): place the image in
    a larger mean-filled canvas; boxes shrink accordingly."""

    def __init__(self, max_pad_scale=2.0, pad_val=(127, 127, 127)):
        self.max_pad_scale = float(max_pad_scale)
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = src.asnumpy()
        H, W, C = arr.shape
        s = _np.random.uniform(1.0, self.max_pad_scale)
        if s <= 1.0:
            return src, label
        nh, nw = int(H * s), int(W * s)
        oy = _np.random.randint(0, nh - H + 1)
        ox = _np.random.randint(0, nw - W + 1)
        canvas = _np.empty((nh, nw, C), arr.dtype)
        canvas[...] = _np.asarray(self.pad_val, arr.dtype)[:C]
        canvas[oy:oy + H, ox:ox + W] = arr
        lab = _np.array(label.asnumpy() if isinstance(label, nd.NDArray)
                        else label, copy=True)
        lab[:, 1] = (lab[:, 1] * W + ox) / nw
        lab[:, 3] = (lab[:, 3] * W + ox) / nw
        lab[:, 2] = (lab[:, 2] * H + oy) / nh
        lab[:, 4] = (lab[:, 4] * H + oy) / nh
        return nd.array(canvas, dtype=src.dtype), nd.array(lab)


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0.0):
    """Reference detection.py:418 CreateMultiRandCropAugmenter: each
    scalar parameter may instead be a list; one DetRandomCropAug per
    parameter tuple, wrapped so a random one fires per sample."""
    def listify(v):
        return list(v) if isinstance(v, (list, tuple)) else [v]

    mocs = listify(min_object_covered)

    # aspect/area entries are pair-tuples; a list of pairs means
    # per-crop settings
    def pairs(v):
        if isinstance(v, (list, tuple)) and v and \
                isinstance(v[0], (list, tuple)):
            return [tuple(p) for p in v]
        return [tuple(v)]

    ratios = pairs(aspect_ratio_range)
    areas = pairs(area_range)
    ejects = listify(min_eject_coverage)
    n = max(len(mocs), len(ratios), len(areas), len(ejects))

    def at(lst, i):
        if len(lst) == 1:
            return lst[0]
        if len(lst) != n:
            raise MXNetError(
                "CreateMultiRandCropAugmenter: parameter lists must share "
                "one length (got %d vs %d)" % (len(lst), n))
        return lst[i]

    crops = [DetRandomCropAug(
        min_object_covered=at(mocs, i),
        aspect_ratio_range=at(ratios, i), area_range=at(areas, i),
        min_eject_coverage=at(ejects, i), max_attempts=max_attempts)
        for i in range(n)]
    return DetRandomSelectAug(crops, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None,
                       std=None, brightness=0, contrast=0, saturation=0,
                       pca_noise=0, hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, max_pad_scale=2.0,
                       pad_val=(127, 127, 127)):
    """Standard detection augmenter chain — full reference option set
    (detection.py:483 CreateDetAugmenter): geometric crop/pad/mirror
    plus the color augmenters borrowed through DetBorrowAug."""
    from .image import ForceResizeAug, ResizeAug, _color_aug_tail

    augs = []
    if resize > 0:
        augs.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = CreateMultiRandCropAugmenter(
            min_object_covered=min_object_covered,
            aspect_ratio_range=aspect_ratio_range,
            area_range=area_range,
            min_eject_coverage=min_eject_coverage,
            max_attempts=max_attempts, skip_prob=1.0 - rand_crop)
        augs.append(crop)
    if rand_pad > 0:
        augs.append(DetRandomSelectAug(
            [DetRandomPadAug(max_pad_scale=max_pad_scale,
                             pad_val=pad_val)],
            skip_prob=1.0 - rand_pad))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    # force the output shape (the crop/pad change it)
    augs.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    augs.extend(DetBorrowAug(a) for a in _color_aug_tail(
        brightness, contrast, saturation, hue, pca_noise, rand_gray,
        mean, std))
    return augs


class ImageDetIter:
    """Detection data iterator (reference image/detection.py
    ImageDetIter): wraps an (images, labels) source, applies the det
    augmenter chain per sample, resizes to data_shape, and yields
    (data (B,C,H,W) f32, label (B,N,5)) batches."""

    def __init__(self, batch_size, data_shape, images=None, labels=None,
                 aug_list=None, shuffle=False, **kwargs):
        if images is None or labels is None:
            raise MXNetError("ImageDetIter needs images= (list of HWC "
                             "uint8 arrays) and labels= (list of (N,5))")
        if len(images) != len(labels):
            raise MXNetError("images/labels length mismatch")
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self._images = list(images)
        self._labels = [_np.asarray(l, _np.float32) for l in labels]
        self._max_boxes = max(l.shape[0] for l in self._labels)
        self._augs = aug_list if aug_list is not None else []
        self._shuffle = shuffle
        self._order = _np.arange(len(images))
        self.reset()

    def reset(self):
        self._cursor = 0
        if self._shuffle:
            _np.random.shuffle(self._order)

    def __iter__(self):
        self.reset()
        return self

    def next(self):
        n_left = len(self._images) - self._cursor
        if n_left <= 0:
            raise StopIteration
        # pad the final partial batch by wrapping (reference ImageDetIter
        # pads and reports DataBatch.pad so no sample is ever dropped)
        pad = max(0, self.batch_size - n_left)
        C, H, W = self.data_shape
        data = _np.zeros((self.batch_size, C, H, W), _np.float32)
        label = _np.full((self.batch_size, self._max_boxes,
                          self._labels[0].shape[1]), -1.0, _np.float32)
        for i in range(self.batch_size):
            j = self._order[(self._cursor + i) % len(self._images)]
            img = nd.array(self._images[j], dtype="uint8")
            lab = nd.array(self._labels[j])
            for aug in self._augs:
                img, lab = aug(img, lab)
            img = _img.imresize(img, W, H)
            arr = img.asnumpy().astype(_np.float32)
            data[i] = arr.transpose(2, 0, 1)
            ln = lab.asnumpy()
            label[i, :ln.shape[0]] = ln
        self._cursor += self.batch_size
        from .io import DataBatch

        return DataBatch([nd.array(data)], [nd.array(label)], pad=pad)

    __next__ = next
