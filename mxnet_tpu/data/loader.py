"""StreamLoader — the production input pipeline front-end.

Composes the pieces of :mod:`mx.data`: a :class:`~.reader.ShardSet`
sliced by this host's ``(process_index, dp_rank)`` coordinates, a
:class:`~.reader.ReaderPool` of decode workers, and a
:class:`~.ring.PrefetchRing` staging the next K batches onto their
mesh shardings while the current step runs.  Iterating yields device
batches (NDArray tuples) for the REMAINDER of the current epoch; the
epoch counter then advances and the next ``iter()`` starts the next
epoch's (differently shuffled) stream.

**Deterministic mid-epoch resume**: ``state_dict()`` is the reader
cursor — seed, epoch, batches *consumed* (not read: batches sitting
staged in the ring are re-read after a restore, never skipped), the
assignment mode and derived shard/offset coordinates for operators.
It rides ``Trainer.state_dict()`` (``Trainer.attach_loader``) so the
``PodCheckpointManager`` commits weights and stream position as ONE
pod-consistent unit, and a whole-world restart resumes the exact
remaining sample order bit-identically (the epoch order is a pure
function of ``(seed, epoch)`` — see reader.py).
"""
from __future__ import annotations

import threading
import weakref

import numpy as _np

from .. import telemetry as _tel
from ..base import MXNetError, get_env
from .reader import ReaderPool, ShardSet, world_coords
from .ring import PrefetchRing, default_depth, make_placer

__all__ = ["StreamLoader", "live_loaders", "default_workers"]

CURSOR_VERSION = 1

# live loaders for tools/diagnose.py --data
_LIVE = weakref.WeakSet()
_LIVE_LOCK = threading.Lock()


def live_loaders():
    with _LIVE_LOCK:
        return list(_LIVE)


def default_workers():
    """``MXNET_DATA_WORKERS`` reader threads per host."""
    return max(1, get_env("MXNET_DATA_WORKERS", int, 2))


def _tuned_prefetch(local_batch, sample_nbytes):
    """Resolve (depth, workers) through the ``data_prefetch`` autotune
    site — structural (order-preserving by construction), so a tuned
    config changes overlap, never the sample stream."""
    from .. import autotune

    default = {"depth": default_depth(), "workers": default_workers()}
    key = (int(local_batch), int(sample_nbytes))
    cfg = autotune.lookup("data_prefetch", key, default)
    try:
        return max(1, int(cfg["depth"])), max(1, int(cfg["workers"]))
    except Exception:
        return default["depth"], default["workers"]


class StreamLoader:
    """Sharded streaming loader with a device-resident prefetch ring.

    Parameters
    ----------
    source : ShardSet, shard-glob pattern, path, or list of paths.
    batch_size : GLOBAL batch size (all hosts together); must divide
        by the host count.  Each host reads and stages only its
        ``batch_size / num_hosts`` slice.
    decode_fn : record bytes -> tuple of numpy arrays (default:
        ``reader.default_decode`` — IRHeader + npy/JPEG payload).
    shuffle / seed : per-epoch order (pure function of (seed, epoch)).
    mesh : ``mx.shard.GlobalMesh`` (default ``shard.current()``);
        staged batches land on its ``batch_sharding`` — the placement
        the captured step program consumes without a second copy.
    num_workers / prefetch : reader threads and ring depth (default:
        env knobs, through the ``data_prefetch`` autotune site).
    num_hosts / host : world coordinates override (drills).
    """

    def __init__(self, source, batch_size, decode_fn=None, shuffle=True,
                 seed=0, mesh=None, num_workers=None, prefetch=None,
                 num_hosts=None, host=None, timeout=120.0):
        if isinstance(source, ShardSet):
            self._set = source
        elif isinstance(source, (list, tuple)):
            self._set = ShardSet(source)
        else:
            self._set = ShardSet.from_pattern(source)
        self.num_hosts, self.host = world_coords(num_hosts, host)
        if mesh is None:
            from .. import shard as _shard

            mesh = _shard.current()
        self._mesh = mesh
        if int(batch_size) % self.num_hosts:
            raise MXNetError(
                "global batch_size %d does not divide across %d hosts"
                % (batch_size, self.num_hosts))
        self.batch_size = int(batch_size)
        self.local_batch = self.batch_size // self.num_hosts
        if mesh is not None and mesh.processes > 1:
            mode = str(get_env("MXNET_SHARD_DATA", str, "dp")
                       or "dp").lower()
            if mode != "dp":
                raise MXNetError(
                    "StreamLoader assembles the global batch from "
                    "per-host slices; MXNET_SHARD_DATA=%s needs every "
                    "host to hold the whole batch — use the classic "
                    "DataLoader for that drill mode" % mode)
        self._decode = decode_fn
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self._timeout = float(timeout)
        self._entries, self.assignment_mode = \
            self._set.assignment(self.num_hosts, self.host)
        self.batches_per_epoch = self._set.batches_per_epoch(
            self.num_hosts, self.local_batch)
        if self.batches_per_epoch < 1:
            raise MXNetError(
                "shard slice of host %d/%d holds %d records — not one "
                "local batch of %d" % (self.host, self.num_hosts,
                                       len(self._entries),
                                       self.local_batch))
        tuned = None
        if num_workers is None or prefetch is None:
            est = max(1, self._probe_sample_bytes()) if self._entries \
                else 1
            tuned = _tuned_prefetch(self.local_batch, est)
        self.num_workers = tuned[1] if num_workers is None \
            else int(num_workers)
        self.prefetch = tuned[0] if prefetch is None else int(prefetch)
        if self.num_workers < 1 or self.prefetch < 1:
            raise MXNetError(
                "StreamLoader needs num_workers >= 1 and prefetch >= 1 "
                "(got %d/%d); the ring cannot be disabled, only "
                "shallowed" % (self.num_workers, self.prefetch))
        # cursor: next batch to CONSUME of the current epoch
        self.epoch = 0
        self.batch = 0
        self.samples_seen = 0
        self._pool = None
        self._ring = None
        self._lock = threading.Lock()
        self._stalls_total = 0      # accumulated across epoch rings
        self._staged_total = 0
        self._worker_records = {}
        self._order_cache = None    # (epoch, order) — one shuffle/epoch
        self.last_ids = None
        self._preempt_hook = "data_loader-%d" % id(self)
        self._install_preempt_hook()
        with _LIVE_LOCK:
            _LIVE.add(self)

    def _probe_sample_bytes(self):
        shard = self._set.shards[self._entries[0][0]]
        # file size / record count ~ mean framed record size; a cheap
        # workload feature for the data_prefetch autotune key
        import os as _os

        try:
            return _os.path.getsize(shard.path) // max(1, len(shard))
        except OSError:
            return 1

    # -- resilience ------------------------------------------------------------
    def _install_preempt_hook(self):
        """SIGTERM mid-epoch must not leak reader/stager threads: the
        loader quiesces under ``resilience.preempt.graceful_shutdown``
        exactly like ``serve.Server`` drains.  Held weakly — the hook
        must not keep a dropped loader alive."""
        from ..resilience import preempt as _preempt

        ref = weakref.ref(self)

        def _drain():
            ldr = ref()
            if ldr is not None:
                ldr.close()

        _preempt.add_shutdown_hook(self._preempt_hook, _drain)

    # -- lifecycle ------------------------------------------------------------
    def _teardown(self):
        ring, pool = self._ring, self._pool
        self._ring = None
        self._pool = None
        if ring is not None:
            self._stalls_total += ring.stalls
            self._staged_total += ring.staged
            ring.stop()
        if pool is not None:
            for w, n in pool.read_counts().items():
                self._worker_records[w] = \
                    self._worker_records.get(w, 0) + n
            pool.stop()

    def close(self):
        """Stop workers and the stager; the cursor survives (a closed
        loader can be state_dict'ed and resumed)."""
        with self._lock:
            self._teardown()
        from ..resilience import preempt as _preempt

        _preempt.remove_shutdown_hook(self._preempt_hook)

    def __del__(self):
        try:
            self.close()   # threads AND the preempt hook — no leaks
        except Exception:
            pass

    # -- iteration ------------------------------------------------------------
    def _epoch_order(self):
        """The current epoch's order, computed ONCE per epoch and
        reused by _spin_up and the cursor's derived shard/offset —
        state_dict() on a large slice must not pay an O(n log n)
        shuffle per checkpoint.  Caller holds the lock."""
        cache = self._order_cache
        if cache is None or cache[0] != self.epoch:
            cache = (self.epoch,
                     ShardSet.epoch_order(self._entries, self.seed,
                                          self.epoch, self.shuffle))
            self._order_cache = cache
        return cache[1]

    def _spin_up(self):
        order = self._epoch_order()
        pool = ReaderPool(
            self._set, self._entries, order, self.local_batch,
            self.num_workers, decode_fn=self._decode,
            start_batch=self.batch, max_batches=self.batches_per_epoch,
            readahead=self.prefetch + self.num_workers,
            epoch=self.epoch)
        ring = PrefetchRing(
            lambda: pool.next_batch(self._timeout),
            make_placer(self._mesh), depth=self.prefetch,
            name="epoch-%d" % self.epoch)
        self._pool, self._ring = pool, ring
        if _tel.ENABLED:
            _tel.DATA_RING_DEPTH.set(self.prefetch)

    def __iter__(self):
        """Yield the REMAINING device batches of the current epoch,
        then advance the epoch.  Each yielded item is the tuple of
        staged arrays (``last_ids`` holds the batch's sample ids)."""
        with self._lock:
            self._teardown()
            if self.batch >= self.batches_per_epoch:
                self.epoch += 1
                self.batch = 0
            self._spin_up()
            ring = self._ring
        try:
            while True:
                item = ring.next(self._timeout)
                if item is None:
                    break
                idx, staged, ids = item
                with self._lock:
                    # consumed == handed to the training loop; the
                    # cursor moves HERE, so batches still staged in
                    # the ring are re-read after a restore, never
                    # skipped
                    self.batch = idx + 1
                    self.samples_seen += self.local_batch
                    self.last_ids = ids
                yield staged
        finally:
            # also runs on GeneratorExit (consumer broke out early):
            # readers/stager must not keep streaming — the cursor
            # stays wherever consumption stopped, so a later iter()
            # or a checkpoint resume continues exactly there
            with self._lock:
                self._teardown()
                if self.batch >= self.batches_per_epoch:
                    self.epoch += 1
                    self.batch = 0

    def __len__(self):
        return self.batches_per_epoch

    # -- checkpointable cursor --------------------------------------------------
    def state_dict(self):
        """The reader cursor as a flat int tree (checkpoint leaves).
        ``shard_index``/``record_offset`` are the DERIVED coordinates
        of the next sample — operator-facing (diagnose), not needed to
        resume (epoch order is re-derived from seed+epoch)."""
        with self._lock:
            si, pos = self._next_entry()
            return {
                "version": CURSOR_VERSION,
                "seed": self.seed,
                "epoch": self.epoch,
                "batch": self.batch,
                "samples_seen": self.samples_seen,
                "shuffle": int(self.shuffle),
                "num_hosts": self.num_hosts,
                "host": self.host,
                "shard_index": si,
                "record_offset": pos,
            }

    def _next_entry(self):
        if not self._entries:
            return -1, -1
        order = self._epoch_order()
        i = self.batch * self.local_batch
        if i >= len(order):
            return -1, -1
        si, pos = self._entries[order[i]]
        return int(si), int(pos)

    def load_state_dict(self, tree):
        """Restore a cursor (values may be jax/numpy scalars from a
        checkpoint restore).  The world geometry must match — a
        resumed stream on different host coordinates would be a
        DIFFERENT stream, silently."""
        def _i(k, default=None):
            v = tree.get(k, default)
            if v is None:
                raise MXNetError("data cursor is missing %r" % k)
            return int(_np.asarray(v))

        if _i("version") != CURSOR_VERSION:
            raise MXNetError("data cursor version %d unsupported"
                             % _i("version"))
        if _i("num_hosts") != self.num_hosts or _i("host") != self.host:
            raise MXNetError(
                "data cursor was taken at host %d/%d, this loader is "
                "host %d/%d — shard slices differ, the stream cannot "
                "resume" % (_i("host"), _i("num_hosts"),
                            self.host, self.num_hosts))
        if bool(_i("shuffle")) != self.shuffle or _i("seed") != self.seed:
            raise MXNetError(
                "data cursor seed/shuffle (%d/%s) do not match this "
                "loader (%d/%s)" % (_i("seed"), bool(_i("shuffle")),
                                    self.seed, self.shuffle))
        with self._lock:
            self._teardown()
            self.epoch = _i("epoch")
            self.batch = _i("batch")
            self.samples_seen = _i("samples_seen", 0)
        if _tel.ENABLED:
            _tel.DATA_RESUMES.inc()

    def _merged_worker_records(self, pool):
        out = dict(self._worker_records)
        if pool is not None:
            for w, n in pool.read_counts().items():
                out[w] = out.get(w, 0) + n
        return out

    # -- introspection ------------------------------------------------------------
    def stats(self):
        """Snapshot for ``tools/diagnose.py --data``."""
        with self._lock:
            ring = self._ring
            pool = self._pool
            si, pos = self._next_entry()
            return {
                "shards": len(self._set),
                "records_total": self._set.total_records,
                "records_local": len(self._entries),
                "assignment": self.assignment_mode,
                "host": "%d/%d" % (self.host, self.num_hosts),
                "global_batch": self.batch_size,
                "local_batch": self.local_batch,
                "batches_per_epoch": self.batches_per_epoch,
                "workers": self.num_workers,
                "ring_depth": self.prefetch,
                "ring_occupancy": ring.occupancy() if ring else 0,
                "ring_staged": self._staged_total
                + (ring.staged if ring else 0),
                "ring_stalls": self._stalls_total
                + (ring.stalls if ring else 0),
                "worker_records": self._merged_worker_records(pool),
                "cursor": {"epoch": self.epoch, "batch": self.batch,
                           "shard_index": si, "record_offset": pos,
                           "samples_seen": self.samples_seen},
                "mesh": None if self._mesh is None
                else self._mesh.describe(),
            }
