"""Device-resident prefetch ring — the H3 fix, generalized.

PERF_PLAN hypothesis H3: the captured step program (mx.step) never
waits on the device, but the loop feeding it did — a blocking
``device_put`` of every host batch sat between steps, so the bench's
"pre-staged tensors" mode was faster than any real loader.  The
:class:`PrefetchRing` closes that gap for streaming input: a stager
thread pulls host batches from the reader pool and ``device_put``\\ s
the next K of them onto their TARGET shardings (the same
``GlobalMesh.batch_sharding`` placement ``step/capture.py`` pins, so
the captured program's dispatch consumes them without a second copy)
while the current step runs.  PJRT transfers are asynchronous — the
ring holds arrays whose copies are still in flight, and the XLA
program dispatch orders after them on-device, never on the host.

Occupancy/stall gauges prove the ring is doing its job: steady state
is ``data_ring_occupancy ~ depth`` and a flat
``data_ring_stalls_total``; a stall means reads or decode (not H2D)
are the bottleneck — raise ``MXNET_DATA_WORKERS``, not the depth.
"""
from __future__ import annotations

import collections
import threading
import time as _time

import numpy as _np

from .. import telemetry as _tel
from .. import trace as _trace
from ..base import MXNetError, get_env

__all__ = ["PrefetchRing", "default_depth", "make_placer"]


def default_depth():
    """``MXNET_DATA_PREFETCH`` ring depth (batches staged ahead)."""
    return max(1, get_env("MXNET_DATA_PREFETCH", int, 2))


def make_placer(mesh=None):
    """Build the stage function host-batch-tuple -> device arrays.

    With a ``GlobalMesh``, every array lands on its
    ``batch_sharding`` — dp-sharded along axis 0 when the shape
    divides — via ``device_put`` (single process) or
    ``make_array_from_process_local_data`` (each host contributes its
    local slice of the global batch).  Without a mesh, arrays go to
    the default device.  Either way the result is wrapped in NDArray
    so downstream code (captured or stitched) is oblivious."""
    from ..ndarray.ndarray import NDArray

    def place(host_batch):
        import jax

        out = []
        nbytes = 0
        for a in host_batch:
            a = _np.asarray(a)
            nbytes += a.nbytes
            if mesh is None:
                import jax.numpy as jnp

                out.append(NDArray(jnp.asarray(a)))
                continue
            if mesh.processes > 1:
                sharding = mesh.batch_sharding(
                    (a.shape[0] * mesh.processes,) + a.shape[1:])
                arr = jax.make_array_from_process_local_data(sharding, a)
            else:
                sharding = mesh.batch_sharding(a.shape)
                arr = jax.device_put(a, sharding)
            out.append(NDArray(arr))
        if _tel.ENABLED and nbytes:
            _tel.TRANSFER_H2D.inc(nbytes)
        return tuple(out)

    return place


class PrefetchRing:
    """Bounded ring of device-staged batches ahead of the consumer.

    ``source`` is a zero-arg callable returning ``(index, host_batch,
    ids)`` or None at end of stream (``ReaderPool.next_batch``);
    ``placer`` stages one host batch onto the device/mesh.  ``next()``
    pops in order; the stall time (consumer arrived, ring empty) feeds
    ``dataloader_batch_wait_seconds`` — the histogram the acceptance
    criterion bounds."""

    def __init__(self, source, placer, depth=None, name="ring"):
        self._source = source
        self._placer = placer
        self._depth = int(depth) if depth else default_depth()
        if self._depth < 1:
            raise MXNetError("prefetch ring depth must be >= 1")
        self._name = name
        self._buf = collections.deque()
        self._cond = threading.Condition()
        self._stop = False
        self._exhausted = False
        self._error = None
        self._ctx = None          # consumer trace ctx for stage spans
        self.staged = 0
        self.stalls = 0
        self._thread = threading.Thread(
            target=self._stage_loop, name="mx-data-stager", daemon=True)
        self._thread.start()

    # -- stager thread -----------------------------------------------------------
    def _stage_loop(self):
        try:
            while True:
                with self._cond:
                    while not self._stop and len(self._buf) >= self._depth:
                        self._cond.wait(0.2)
                    if self._stop:
                        return
                    ctx = self._ctx
                item = self._source()
                if item is None:
                    break
                idx, host_batch, ids = item
                t0 = _time.perf_counter()
                # adopt the consumer's trace ctx so data_stage spans
                # land under the train_step trace that will eat this
                # batch (ISSUE 15: loader spans on the step timeline)
                with _trace.use(ctx):
                    with _trace.span("data_stage", hist=False, cat="data",
                                     args={"batch": int(idx)}):
                        staged = self._placer(host_batch)
                if _tel.ENABLED:
                    _tel.DATA_STAGE_SECONDS.observe(
                        _time.perf_counter() - t0)
                with self._cond:
                    if self._stop:
                        return
                    self._buf.append((idx, staged, ids))
                    self.staged += 1
                    if _tel.ENABLED:
                        _tel.DATA_RING_OCCUPANCY.set(len(self._buf))
                        _tel.DATA_BATCHES.inc()
                    self._cond.notify_all()
        except Exception as exc:  # noqa: BLE001 — surfaced at next()
            with self._cond:
                self._error = exc
                self._cond.notify_all()
        finally:
            with self._cond:
                self._exhausted = True
                self._cond.notify_all()

    # -- consumer -----------------------------------------------------------
    def next(self, timeout=120.0):
        """Pop the next staged ``(index, device_batch, ids)`` or None
        at end of stream.  Blocks (counted as a stall) when the ring
        is empty but the stream is not done."""
        tel_on = _tel.ENABLED
        t0 = _time.perf_counter()
        with self._cond:
            self._ctx = _trace.current()
            stalled = not self._buf and not self._exhausted \
                and self._error is None
            deadline = _time.monotonic() + timeout
            while not self._buf and not self._exhausted \
                    and self._error is None:
                if not self._cond.wait(0.2) and \
                        _time.monotonic() > deadline:
                    raise MXNetError(
                        "prefetch ring %r starved for %.0fs (readers "
                        "wedged?)" % (self._name, timeout))
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            if stalled:
                self.stalls += 1
                if tel_on:
                    _tel.DATA_RING_STALLS.inc()
            if not self._buf:
                if tel_on:
                    _tel.DATALOADER_WAIT_SECONDS.observe(
                        _time.perf_counter() - t0)
                return None
            item = self._buf.popleft()
            if tel_on:
                _tel.DATA_RING_OCCUPANCY.set(len(self._buf))
            self._cond.notify_all()
        if tel_on:
            # the time the training loop actually blocked on data —
            # ~0 when the ring stayed ahead (the H3 acceptance bound)
            _tel.DATALOADER_WAIT_SECONDS.observe(
                _time.perf_counter() - t0)
        return item

    def occupancy(self):
        with self._cond:
            return len(self._buf)

    @property
    def depth(self):
        return self._depth

    def stop(self):
        with self._cond:
            self._stop = True
            self._buf.clear()
            self._cond.notify_all()
        self._thread.join(timeout=2.0)
        if _tel.ENABLED:
            _tel.DATA_RING_OCCUPANCY.set(0)
