"""mx.data — sharded streaming input pipeline (ISSUE 15 / ROADMAP 5).

The compute plane is captured and sharded (mx.step + mx.shard), but a
``gluon.data.DataLoader`` over local files still serialized a blocking
``device_put`` in front of every captured step — the PERF_PLAN H3
host-gap.  This package is the production input path that keeps the
pipeline ahead of the program (Relay's whole-pipeline argument: e2e
throughput is set by the slowest stitched stage):

- :class:`ShardSet` + :class:`ReaderPool` (reader.py) — per-host
  reader workers over sharded RecordIO sources, shard assignment
  derived from the ``(process_index, dp_rank)`` world coordinates so
  each host reads only its slice;
- :class:`PrefetchRing` (ring.py) — a device-resident ring that
  asynchronously stages the next ``MXNET_DATA_PREFETCH`` batches onto
  their ``GlobalMesh.batch_sharding`` placements while the current
  step runs, so captured-program dispatch never waits on H2D;
- :class:`StreamLoader` (loader.py) — the front-end tying them
  together, with a **deterministic mid-epoch cursor** that rides
  ``Trainer.state_dict()`` into the ``PodCheckpointManager``: a
  whole-world restart resumes the exact remaining sample order
  bit-identically.

``data_*`` telemetry (ring occupancy/stalls, read/decode/stage
histograms) + ``data_stage``/``data_read_batch`` trace spans make the
pipeline observable; ``make data-smoke`` drills the H3 bound and the
mid-epoch world-restart resume on CPU.
"""
from __future__ import annotations

from ..base import MXNetError, get_env
from .loader import StreamLoader, default_workers, live_loaders
from .reader import (ReaderPool, ShardSet, default_decode, world_coords)
from .ring import PrefetchRing, default_depth

__all__ = ["StreamLoader", "ShardSet", "ReaderPool", "PrefetchRing",
           "default_decode", "default_depth", "default_workers",
           "world_coords", "live_loaders", "require_sharded", "state"]


def require_sharded(what):
    """Guard for legacy whole-dataset iterators: in a multi-host world
    every host feeding itself the FULL dataset silently breaks
    data-parallel semantics (each global batch is seen world times).
    Raises a clear ``MXNetError`` naming the replacement; set
    ``MXNET_DATA_ALLOW_UNSHARDED=1`` to accept the duplication
    knowingly (debug/replicated-eval runs)."""
    num_hosts, _host = world_coords()
    if num_hosts <= 1:
        return
    if get_env("MXNET_DATA_ALLOW_UNSHARDED", bool, False):
        return
    raise MXNetError(
        "%s reads the whole dataset on every host — in this %d-host "
        "world each sample would be trained %d times per epoch.  Use "
        "mx.data.StreamLoader (sharded streaming + prefetch ring + "
        "checkpointed cursor), or set MXNET_DATA_ALLOW_UNSHARDED=1 to "
        "bypass this check deliberately." % (what, num_hosts, num_hosts))


def state():
    """Snapshot of every live loader for ``tools/diagnose.py --data``."""
    return [ldr.stats() for ldr in live_loaders()]
