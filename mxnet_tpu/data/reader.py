"""Sharded streaming RecordIO readers — the host half of ``mx.data``.

A :class:`ShardSet` describes a dataset stored as N RecordIO shard
files (the webdataset-style layout ``tools/im2rec.py`` and the bench
writers already produce).  Shard **assignment** is derived from the
host coordinates of the training world — ``(process_index, dp_rank)``
of the PR 11 ``GlobalMesh``, or the ``tools/launch.py`` env on CPU
drill worlds — so each host opens and reads ONLY its slice:

- ``len(shards) >= num_hosts``: whole shards round-robin per host
  (the production layout — no host ever touches a peer's files);
- fewer shards than hosts: record-level striping (``entries[host::
  num_hosts]``) so small drill datasets still shard correctly.

The per-epoch **sample order** is a pure function of ``(seed, epoch,
host)``: a ``numpy.random.default_rng(SeedSequence((seed, epoch)))``
permutation of the host's entry list.  That purity is the whole
resume story — a cursor is just ``(epoch, batches_consumed)`` plus
the seed, and replaying from it reproduces the remaining sample
stream bit-identically on every host (ISSUE 15 acceptance).

The :class:`ReaderPool` runs ``num_workers`` threads; batch ``b`` is
built by worker ``b % W`` (each worker holds its own file handles, so
reads never contend on a shared seek pointer), completions reorder by
batch index, and backpressure bounds read-ahead to what the prefetch
ring downstream can hold.  Reader IO is an ``MXNET_FAULTS`` site
(``data_read@<batch>``): ``io``-kind faults engage the bounded retry
loop exactly like a real storage hiccup, anything else surfaces to
the consumer.
"""
from __future__ import annotations

import glob as _glob
import io as _bio
import os
import struct
import threading
import time as _time

import numpy as _np

from .. import telemetry as _tel
from .. import trace as _trace
from ..base import MXNetError, get_env
from ..resilience import inject as _inject

__all__ = ["ShardSet", "ReaderPool", "default_decode", "world_coords",
           "read_record_at"]

_MAGIC = 0xced7230a          # recordio.py framing (same container)
_READ_RETRIES = 3
_RETRY_SLEEP = 0.05


def world_coords(num_hosts=None, host=None):
    """The (num_hosts, host) data-plane coordinates of this process.

    Order of truth: explicit args > the ``tools/launch.py`` rendezvous
    env (``MXNET_DIST_NUM_WORKERS``/``MXNET_DIST_RANK`` — set even on
    ``--rendezvous none`` CPU drill worlds where jax.distributed never
    initializes) > the live jax process grid > a world of one.  The
    jax probe is best-effort and never *initializes* the backend."""
    if num_hosts is None:
        num_hosts = get_env("MXNET_DIST_NUM_WORKERS", int, 0) or 0
        if num_hosts <= 0:
            try:
                from ..shard.mesh import _distributed_client

                client = _distributed_client()
                import jax

                num_hosts = jax.process_count() if client is not None \
                    else 1
            except Exception:
                num_hosts = 1
    if host is None:
        host = get_env("MXNET_DIST_RANK", int, 0) or 0
        if num_hosts > 1 and host == 0:
            try:
                import jax

                host = jax.process_index()
            except Exception:
                host = 0
    num_hosts = max(1, int(num_hosts))
    host = int(host)
    if not 0 <= host < num_hosts:
        raise MXNetError("data host %d outside world of %d"
                         % (host, num_hosts))
    return num_hosts, host


def _scan_offsets(path):
    """Record byte offsets of a RecordIO file without an .idx sidecar
    (one sequential pass of the framing headers; payloads skipped)."""
    offsets = []
    with open(path, "rb") as f:
        pos = 0
        while True:
            head = f.read(8)
            if len(head) < 8:
                break
            magic, length = struct.unpack("<II", head)
            if magic != _MAGIC:
                raise MXNetError("invalid record magic in %s at byte %d"
                                 % (path, pos))
            offsets.append(pos)
            pad = (4 - length % 4) % 4
            f.seek(length + pad, os.SEEK_CUR)
            pos += 8 + length + pad
    return offsets


def _load_idx(idx_path):
    offsets = []
    with open(idx_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) == 2:
                offsets.append(int(parts[1]))
    return offsets


def read_record_at(handle, offset):
    """Read ONE framed record payload at ``offset`` from an open
    binary handle (the random-access primitive under every worker)."""
    handle.seek(offset)
    head = handle.read(8)
    if len(head) < 8:
        raise MXNetError("truncated record at byte %d" % offset)
    magic, length = struct.unpack("<II", head)
    if magic != _MAGIC:
        raise MXNetError("invalid record magic at byte %d" % offset)
    buf = handle.read(length)
    if len(buf) < length:
        raise MXNetError("truncated record payload at byte %d" % offset)
    return buf


def default_decode(raw):
    """Default record decoder: ``recordio.pack``-framed IRHeader +
    payload -> ``(data, label)`` numpy arrays.  npy payloads load
    directly; JPEG payloads go through ``unpack_img``'s decoders."""
    from ..recordio import unpack, unpack_img

    header, payload = unpack(raw)
    if payload[:2] == b"\xff\xd8":                    # JPEG magic
        header, img = unpack_img(raw)
        data = _np.asarray(img)
    else:
        data = _np.load(_bio.BytesIO(payload), allow_pickle=False)
    label = _np.asarray(header.label, dtype=_np.float32)
    return data, label


class _Shard:
    __slots__ = ("path", "idx_path", "offsets")

    def __init__(self, path, idx_path=None):
        self.path = os.fspath(path)
        if idx_path is None:
            cand = os.path.splitext(self.path)[0] + ".idx"
            idx_path = cand if os.path.exists(cand) else None
        self.idx_path = idx_path
        self.offsets = (_load_idx(idx_path) if idx_path
                        else _scan_offsets(self.path))

    def __len__(self):
        return len(self.offsets)


class ShardSet:
    """An ordered set of RecordIO shards + the deterministic
    host-assignment and epoch-order math of the streaming loader."""

    def __init__(self, paths):
        paths = [os.fspath(p) for p in paths]
        if not paths:
            raise MXNetError("ShardSet needs at least one shard file")
        self.shards = [_Shard(p) for p in sorted(paths)]
        # global id base per shard: sample id = base[si] + record pos —
        # stable across any assignment mode, the drill's audit key
        self._base = []
        total = 0
        for s in self.shards:
            self._base.append(total)
            total += len(s)
        self.total_records = total

    @classmethod
    def from_pattern(cls, pattern):
        """Glob a shard pattern (``train-*.rec``); a single concrete
        file is a one-shard set."""
        paths = sorted(_glob.glob(os.fspath(pattern)))
        if not paths:
            if os.path.exists(pattern):
                paths = [pattern]
            else:
                raise MXNetError("no shard files match %r" % (pattern,))
        return cls(paths)

    def __len__(self):
        return len(self.shards)

    def global_id(self, shard_index, pos):
        return self._base[shard_index] + int(pos)

    # -- assignment ----------------------------------------------------------
    def assignment(self, num_hosts, host):
        """This host's entry list ``[(shard_index, record_pos), ...]``
        in canonical (pre-shuffle) order, plus the assignment mode.
        Whole shards round-robin when there are enough of them; else
        record-level striping keeps every host fed."""
        num_hosts = max(1, int(num_hosts))
        host = int(host)
        if len(self.shards) >= num_hosts:
            mine = range(host, len(self.shards), num_hosts)
            entries = [(si, pos) for si in mine
                       for pos in range(len(self.shards[si]))]
            return entries, "shard"
        entries = [(si, pos) for si in range(len(self.shards))
                   for pos in range(len(self.shards[si]))]
        return entries[host::num_hosts], "record"

    def host_record_count(self, num_hosts, host):
        """O(shards) count of ``assignment(num_hosts, host)`` —
        every host can compute every peer's slice size, which is how
        the epoch length becomes a world-wide constant."""
        num_hosts = max(1, int(num_hosts))
        if len(self.shards) >= num_hosts:
            return sum(len(self.shards[si])
                       for si in range(int(host), len(self.shards),
                                       num_hosts))
        # record striping: ceil((total - host) / num_hosts)
        return max(0, (self.total_records - int(host) + num_hosts - 1)
                   // num_hosts)

    def batches_per_epoch(self, num_hosts, local_batch):
        """Epoch length every host agrees on: the MIN host slice,
        whole batches only (the distributed drop-last rule — a global
        batch must have every host's contribution)."""
        counts = [self.host_record_count(num_hosts, h)
                  for h in range(max(1, int(num_hosts)))]
        return min(counts) // max(1, int(local_batch))

    # -- epoch order -----------------------------------------------------------
    @staticmethod
    def epoch_order(entries, seed, epoch, shuffle=True):
        """The epoch's sample order over ``entries`` — a pure function
        of ``(seed, epoch)`` (numpy ``SeedSequence`` keyed on both), so
        any position in it can be re-derived after a restart without
        replaying reads."""
        n = len(entries)
        if not shuffle:
            return list(range(n))
        rng = _np.random.default_rng(
            _np.random.SeedSequence((int(seed), int(epoch))))
        return list(rng.permutation(n))

    def describe(self):
        return {"shards": [s.path for s in self.shards],
                "records": self.total_records,
                "per_shard": [len(s) for s in self.shards]}


def _batchify(samples):
    """Stack decoded samples ((a, b, ...) tuples of numpy arrays) into
    a tuple of batch arrays; f64 narrows to f32 like the gluon
    default_batchify_fn."""
    first = samples[0]
    if not isinstance(first, (tuple, list)):
        samples = [(s,) for s in samples]
        first = samples[0]
    out = []
    for col in range(len(first)):
        arr = _np.stack([_np.asarray(s[col]) for s in samples], axis=0)
        if arr.dtype == _np.float64:
            arr = arr.astype(_np.float32)
        out.append(arr)
    return tuple(out)


class ReaderPool:
    """Ordered multi-threaded batch reader over one host's shard
    slice.  ``next_batch()`` returns ``(batch_index, np_batch_tuple,
    sample_ids)`` strictly in order; ``start_batch`` fast-forwards an
    epoch resume without reading a single skipped record."""

    def __init__(self, shard_set, entries, order, local_batch,
                 num_workers, decode_fn=None, start_batch=0,
                 max_batches=None, readahead=4, epoch=0):
        self._set = shard_set
        self._entries = entries
        self._order = order
        self._batch = int(local_batch)
        self._decode = decode_fn or default_decode
        self._epoch = int(epoch)
        n_batches = len(order) // self._batch
        if max_batches is not None:
            n_batches = min(n_batches, int(max_batches))
        self._n_batches = n_batches
        self._next_emit = int(start_batch)
        self._readahead = max(1, int(readahead))
        self._done = {}                       # batch idx -> (payload, ids, err)
        self._cond = threading.Condition()
        self._stop = False
        self._workers = []
        self._read_counts = {}                # worker id -> records read
        nw = max(1, int(num_workers))
        for w in range(nw):
            t = threading.Thread(
                target=self._worker_loop,
                args=(w, nw, int(start_batch)),
                name="mx-data-reader-%d" % w, daemon=True)
            t.start()
            self._workers.append(t)

    # -- worker side -----------------------------------------------------------
    def _batch_entries(self, b):
        lo = b * self._batch
        return [self._entries[self._order[i]]
                for i in range(lo, lo + self._batch)]

    def _read_one(self, handles, si, pos):
        shard = self._set.shards[si]
        h = handles.get(si)
        if h is None:
            h = handles[si] = open(shard.path, "rb")
        return read_record_at(h, shard.offsets[pos])

    def _build_batch(self, handles, b):
        """Read + decode + batchify batch ``b`` with the bounded IO
        retry loop around the read phase (the ``data_read`` fault
        site fires here, keyed by batch index)."""
        entries = self._batch_entries(b)
        ids = _np.asarray([self._set.global_id(si, pos)
                           for si, pos in entries], dtype=_np.int64)
        delay = _RETRY_SLEEP
        for attempt in range(_READ_RETRIES):
            t0 = _time.perf_counter()
            try:
                _inject.fire("data_read", seq=b)
                raws = [self._read_one(handles, si, pos)
                        for si, pos in entries]
                break
            except OSError:
                # a real (or injected-io) storage hiccup: reopen the
                # handles and retry with backoff, like checkpoint IO
                for h in handles.values():
                    try:
                        h.close()
                    except OSError:
                        pass
                handles.clear()
                if _tel.ENABLED:
                    _tel.DATA_READ_RETRIES.inc()
                if attempt == _READ_RETRIES - 1:
                    raise
                _time.sleep(delay)
                delay *= 2
        if _tel.ENABLED:
            _tel.DATA_READ_SECONDS.observe(_time.perf_counter() - t0)
        t1 = _time.perf_counter()
        samples = [self._decode(raw) for raw in raws]
        batch = _batchify(samples)
        if _tel.ENABLED:
            _tel.DATA_DECODE_SECONDS.observe(_time.perf_counter() - t1)
            _tel.DATA_RECORDS.inc(len(raws))
        return batch, ids

    def _worker_loop(self, w, nw, start_batch):
        handles = {}
        # worker w owns batch indices congruent to (start + w) mod nw
        b = start_batch + w
        try:
            while True:
                if b >= self._n_batches:
                    return
                with self._cond:
                    # backpressure: never run further than `readahead`
                    # batches past the consumer (the prefetch ring
                    # downstream bounds device residency the same way)
                    while not self._stop and \
                            b >= self._next_emit + self._readahead:
                        self._cond.wait(0.2)
                    if self._stop:
                        return
                err = payload = ids = None
                try:
                    with _trace.span("data_read_batch", hist=False,
                                     cat="data", args={"batch": b}):
                        payload, ids = self._build_batch(handles, b)
                except Exception as exc:  # noqa: BLE001 — surfaced at next()
                    err = exc
                with self._cond:
                    if self._stop:
                        return
                    self._done[b] = (payload, ids, err)
                    self._read_counts[w] = \
                        self._read_counts.get(w, 0) + self._batch
                    self._cond.notify_all()
                b += nw
        finally:
            for h in handles.values():
                try:
                    h.close()
                except OSError:
                    pass

    # -- consumer side -----------------------------------------------------------
    @property
    def n_batches(self):
        return self._n_batches

    def next_batch(self, timeout=120.0):
        """The next in-order ``(index, batch, ids)``, or None at epoch
        end.  Worker exceptions re-raise here."""
        with self._cond:
            b = self._next_emit
            if b >= self._n_batches:
                return None
            deadline = _time.monotonic() + timeout
            while b not in self._done:
                if self._stop:
                    return None
                if not self._cond.wait(0.2):
                    if _time.monotonic() > deadline:
                        raise MXNetError(
                            "data reader timed out after %.0fs waiting "
                            "for batch %d (workers alive: %d)"
                            % (timeout, b,
                               sum(t.is_alive() for t in self._workers)))
            payload, ids, err = self._done.pop(b)
            self._next_emit = b + 1
            self._cond.notify_all()
        if err is not None:
            raise err
        return b, payload, ids

    def stop(self):
        with self._cond:
            self._stop = True
            self._done.clear()
            self._cond.notify_all()
        for t in self._workers:
            t.join(timeout=2.0)

    def read_counts(self):
        with self._cond:
            return dict(self._read_counts)
