"""mx.serve.decode — continuous batching over a paged KV-cache.

The PR 3 scheduler coalesces fixed-shape micro-batches: right for
vision, wrong for decoder-LLM traffic, where every request is a
*sequence* that produces one token per model step and lives for
hundreds of steps.  Request-level batching would hold a finished
sequence's slot (and its KV cache) hostage until the slowest
batch-mate finished.  This module implements Orca-style
**iteration-level scheduling** instead: one jitted decode-step program
runs every iteration over whichever sequences are live *right now* —
new sequences are admitted into freed slots mid-flight, finished /
expired / poisoned sequences are evicted and their KV pages reclaimed
the same step.

Layers:

- ``DecodeRunner`` — owns the model (a decoder ``HybridBlock``
  following the contract below), the ``kvcache.PagePool``, and the
  compiled program table: ONE program per decode batch bucket and one
  per prefill length bucket, each built once (``jax.jit`` with pool
  donation), fingerprinted into the ``mx.compile`` persistent cache
  (``attach_lowered``) so a restarted server reaches readiness with
  zero fresh XLA compiles, and metered per bucket
  (``serve_decode_compile_total``: steady state adds nothing).
- ``DecodeScheduler`` — the admission queue + continuous-batching
  loop: bounded waiting queue with deadline expiry, page reservation
  at admission (the whole worst case — never a mid-decode allocation
  failure), prefill through the bucket path, then the decode loop.
  Failure containment mirrors the vision scheduler: a failing step is
  retried **bisected** down to single sequences so a poisoned sequence
  fails ALONE with its pages reclaimed while batch-mates keep
  decoding (``serve_poison_requests_total``; drilled via the
  ``MXNET_FAULTS`` ``serve_poison@<request-id>`` site), and decode
  buckets carry their own circuit breakers.
- ``TinyDecoder`` — a small but real transformer decoder implementing
  the model contract; the reference model for tests, the smoke drill
  and the bench row, and executable documentation of the contract.

**Decoder model contract.**  Any ``HybridBlock`` with integer
attributes ``num_layers`` / ``num_kv_heads`` / ``head_dim`` /
``vocab_size`` (optional ``eos_id``) and the forward signature::

    forward(tokens,        # [B, T]            int32 token ids
            k_ctx, v_ctx,  # [B, L, S, H, D]   gathered paged context
            ctx_lengths,   # [B]               int32 cached positions
            chunk_lengths) # [B]               int32 valid chunk length
        -> (last_logits,   # [B, vocab]        logits at the last
                           #                   valid chunk position
            k_new, v_new)  # [B, T, L, H, D]   cache rows for the chunk

serves through this path.  Prefill is the ``S == 0`` signature
(``T`` = prompt bucket); decode is ``T == 1`` with the full paged
context.  The forward must attend causally within the chunk and mask
context positions ``>= ctx_length``; everything page-shaped (gather,
scatter, argmax sampling, the per-token nonfinite guard) happens in
the jitted wrapper the runner builds around ``export_pure``, so the
model stays paging-agnostic.

Every emitted token passes the PR 7 output guard *in-program* (a
nonfinite logit row costs one int per sequence, not a logits
round-trip): a sequence that goes NaN is evicted alone.  Per-token
``serve_decode_token`` trace spans hang off the request's single
``X-Request-Id`` trace, and token streaming reaches the HTTP
front-end through the ``on_token`` callback (``server.py`` chunked
responses on ``/predict?stream=1``).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError

import numpy as _np

from .. import telemetry, trace
from ..base import get_env
from ..resilience import inject as _inject
from ..resilience.inject import InjectedFault, InjectedIOError
from .batching import (RequestTimeout, ServeError,
                       ServerClosed, ServerOverloaded, fail_request)
from .kvcache import (PageConfig, PagePool, PagePoolExhausted,
                      gather_pages, scatter_pages)

__all__ = ["DecodeError", "DecodeConfig", "DecodeRequest",
           "DecodeRunner", "DecodeScheduler", "TinyDecoder"]


class DecodeError(ServeError):
    """Decode-path request validation / execution error."""


def _pow2_up_to(lo, hi):
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(sorted(set(out)))


class DecodeConfig:
    """Knobs of the decode path (README "Autoregressive serving").

    page_size / pool_pages : KV page geometry
        (``MXNET_SERVE_DECODE_PAGE_SIZE`` / ``_POOL_PAGES``).
    max_live : concurrent sequences in the running batch
        (``MXNET_SERVE_DECODE_MAX_LIVE``); also caps the decode batch
        bucket table.
    max_new_tokens : default + hard per-request generation cap
        (``MXNET_SERVE_DECODE_MAX_NEW``).
    max_context : bound on prompt + generated tokens per sequence;
        fixes the paged-attention context extent every decode program
        compiles for.
    prefill_lengths : prompt padding buckets (default: powers of two
        up to ``max_context``).
    batch_sizes : decode batch buckets (default: powers of two up to
        ``max_live``).
    queue_depth : bound on ADMISSION-waiting sequences; beyond it
        submissions are rejected with ``ServerOverloaded``.
    timeout_ms : default per-request deadline (expires a sequence
        mid-generation too).
    stream : whether the HTTP front-end advertises/serves chunked
        token streaming (``MXNET_SERVE_DECODE_STREAM``).
    eos_id : default stop token (None = length-only stopping).
    prefix_cache : enable the radix prefix cache (serve/cache.py;
        ``MXNET_SERVE_PREFIX_CACHE``, default OFF — opt-in so the
        warm-up program table is unchanged for existing deployments).
    spec_k : speculative draft proposal count when a draft model is
        given (``MXNET_SERVE_SPEC_K``; 0 = resolve via the ``spec_k``
        autotune site / the built-in default).
    """

    def __init__(self, page_size=None, pool_pages=None, max_live=None,
                 max_new_tokens=None, max_context=128,
                 prefill_lengths=None, batch_sizes=None, queue_depth=64,
                 timeout_ms=None, stream=None, eos_id=None,
                 dtype="float32", prefix_cache=None, spec_k=None):
        self.page_size = get_env("MXNET_SERVE_DECODE_PAGE_SIZE", int, 16) \
            if page_size is None else int(page_size)
        self.pool_pages = get_env("MXNET_SERVE_DECODE_POOL_PAGES", int,
                                  256) \
            if pool_pages is None else int(pool_pages)
        self.max_live = get_env("MXNET_SERVE_DECODE_MAX_LIVE", int, 8) \
            if max_live is None else int(max_live)
        self.max_new_tokens = get_env("MXNET_SERVE_DECODE_MAX_NEW", int,
                                      64) \
            if max_new_tokens is None else int(max_new_tokens)
        self.stream = get_env("MXNET_SERVE_DECODE_STREAM", bool, True) \
            if stream is None else bool(stream)
        self.max_context = int(max_context)
        if prefill_lengths is None:
            prefill_lengths = _pow2_up_to(
                min(8, self.max_context), self.max_context)
        self.prefill_lengths = tuple(sorted(set(
            int(t) for t in prefill_lengths if int(t) <= self.max_context)))
        if not self.prefill_lengths:
            raise ValueError("no prefill bucket <= max_context=%d"
                             % self.max_context)
        if batch_sizes is None:
            default_set = _pow2_up_to(1, max(1, self.max_live))
            batch_sizes = self._tuned_batch_sizes(default_set)
        self.batch_sizes = tuple(sorted(set(int(b) for b in batch_sizes)))
        if self.batch_sizes[-1] < self.max_live:
            raise ValueError(
                "largest decode batch bucket %d < max_live=%d: live "
                "sequences could never all step"
                % (self.batch_sizes[-1], self.max_live))
        self.queue_depth = int(queue_depth)
        self.timeout_ms = timeout_ms
        self.eos_id = eos_id
        self.dtype = dtype
        self.prefix_cache = get_env("MXNET_SERVE_PREFIX_CACHE", bool,
                                    False) \
            if prefix_cache is None else bool(prefix_cache)
        self.spec_k = get_env("MXNET_SERVE_SPEC_K", int, 0) \
            if spec_k is None else int(spec_k)

    def _tuned_batch_sizes(self, default_set):
        """The mx.autotune ``decode_bucket`` winner for this
        ``max_live`` (committed by the decode runner's idle tuner in a
        previous process), validated — every tuned set must still
        cover ``max_live`` — else the power-of-two default.  Decode
        outputs are bucket-table-invariant by the padding design, so a
        tuned table changes compile count and step latency, never
        tokens."""
        from .. import autotune as _at

        if not _at.is_enabled():
            return default_set
        cfg, prov = _at.lookup_info("decode_bucket", (self.max_live,),
                                    list(default_set))
        if prov != "tuned":
            return default_set
        try:
            buckets = sorted(set(int(b) for b in cfg))
        except (TypeError, ValueError):
            buckets = []
        if not buckets or buckets[0] < 1 or buckets[-1] < self.max_live:
            _at.fallback("invalid_config")
            return default_set
        return buckets

    def as_dict(self):
        return {
            "page_size": self.page_size, "pool_pages": self.pool_pages,
            "max_live": self.max_live,
            "max_new_tokens": self.max_new_tokens,
            "max_context": self.max_context,
            "prefill_lengths": list(self.prefill_lengths),
            "batch_sizes": list(self.batch_sizes),
            "queue_depth": self.queue_depth,
            "timeout_ms": self.timeout_ms, "stream": self.stream,
            "eos_id": self.eos_id, "dtype": self.dtype,
            "prefix_cache": self.prefix_cache, "spec_k": self.spec_k,
        }


class DecodeRequest:
    """One autoregressive generation request.

    Carries the same resolution surface as ``batching.Request``
    (``future`` / ``enqueued`` / ``deadline`` / ``request_id`` /
    ``trace``) so the shared failure/telemetry plumbing applies; the
    future resolves to ``{"tokens": [ids...], "finish_reason": ...}``.
    ``on_token(token_id, index)`` — when given — is called once per
    emitted token from the decode loop (it must be cheap and
    non-blocking: enqueue, don't write sockets); the streamed sequence
    is bit-identical to the future's ``tokens``."""

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "on_token",
                 "future", "enqueued", "deadline", "request_id", "trace",
                 "export_only", "handoff", "tenant")

    def __init__(self, prompt, max_new_tokens, eos_id=None, deadline=None,
                 request_id=None, on_token=None, export_only=False,
                 handoff=None, tenant=None):
        # mx.tenant: the registered tenant this request bills to (None
        # = base/anonymous traffic — no WFQ charge, no adapter)
        self.tenant = None if tenant is None else str(tenant)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.on_token = on_token
        # mx.fleet disaggregation: export_only sequences stop after
        # prefill (future resolves to the handoff state dict); handoff
        # carries an unpacked fleet.handoff state to install instead of
        # prefilling locally
        self.export_only = bool(export_only)
        self.handoff = handoff
        self.future = Future()
        self.enqueued = time.perf_counter()
        self.deadline = deadline
        self.request_id = request_id
        self.trace = trace.new_request(request_id)
        if self.trace is not None:
            trace.instant("serve_decode_enqueue", cat="serve",
                          ctx=self.trace,
                          args={"request_id": request_id,
                                "prompt_tokens": len(self.prompt)})

    def expired(self, now=None):
        return self.deadline is not None and \
            (time.perf_counter() if now is None else now) >= self.deadline


class _Seq:
    """Decode-loop bookkeeping for one live sequence."""

    __slots__ = ("req", "sid", "tokens", "length", "pages", "joined_step",
                 "t_prefill", "first_token_t", "last_token",
                 "cache_class", "prefix_len", "shared",
                 "spec", "dlen", "dpages", "depoch",
                 "tenant", "adapter_slot", "quota_pages")

    def __init__(self, req, sid):
        # mx.tenant: billing identity, the bank slot this sequence
        # decodes with (-1 = base weights), and the pages charged to
        # the tenant's quota ledger (None until admission reserves)
        self.tenant = req.tenant
        self.adapter_slot = -1
        self.quota_pages = None
        self.req = req
        self.sid = sid
        self.tokens = []          # generated token ids
        self.length = 0           # positions resident in the KV pages
        self.pages = None
        self.joined_step = None
        self.t_prefill = None
        self.first_token_t = None
        self.last_token = None    # next decode-step input token
        # serve/cache.py: TTFT class, shared-prefix floor (the scrub
        # guard's write boundary) and the shared pages this sequence
        # holds references on (a prefix of ``pages``)
        self.cache_class = None
        self.prefix_len = 0
        self.shared = []
        # serve/spec.py: None = not yet offered to the plane, True =
        # speculating, False = detached/ineligible; dlen is the draft
        # cache cursor, dpages the draft pool reservation
        self.spec = None
        self.dlen = 0
        self.dpages = None
        self.depoch = None

    @property
    def done_reason(self):
        if self.req.eos_id is not None and self.tokens and \
                self.tokens[-1] == self.req.eos_id:
            return "eos"
        if len(self.tokens) >= self.req.max_new_tokens:
            return "length"
        return None


class _Program:
    __slots__ = ("fn", "label", "provenance", "builds")

    def __init__(self, fn, label, provenance):
        self.fn = fn
        self.label = label
        self.provenance = provenance
        self.builds = 1


class DecodeRunner:
    """Model + paged KV pool + compiled decode/prefill program table.

    ``block`` is a decoder HybridBlock following the module-doc
    contract (or a zero-arg factory); ``root``/``step`` restore from an
    ``mx.checkpoint`` root like ``ModelRunner``.  ``warm_up()`` builds
    every (bucket, page-config) program — consulting the ``mx.compile``
    persistent cache first — and runs each once, so steady-state
    decoding triggers at most ONE compile per bucket and a restarted
    server can reach readiness with zero fresh XLA compiles."""

    def __init__(self, block, root=None, step=None, ctx=None, config=None,
                 warm=True, draft=None, tenant=None, mesh=None):
        from ..gluon.block import HybridBlock
        from .runner import resolve_block

        block = resolve_block(block, HybridBlock, "DecodeRunner")
        for attr in ("num_layers", "num_kv_heads", "head_dim",
                     "vocab_size"):
            if not isinstance(getattr(block, attr, None), int):
                raise ValueError(
                    "decoder contract: block must carry int attribute "
                    "%r (see serve/decode.py module doc)" % attr)
        self._block = block
        self._ctx = ctx
        self.config = config or DecodeConfig()
        # the effective stop token lives on the RUNNER, not the config:
        # a DecodeConfig may be shared across runners/models and must
        # not absorb one model's eos_id
        self.eos_id = self.config.eos_id \
            if self.config.eos_id is not None \
            else getattr(block, "eos_id", None)
        self.root = root
        self.step = None
        if root is not None:
            self.step = block.load_checkpoint(root, step=step, ctx=ctx)
        self._resolve_params()
        self._apply_fn, self._params = block.export_pure(training=False)
        # mx.shard phase 2: a model sharded over the mesh's mdl axis.
        # Parameters are STORED per the layout table (1/mdl per device)
        # and each program constrains them in-program: gather mode
        # re-materializes replicated weights (the decode math — and
        # therefore the greedy token stream — is byte-identical to the
        # single-chip program), compute mode keeps them sharded and
        # lets GSPMD shard the matmuls.  dp must be 1: replica fan-out
        # is mx.fleet's job, one runner serves one model instance.
        self.mesh = None
        self._fwd_shardings = None
        if mesh is not None:
            from .. import shard as _shard

            gm = _shard.as_global(mesh)
            if gm.dp != 1:
                raise ValueError(
                    "DecodeRunner(mesh=...) needs dp=1 (got dp=%d): "
                    "one runner serves one model instance; use "
                    "mx.fleet for replicas" % gm.dp)
            if gm.mdl > 1:
                import jax

                self.mesh = gm
                policy = _shard.ShardPolicy(0, gm)
                self._params = {
                    n: jax.device_put(v, policy.param_sharding(
                        v.shape, name=n))
                    for n, v in self._params.items()}
                self._fwd_shardings = {
                    n: policy.forward_sharding(v.shape, name=n)
                    for n, v in self._params.items()}
                self._tp_mode = policy.mode
        # mx.tenant: the adapter bank MUST exist before warm_up so
        # every program compiles with the bank inputs in its signature
        # — adapter churn afterwards is slot-content data, never a
        # recompile.  Without a plane the program table (and its
        # mx.compile fingerprints) is byte-identical to pre-tenant.
        self.tenant = tenant
        self.bank = tenant.build_bank(block) if tenant is not None \
            else None
        c = self.config
        self.page_config = PageConfig(
            c.page_size, c.pool_pages, block.num_layers,
            block.num_kv_heads, block.head_dim, c.max_context,
            dtype=c.dtype)
        self.pool = PagePool(self.page_config, mesh=self.mesh)
        self._programs = {}
        self._run_lock = threading.RLock()
        self._warmed = False
        self.cache = None
        if self.config.prefix_cache:
            from .cache import PrefixCache

            self.cache = PrefixCache(self.pool)
        self.spec = None
        if warm:
            self.warm_up()
        if draft is not None:
            from .spec import SpecPlane

            self.spec = SpecPlane(self, draft,
                                  k=self.config.spec_k or None,
                                  warm=self._warmed)

    # -- setup --------------------------------------------------------------
    def _resolve_params(self):
        """One tiny forward resolves deferred parameter shapes before
        ``export_pure`` (the contract signature with S=0, T=1)."""
        from .. import ndarray as nd

        b = self._block
        zero_ctx = nd.zeros((1, b.num_layers, 0, b.num_kv_heads,
                             b.head_dim), dtype=self.config.dtype)
        ones = nd.array(_np.array([1], dtype="int32"))
        self._block(nd.zeros((1, 1), dtype="int32"), zero_ctx, zero_ctx,
                    nd.zeros((1,), dtype="int32"), ones)

    @property
    def block(self):
        return self._block

    @property
    def warmed(self):
        return self._warmed

    # -- bucket choice ------------------------------------------------------
    def prefill_bucket(self, n):
        for t in self.config.prefill_lengths:
            if t >= n:
                return t
        raise DecodeError(
            "prompt of %d token(s) exceeds the largest prefill bucket "
            "(%d); buckets: %s" % (n, self.config.prefill_lengths[-1],
                                   list(self.config.prefill_lengths)))

    def decode_bucket(self, n):
        for b in self.config.batch_sizes:
            if b >= n:
                return b
        return self.config.batch_sizes[-1]

    # -- program build ------------------------------------------------------
    @staticmethod
    def bucket_key_label(key):
        kind, n = key
        if kind == "verify":
            return "verify:b%dk%d" % n
        return "%s%d" % ({"decode": "decode:b", "prefill": "prefill:t",
                          "chunk": "chunk:t"}[kind], n)

    def _make_step_fn(self, batch, chunk, with_ctx, with_floors=False):
        """The pure (params, k_pool, v_pool, tokens, tables, ctx_lens,
        chunk_lens) -> (k_pool, v_pool, next_tokens, nonfinite) function
        one (bucket, page-config) jit-compiles.  Sampling (greedy
        argmax) and the per-token output guard run in-program: the host
        reads B ints per step, never a logits tensor."""
        import jax.numpy as jnp

        apply_fn = self._apply_fn
        blk = self._block
        nlayers, nheads, hdim = (blk.num_layers, blk.num_kv_heads,
                                 blk.head_dim)
        dtype = self.page_config.dtype
        bank = self.bank

        def core(params, kp, vp, tokens, tables, ctx_lens, chunk_lens,
                 floors, aidx=None, bankf=None):
            if with_ctx:
                k_ctx = gather_pages(kp, tables)
                v_ctx = gather_pages(vp, tables)
                # scrub positions past each sequence's length: freed
                # pages are reallocated WITHOUT zeroing, so a previous
                # owner's values (possibly NaN — that is how a poisoned
                # sequence died) sit in the tail of the current page.
                # Additive attention masking cannot discard NaN inputs
                # (NaN + -1e9 is NaN, and softmax-0 x NaN is NaN), so
                # the contract guarantees the model NEVER sees
                # unwritten context.
                live = (jnp.arange(k_ctx.shape[2])[None, None, :, None,
                                                   None]
                        < ctx_lens[:, None, None, None, None])
                k_ctx = jnp.where(live, k_ctx, 0)
                v_ctx = jnp.where(live, v_ctx, 0)
            else:
                k_ctx = jnp.zeros((batch, nlayers, 0, nheads, hdim),
                                  dtype=dtype)
                v_ctx = k_ctx
            if bank is not None:
                # mx.tenant: bind the (traced) per-sequence adapter
                # index + bank inputs; the instrumented Dense forwards
                # add gather(A,idx)/gather(B,idx) deltas inline, so the
                # mixed-tenant batch stays ONE program
                with bank.applying(aidx, bankf):
                    outs, _states = apply_fn(params, None, tokens,
                                             k_ctx, v_ctx, ctx_lens,
                                             chunk_lens)
            else:
                outs, _states = apply_fn(params, None, tokens, k_ctx,
                                         v_ctx, ctx_lens, chunk_lens)
            logits, k_new, v_new = outs
            pos = ctx_lens[:, None] + jnp.arange(chunk, dtype=jnp.int32)
            valid = jnp.arange(chunk, dtype=jnp.int32)[None, :] \
                < chunk_lens[:, None]
            if floors is not None:
                # COW scrub guard (serve/cache.py): a shared prefix
                # page is NEVER writable — scatter below the floor is
                # dropped even if a caller miscomputes ctx_lens
                valid = valid & (pos >= floors[:, None])
            kp = scatter_pages(kp, tables, pos, valid, k_new)
            vp = scatter_pages(vp, tables, pos, valid, v_new)
            next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            bad = jnp.sum(~jnp.isfinite(logits), axis=-1,
                          dtype=jnp.int32)
            return kp, vp, next_tok, bad

        if with_floors and bank is not None:
            def step(params, kp, vp, tokens, tables, ctx_lens,
                     chunk_lens, floors, aidx, bankf):
                return core(params, kp, vp, tokens, tables, ctx_lens,
                            chunk_lens, floors, aidx, bankf)
        elif with_floors:
            def step(params, kp, vp, tokens, tables, ctx_lens,
                     chunk_lens, floors):
                return core(params, kp, vp, tokens, tables, ctx_lens,
                            chunk_lens, floors)
        elif bank is not None:
            def step(params, kp, vp, tokens, tables, ctx_lens,
                     chunk_lens, aidx, bankf):
                return core(params, kp, vp, tokens, tables, ctx_lens,
                            chunk_lens, None, aidx, bankf)
        else:
            def step(params, kp, vp, tokens, tables, ctx_lens,
                     chunk_lens):
                return core(params, kp, vp, tokens, tables, ctx_lens,
                            chunk_lens, None)
        return step

    def _make_verify_fn(self, batch, k):
        """The speculative verify program (serve/spec.py): judge a
        K-token draft chunk with ONE dispatch.  The model contract
        only exposes the LAST valid chunk logit, so each sequence is
        replicated K+1 times with chunk lengths ``1..K+1`` — row j of
        a group yields the target's argmax after the chunk's first
        j+1 tokens.  K/V is scattered once per sequence from the
        full-chunk replica (causal attention makes per-position rows
        identical across replicas); positions past the eventual
        acceptance point hold draft-conditioned garbage that the
        decode-path scrub guard hides until it is overwritten in
        place."""
        import jax.numpy as jnp

        apply_fn = self._apply_fn
        T = k + 1
        bank = self.bank

        def core(params, kp, vp, tokens, tables, ctx_lens, chunk_lens,
                 floors, aidx=None, bankf=None):
            k_ctx = gather_pages(kp, tables)
            v_ctx = gather_pages(vp, tables)
            live = (jnp.arange(k_ctx.shape[2])[None, None, :, None,
                                               None]
                    < ctx_lens[:, None, None, None, None])
            k_ctx = jnp.where(live, k_ctx, 0)
            v_ctx = jnp.where(live, v_ctx, 0)
            rep = lambda a: jnp.repeat(a, T, axis=0)  # noqa: E731
            rj = jnp.tile(jnp.arange(1, T + 1, dtype=jnp.int32), batch)
            # replicas past a sequence's real chunk length would be
            # conditioned on padding garbage; clamp them to the full
            # chunk (their outputs are never read)
            rep_chunk = jnp.minimum(
                rj, jnp.repeat(jnp.maximum(chunk_lens, 1), T))
            if bank is not None:
                # the adapter index replicates with its sequence: every
                # verify replica of a row applies the SAME adapter the
                # decode path would (bit-parity with single-step)
                with bank.applying(rep(aidx), bankf):
                    outs, _states = apply_fn(params, None, rep(tokens),
                                             rep(k_ctx), rep(v_ctx),
                                             rep(ctx_lens), rep_chunk)
            else:
                outs, _states = apply_fn(params, None, rep(tokens),
                                         rep(k_ctx), rep(v_ctx),
                                         rep(ctx_lens), rep_chunk)
            logits, k_new, v_new = outs
            y = jnp.argmax(logits, axis=-1).astype(jnp.int32) \
                .reshape(batch, T)
            mask = (jnp.arange(T, dtype=jnp.int32)[None, :]
                    < chunk_lens[:, None])
            badrow = jnp.sum(~jnp.isfinite(logits), axis=-1,
                             dtype=jnp.int32).reshape(batch, T)
            bad = jnp.sum(jnp.where(mask, badrow, 0), axis=1)
            k_full = k_new.reshape((batch, T) + k_new.shape[1:])[:, T - 1]
            v_full = v_new.reshape((batch, T) + v_new.shape[1:])[:, T - 1]
            pos = ctx_lens[:, None] + jnp.arange(T, dtype=jnp.int32)
            valid = mask & (pos >= floors[:, None])
            kp = scatter_pages(kp, tables, pos, valid, k_full)
            vp = scatter_pages(vp, tables, pos, valid, v_full)
            return kp, vp, y, bad

        if bank is not None:
            def step(params, kp, vp, tokens, tables, ctx_lens,
                     chunk_lens, floors, aidx, bankf):
                return core(params, kp, vp, tokens, tables, ctx_lens,
                            chunk_lens, floors, aidx, bankf)
        else:
            def step(params, kp, vp, tokens, tables, ctx_lens,
                     chunk_lens, floors):
                return core(params, kp, vp, tokens, tables, ctx_lens,
                            chunk_lens, floors)
        return step

    def _mesh_wrap(self, fn):
        """Pin in-program layouts for a ``mdl > 1`` mesh (mx.shard
        phase 2).  Weights are constrained per the ShardPolicy —
        replicated in gather mode, so the decode math and the greedy
        argmax stay byte-identical to single-chip, or their Megatron
        layout in compute mode.  The KV pool is gathered at entry for
        the math and the OUTPUT pool is pinned back onto its
        head-sharded storage layout, so the donated re-bind keeps
        per-device KV residency at 1/mdl between steps."""
        import jax

        fs = self._fwd_shardings
        store = self.pool.sharding
        entry = self.mesh.replicated() if self._tp_mode == "gather" \
            else store

        def wrapped(params, kp, vp, *rest, _fn=fn):
            wsc = jax.lax.with_sharding_constraint
            params = {n: wsc(v, fs[n]) for n, v in params.items()}
            if store is not None:
                kp, vp = wsc(kp, entry), wsc(vp, entry)
            out = _fn(params, kp, vp, *rest)
            if store is not None:
                out = (wsc(out[0], store), wsc(out[1], store)) \
                    + tuple(out[2:])
            return out

        return wrapped

    def _build(self, key):
        """Build (or restore from the mx.compile persistent cache) the
        program for ``key`` = ("decode", B) | ("prefill", T) |
        ("chunk", T) cached-suffix prefill | ("verify", (B, K))
        speculative verify."""
        import jax

        kind, n = key
        if kind == "verify":
            vb, vk = n
            batch, chunk = vb, vk + 1
            with_floors = True
            fn = self._make_verify_fn(vb, vk)
        else:
            batch = n if kind == "decode" else 1
            chunk = 1 if kind == "decode" else n
            with_floors = kind == "chunk"
            fn = self._make_step_fn(
                batch, chunk, with_ctx=kind in ("decode", "chunk"),
                with_floors=with_floors)
        label = self.bucket_key_label(key)
        if self.mesh is not None:
            fn = self._mesh_wrap(fn)
        jitted = jax.jit(fn, donate_argnums=(1, 2))
        provenance = "fresh"
        compiled = None
        try:
            if self.mesh is None:
                aval = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
            else:
                # committed mesh layouts are part of the program
                # signature: the compiled executable must expect the
                # sharded params/pool it will be fed
                aval = lambda a: jax.ShapeDtypeStruct(  # noqa: E731
                    a.shape, a.dtype, sharding=getattr(a, "sharding",
                                                       None))
            params_avals = jax.tree_util.tree_map(aval, self._params)
            c = self.page_config
            pool_aval = jax.ShapeDtypeStruct(
                (c.num_layers, c.num_pages, c.page_size, c.num_kv_heads,
                 c.head_dim), _np.dtype(c.dtype),
                sharding=self.pool.sharding)
            i32 = _np.dtype("int32")
            avals = [params_avals, pool_aval, pool_aval,
                     jax.ShapeDtypeStruct((batch, chunk), i32),
                     jax.ShapeDtypeStruct((batch, c.pages_per_seq), i32),
                     jax.ShapeDtypeStruct((batch,), i32),
                     jax.ShapeDtypeStruct((batch,), i32)]
            if with_floors:
                avals.append(jax.ShapeDtypeStruct((batch,), i32))
            if self.bank is not None:
                # adapter index + flat bank tuple (mx.tenant): bank
                # shapes are part of the program fingerprint, so a
                # restored cache entry matches only an identically
                # shaped bank
                avals.append(jax.ShapeDtypeStruct((batch,), i32))
                avals.append(tuple(self.bank.avals()))
            lowered = jitted.lower(*avals)
            from ..compile.aot import attach_lowered

            compiled, _fp, provenance = attach_lowered(
                lowered, type(self._block).__name__ + ".decode_step",
                label)
        except Exception:
            compiled = None  # lazy jit path below; still one compile
        prog = _Program(compiled if compiled is not None else jitted,
                        label, provenance)
        self._programs[key] = prog
        if telemetry.ENABLED and provenance != "cache":
            telemetry.SERVE_DECODE_COMPILES.labels(bucket=label).inc()
        return prog

    def warm_up(self):
        """Pre-build every decode batch bucket and prefill length
        bucket program and run each once (compiles now, not on the
        first live sequence).  Returns the number of fresh builds
        (cache restores count 0)."""
        fresh = 0
        keys = [("decode", b) for b in self.config.batch_sizes] + \
            [("prefill", t) for t in self.config.prefill_lengths]
        if self.config.prefix_cache:
            # cached-suffix prefill programs (serve/cache.py), one per
            # prefill bucket — opt-in, so deployments without the
            # prefix cache keep an identical program table
            keys += [("chunk", t) for t in self.config.prefill_lengths]
        for key in keys:
            if key in self._programs:
                continue
            with trace.span("serve_decode_warmup", hist=False,
                            cat="serve",
                            args={"bucket": self.bucket_key_label(key)}):
                prog = self._build(key)
                if prog.provenance != "cache":
                    fresh += 1
                # one throw-away execution against all-null page tables
                # (drop-mode scatter: the pool is untouched) proves the
                # program runs — and in the lazy-jit fallback forces
                # the XLA compile to happen before readiness
                kind, n = key
                batch = n if kind == "decode" else 1
                chunk = 1 if kind == "decode" else n
                self._dispatch(prog, self._null_inputs(
                    batch, chunk, floors=(kind == "chunk")))
        self._warmed = True
        # mx.autotune idle-time tuning (MXNET_AUTOTUNE=search): every
        # decode bucket program is warm and idempotent against null
        # inputs (drop-mode page tables leave the pool untouched), so
        # measure each one and commit the cheapest candidate bucket
        # SET — the next process's DecodeConfig looks it up at build
        # time.  Budget-bounded; failures degrade to the untuned table
        from .. import autotune as _autotune

        if _autotune.search_enabled():
            try:
                _autotune.measure.decode_idle_tune(self)
            except Exception:
                _autotune.fallback("serve_idle")
        spec = getattr(self, "spec", None)
        if spec is not None and not spec.warmed:
            fresh += spec.warm_up()
        return fresh

    def _null_inputs(self, batch, chunk, floors=False):
        c = self.page_config
        inputs = (_np.zeros((batch, chunk), dtype=_np.int32),
                  _np.full((batch, c.pages_per_seq), self.pool.null_page,
                           dtype=_np.int32),
                  _np.zeros((batch,), dtype=_np.int32),
                  _np.ones((batch,), dtype=_np.int32))
        if floors:
            inputs += (_np.zeros((batch,), dtype=_np.int32),)
        if self.bank is not None:
            inputs += (self.bank.null_index(batch),
                       self.bank.flat_arrays())
        return inputs

    def provenance(self):
        return {p.label: p.provenance for p in self._programs.values()}

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, prog, inputs):
        """Run one program over the CURRENT pool arrays (donated) and
        re-bind the updated pool.  Any failure after the donation point
        can leave the pool consumed — detected and surfaced as a
        ``pool_lost`` DecodeError (the scheduler evicts everything;
        per-sequence containment is impossible without storage)."""
        kp, vp = self.pool.k, self.pool.v
        try:
            out = prog.fn(self._params, kp, vp, *inputs)
            next_tok = _np.asarray(out[2])   # hard sync: errors land here
            bad = _np.asarray(out[3])
            self.pool.k, self.pool.v = out[0], out[1]
            return next_tok, bad
        except (InjectedFault, InjectedIOError):
            raise
        except BaseException as exc:  # noqa: BLE001 - classified below
            if getattr(kp, "is_deleted", lambda: False)():
                import jax.numpy as jnp

                c = self.page_config
                shape = (c.num_layers, c.num_pages, c.page_size,
                         c.num_kv_heads, c.head_dim)
                self.pool.k = jnp.zeros(shape, dtype=c.dtype)
                self.pool.v = jnp.zeros(shape, dtype=c.dtype)
                if self.pool.sharding is not None:
                    import jax

                    self.pool.k = jax.device_put(self.pool.k,
                                                 self.pool.sharding)
                    self.pool.v = jax.device_put(self.pool.v,
                                                 self.pool.sharding)
                err = DecodeError(
                    "decode step failed AFTER pool donation; KV storage "
                    "lost, all live sequences must restart: %r" % (exc,))
                err.pool_lost = True
                raise err from exc
            raise

    def prefill(self, seq):
        """Run one sequence's prompt through its prefill bucket; writes
        the prompt's K/V into the sequence's reserved pages and returns
        ``(first_token, nonfinite_count)``."""
        c = self.page_config
        prompt = seq.req.prompt
        t_bucket = self.prefill_bucket(len(prompt))
        tokens = _np.zeros((1, t_bucket), dtype=_np.int32)
        tokens[0, :len(prompt)] = prompt
        tables = _np.full((1, c.pages_per_seq), self.pool.null_page,
                          dtype=_np.int32)
        tables[0, :len(seq.pages)] = seq.pages
        ctx_lens = _np.zeros((1,), dtype=_np.int32)
        chunk_lens = _np.array([len(prompt)], dtype=_np.int32)
        inputs = (tokens, tables, ctx_lens, chunk_lens)
        if self.bank is not None:
            inputs += (_np.array([seq.adapter_slot], dtype=_np.int32),
                       self.bank.flat_arrays())
        with self._run_lock:
            prog = self._programs.get(("prefill", t_bucket)) or \
                self._build(("prefill", t_bucket))
            next_tok, bad = self._dispatch(prog, inputs)
        return int(next_tok[0]), int(bad[0])

    def prefill_cached(self, seq, hit_tokens):
        """Cached-suffix prefill (serve/cache.py): the first
        ``hit_tokens`` positions of the prompt are already resident in
        shared pages, so only the suffix runs — through the
        ``("chunk", T)`` program, which attends over the shared
        context and scatters strictly above the ``hit_tokens`` floor
        (a shared page is never writable)."""
        c = self.page_config
        prompt = seq.req.prompt
        suffix = prompt[hit_tokens:]
        t_bucket = self.prefill_bucket(len(suffix))
        tokens = _np.zeros((1, t_bucket), dtype=_np.int32)
        tokens[0, :len(suffix)] = suffix
        tables = _np.full((1, c.pages_per_seq), self.pool.null_page,
                          dtype=_np.int32)
        tables[0, :len(seq.pages)] = seq.pages
        ctx_lens = _np.array([hit_tokens], dtype=_np.int32)
        chunk_lens = _np.array([len(suffix)], dtype=_np.int32)
        floors = _np.array([hit_tokens], dtype=_np.int32)
        inputs = (tokens, tables, ctx_lens, chunk_lens, floors)
        if self.bank is not None:
            inputs += (_np.array([seq.adapter_slot], dtype=_np.int32),
                       self.bank.flat_arrays())
        with self._run_lock:
            prog = self._programs.get(("chunk", t_bucket)) or \
                self._build(("chunk", t_bucket))
            next_tok, bad = self._dispatch(prog, inputs)
        return int(next_tok[0]), int(bad[0])

    def verify_step(self, seqs, chunks, k):
        """One speculative verify dispatch (serve/spec.py): judge each
        sequence's draft chunk (``chunks[i]``, 1..K+1 tokens starting
        at its last committed token) in a single program run.  Returns
        ``(y, bad)`` — ``y[i][j]`` is the target's argmax after
        ``chunks[i][:j+1]``, aligned with ``seqs``."""
        c = self.page_config
        bucket = self.decode_bucket(len(seqs))
        T = k + 1
        tokens = _np.zeros((bucket, T), dtype=_np.int32)
        tables = _np.full((bucket, c.pages_per_seq), self.pool.null_page,
                          dtype=_np.int32)
        ctx_lens = _np.zeros((bucket,), dtype=_np.int32)
        chunk_lens = _np.zeros((bucket,), dtype=_np.int32)
        floors = _np.zeros((bucket,), dtype=_np.int32)
        for i, (seq, ch) in enumerate(zip(seqs, chunks)):
            tokens[i, :len(ch)] = ch
            tables[i, :len(seq.pages)] = seq.pages
            ctx_lens[i] = seq.length
            chunk_lens[i] = len(ch)
            floors[i] = seq.prefix_len
        inputs = (tokens, tables, ctx_lens, chunk_lens, floors)
        if self.bank is not None:
            aidx = _np.full((bucket,), -1, dtype=_np.int32)
            for i, seq in enumerate(seqs):
                aidx[i] = seq.adapter_slot
            inputs += (aidx, self.bank.flat_arrays())
        with self._run_lock:
            key = ("verify", (bucket, k))
            prog = self._programs.get(key) or self._build(key)
            y, bad = self._dispatch(prog, inputs)
        return y[:len(seqs)], bad[:len(seqs)]

    def decode_step(self, seqs):
        """One iteration over ``seqs`` (the live set or a bisected
        subset): each sequence's pending token is written at its next
        position and its next token sampled.  Returns aligned
        ``(next_tokens, nonfinite_counts)`` numpy arrays."""
        c = self.page_config
        bucket = self.decode_bucket(len(seqs))
        tokens = _np.zeros((bucket, 1), dtype=_np.int32)
        tables = _np.full((bucket, c.pages_per_seq), self.pool.null_page,
                          dtype=_np.int32)
        ctx_lens = _np.zeros((bucket,), dtype=_np.int32)
        chunk_lens = _np.ones((bucket,), dtype=_np.int32)
        for i, seq in enumerate(seqs):
            tokens[i, 0] = seq.last_token
            tables[i, :len(seq.pages)] = seq.pages
            ctx_lens[i] = seq.length
        inputs = (tokens, tables, ctx_lens, chunk_lens)
        if self.bank is not None:
            # padding rows stay -1 (base weights, zero delta): a mixed
            # 8-tenant batch is ONE dispatch of the bucket's program
            aidx = _np.full((bucket,), -1, dtype=_np.int32)
            for i, seq in enumerate(seqs):
                aidx[i] = seq.adapter_slot
            inputs += (aidx, self.bank.flat_arrays())
        with self._run_lock:
            prog = self._programs.get(("decode", bucket)) or \
                self._build(("decode", bucket))
            next_tok, bad = self._dispatch(prog, inputs)
        return next_tok[:len(seqs)], bad[:len(seqs)]

    def stats(self):
        return {
            "step": self.step, "root": self.root, "warmed": self._warmed,
            "model": type(self._block).__name__,
            "geometry": {"num_layers": self._block.num_layers,
                         "num_kv_heads": self._block.num_kv_heads,
                         "head_dim": self._block.head_dim,
                         "vocab_size": self._block.vocab_size},
            "pool": self.pool.stats(),
            "buckets": self.provenance(),
            "config": self.config.as_dict(),
            "cache": self.cache.stats() if self.cache is not None
            else {"enabled": False},
            "spec": self.spec.stats() if self.spec is not None
            else {"enabled": False},
            "bank": self.bank.stats() if self.bank is not None
            else {"enabled": False},
        }


class DecodeScheduler:
    """The continuous-batching loop (module doc).

    One daemon thread owns the model, the pool and every live
    sequence; admission (``submit``) only validates, reserves nothing,
    and enqueues — page reservation, prefill, decode, eviction and
    reclamation all happen on the loop so there is exactly one writer
    of serving state.  ``breakers`` (a ``breaker.BreakerBoard``, shared
    with the owning Server) quarantines repeatedly-failing decode /
    prefill buckets: blocked decode buckets are skipped by the bucket
    chooser (a smaller non-blocked bucket chunks the live set), and a
    blocked prefill bucket fast-rejects its admissions."""

    def __init__(self, runner, breakers=None, start=True, tenant=None):
        self._runner = runner
        self.config = runner.config
        self._breakers = breakers
        # mx.tenant plane (registry.TenantPlane): WFQ admission order,
        # per-tenant quota ledger, adapter bank.  Defaults to the
        # runner's plane so Server wiring stays one argument.
        self._tenant = tenant if tenant is not None \
            else getattr(runner, "tenant", None)
        self._cond = threading.Condition()
        self._waiting = deque()
        self._live = {}               # sid -> _Seq, insertion-ordered
        self._next_sid = 0
        self._closed = False
        self._drain = True
        self._pending_runner = None
        self.steps = 0
        self.admitted_total = 0
        self.evictions = {}
        self._recent = deque(maxlen=64)
        self._thread = None
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        import weakref

        self._thread = threading.Thread(
            target=self._run, args=(weakref.ref(self),), daemon=True,
            name="mx-serve-decode")
        self._thread.start()

    @property
    def alive(self):
        return self._thread is not None and self._thread.is_alive()

    @property
    def runner(self):
        return self._runner

    def stop(self, drain=True, timeout=None):
        """Stop intake; with ``drain`` (default) live sequences finish
        their generation and waiting ones are admitted/served first,
        otherwise everything fails fast with ``ServerClosed``."""
        with self._cond:
            self._closed = True
            self._drain = bool(drain)
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        return not self.alive

    def swap(self, new_runner):
        """Repoint decoding at a new runner/checkpoint.  Live sequences
        FINISH on the old runner (their KV state is its pool); new
        admissions wait and start on the new one once the old batch
        drains.  Returns immediately."""
        if not isinstance(new_runner, DecodeRunner):
            raise ValueError("swap needs a DecodeRunner")
        with self._cond:
            if self._closed:
                raise ServerClosed("decode scheduler is shut down")
            self._pending_runner = new_runner
            self._cond.notify_all()

    # -- admission ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               timeout_ms=None, request_id=None, on_token=None,
               tenant=None):
        """Enqueue one generation request; returns its
        ``concurrent.futures.Future``.  Validation is all up-front and
        fast: static shape limits raise ``DecodeError``, an impossible
        page reservation raises ``PagePoolExhausted``, a full waiting
        queue rejects with ``ServerOverloaded``, a quarantined prefill
        bucket with ``BucketQuarantined`` — a request that enqueues can
        always be admitted once capacity frees.  ``tenant`` bills the
        request to a registered tenant (mx.tenant): its quota gates
        here (``TenantQuotaExceeded`` -> per-tenant 503), its WFQ
        weight orders admission, its adapter applies in-program."""
        cfg = self.config
        prompt = [int(t) for t in (prompt or ())]
        if not prompt:
            raise DecodeError("decode needs a non-empty prompt "
                              "(list of int token ids)")
        vocab = self._runner.block.vocab_size
        if min(prompt) < 0 or max(prompt) >= vocab:
            raise DecodeError("prompt token ids must be in [0, %d)"
                              % vocab)
        mnt = cfg.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        if mnt < 1:
            raise DecodeError("max_new_tokens must be >= 1")
        mnt = min(mnt, cfg.max_new_tokens)
        total = len(prompt) + mnt
        if total > cfg.max_context:
            raise DecodeError(
                "prompt (%d) + max_new_tokens (%d) exceeds "
                "max_context=%d" % (len(prompt), mnt, cfg.max_context))
        t_bucket = self._runner.prefill_bucket(len(prompt))
        need = self._runner.page_config.pages_for(total)
        if need > self._runner.pool.capacity:
            raise PagePoolExhausted(
                "request needs %d KV pages but the pool only has %d"
                % (need, self._runner.pool.capacity))
        if self._breakers is not None and \
                self._breakers.blocked(("prefill", t_bucket)):
            if telemetry.ENABLED:
                telemetry.SERVE_REQUESTS.labels(
                    result="quarantined").inc()
            raise self._breakers.quarantine_error(("prefill", t_bucket))
        plane = self._tenant
        if tenant is not None:
            if plane is None:
                raise DecodeError(
                    "request names tenant %r but this server has no "
                    "tenant plane (build with tenant=TenantPlane())"
                    % (tenant,))
            # a quarantined (NaN'ing) adapter fast-rejects ITS tenant's
            # submissions while the half-open probe cools — batch-mates
            # are untouched
            aclass = ("adapter", str(tenant))
            if self._breakers is not None and \
                    self._breakers.blocked(aclass):
                if telemetry.ENABLED:
                    telemetry.SERVE_REQUESTS.labels(
                        result="quarantined").inc()
                    telemetry.TENANT_REQUESTS.labels(
                        tenant=str(tenant), result="quarantined").inc()
                raise self._breakers.quarantine_error(aclass)
            from ..tenant.quota import TenantQuotaExceeded
            from ..tenant.registry import UnknownTenant

            try:
                plane.check_submit(tenant, need)
            except UnknownTenant as exc:
                raise DecodeError(str(exc))
            except TenantQuotaExceeded:
                if telemetry.ENABLED:
                    telemetry.SERVE_REQUESTS.labels(
                        result="rejected").inc()
                    telemetry.TENANT_REQUESTS.labels(
                        tenant=str(tenant), result="rejected").inc()
                raise
        timeout_ms = cfg.timeout_ms if timeout_ms is None else timeout_ms
        deadline = None if timeout_ms is None \
            else time.perf_counter() + float(timeout_ms) / 1e3
        req = DecodeRequest(
            prompt, mnt,
            eos_id=self._runner.eos_id if eos_id is None else eos_id,
            deadline=deadline, request_id=request_id, on_token=on_token,
            tenant=tenant)
        with self._cond:
            if self._closed:
                if tenant is not None:
                    plane.note_dequeue(tenant)
                raise ServerClosed("decode scheduler is shut down")
            if len(self._waiting) >= cfg.queue_depth:
                if tenant is not None:
                    plane.note_dequeue(tenant)
                if telemetry.ENABLED:
                    telemetry.SERVE_REQUESTS.labels(
                        result="rejected").inc()
                raise ServerOverloaded(
                    "decode admission queue full (%d waiting, depth=%d)"
                    % (len(self._waiting), cfg.queue_depth))
            self._waiting.append(req)
            if telemetry.ENABLED:
                telemetry.SERVE_DECODE_WAITING.set(len(self._waiting))
            self._cond.notify_all()
        return req.future

    # -- fleet disaggregation (mxnet_tpu/fleet/handoff.py) -------------------
    def submit_export(self, prompt, max_new_tokens=None, eos_id=None,
                      timeout_ms=None, request_id=None):
        """Prefill-only admission for a disaggregated PREFILL replica:
        the sequence runs its prompt, then its future resolves to the
        ``fleet.handoff`` state dict (pages + cursor + first token)
        instead of decoding — the decode happens on whichever replica
        imports the blob.  Validation mirrors ``submit`` but the page
        reservation is prompt-only (no generation happens here)."""
        cfg = self.config
        prompt = [int(t) for t in (prompt or ())]
        if not prompt:
            raise DecodeError("export needs a non-empty prompt")
        vocab = self._runner.block.vocab_size
        if min(prompt) < 0 or max(prompt) >= vocab:
            raise DecodeError("prompt token ids must be in [0, %d)"
                              % vocab)
        mnt = cfg.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        if mnt < 1:
            raise DecodeError("max_new_tokens must be >= 1")
        mnt = min(mnt, cfg.max_new_tokens)
        t_bucket = self._runner.prefill_bucket(len(prompt))
        need = self._runner.page_config.pages_for(len(prompt))
        if need > self._runner.pool.capacity:
            raise PagePoolExhausted(
                "export needs %d KV pages but the pool only has %d"
                % (need, self._runner.pool.capacity))
        if self._breakers is not None and \
                self._breakers.blocked(("prefill", t_bucket)):
            if telemetry.ENABLED:
                telemetry.SERVE_REQUESTS.labels(
                    result="quarantined").inc()
            raise self._breakers.quarantine_error(("prefill", t_bucket))
        timeout_ms = cfg.timeout_ms if timeout_ms is None else timeout_ms
        deadline = None if timeout_ms is None \
            else time.perf_counter() + float(timeout_ms) / 1e3
        req = DecodeRequest(
            prompt, mnt,
            eos_id=self._runner.eos_id if eos_id is None else eos_id,
            deadline=deadline, request_id=request_id, export_only=True)
        with self._cond:
            if self._closed:
                raise ServerClosed("decode scheduler is shut down")
            if len(self._waiting) >= cfg.queue_depth:
                if telemetry.ENABLED:
                    telemetry.SERVE_REQUESTS.labels(
                        result="rejected").inc()
                raise ServerOverloaded(
                    "decode admission queue full (%d waiting, depth=%d)"
                    % (len(self._waiting), cfg.queue_depth))
            self._waiting.append(req)
            if telemetry.ENABLED:
                telemetry.SERVE_DECODE_WAITING.set(len(self._waiting))
            self._cond.notify_all()
        return req.future

    def submit_handoff(self, state, timeout_ms=None, request_id=None,
                       on_token=None):
        """Import admission for a disaggregated DECODE replica: the
        PR 12 reservation math re-runs HERE against this pool — full
        worst case (``pages_for(length + max_new_tokens)``) reserved up
        front, geometry cross-checked — so an imported sequence carries
        exactly the admission guarantees of a local one (no mid-decode
        allocation failure, scrub guard over positions >= cursor).
        ``state`` is an unpacked ``fleet.handoff`` blob."""
        from ..fleet import handoff as _handoff

        cfg = self.config
        prompt = [int(t) for t in (state.get("prompt") or ())]
        if not prompt:
            raise DecodeError("handoff carries an empty prompt")
        vocab = self._runner.block.vocab_size
        first = int(state["first_token"])
        if min(prompt) < 0 or max(prompt) >= vocab or \
                not 0 <= first < vocab:
            raise DecodeError(
                "handoff token ids must be in [0, %d)" % vocab)
        mnt = int(state["max_new_tokens"])
        if mnt < 1:
            raise DecodeError("max_new_tokens must be >= 1")
        mnt = min(mnt, cfg.max_new_tokens)
        _handoff.validate_geometry(state, self._runner.page_config)
        total = int(state["length"]) + mnt
        if total > cfg.max_context:
            raise DecodeError(
                "handoff cursor (%d) + max_new_tokens (%d) exceeds "
                "max_context=%d" % (state["length"], mnt,
                                    cfg.max_context))
        need = self._runner.page_config.pages_for(total)
        if need > self._runner.pool.capacity:
            raise PagePoolExhausted(
                "handoff needs %d KV pages but the pool only has %d"
                % (need, self._runner.pool.capacity))
        timeout_ms = cfg.timeout_ms if timeout_ms is None else timeout_ms
        deadline = None if timeout_ms is None \
            else time.perf_counter() + float(timeout_ms) / 1e3
        eos = state.get("eos_id")
        req = DecodeRequest(
            prompt, mnt,
            eos_id=self._runner.eos_id if eos is None else eos,
            deadline=deadline,
            request_id=request_id if request_id is not None
            else state.get("request_id"),
            on_token=on_token, handoff=state)
        with self._cond:
            if self._closed:
                raise ServerClosed("decode scheduler is shut down")
            if len(self._waiting) >= cfg.queue_depth:
                if telemetry.ENABLED:
                    telemetry.SERVE_REQUESTS.labels(
                        result="rejected").inc()
                raise ServerOverloaded(
                    "decode admission queue full (%d waiting, depth=%d)"
                    % (len(self._waiting), cfg.queue_depth))
            self._waiting.append(req)
            if telemetry.ENABLED:
                telemetry.SERVE_DECODE_WAITING.set(len(self._waiting))
            self._cond.notify_all()
        return req.future

    # -- introspection ------------------------------------------------------
    def stats(self):
        with self._cond:
            waiting = len(self._waiting)
            live = [{"request_id": s.req.request_id,
                     "prompt_tokens": len(s.req.prompt),
                     "generated": len(s.tokens),
                     "max_new_tokens": s.req.max_new_tokens,
                     "length": s.length,
                     "pages": len(s.pages or ()),
                     "joined_step": s.joined_step}
                    for s in self._live.values()]
        board = {}
        if self._breakers is not None:
            board = {k: v for k, v in self._breakers.snapshot().items()
                     if k.startswith("('decode'") or
                     k.startswith("('prefill'") or
                     k.startswith("('spec'") or
                     k.startswith("('draft'") or
                     k.startswith("('adapter'")}
        return {
            "alive": self.alive,
            "waiting": waiting,
            "live": live,
            "steps": self.steps,
            "admitted": self.admitted_total,
            "evictions": dict(self.evictions),
            "runner": self._runner.stats(),
            "breakers": board,
            "recent": list(self._recent)[-16:],
        }

    def recent(self):
        return list(self._recent)

    def oldest_waiting_age(self):
        """Seconds the head-of-line waiting request has queued (0.0
        when empty) — the decode-plane half of the fleet router's
        queue-age load signal."""
        with self._cond:
            if not self._waiting:
                return 0.0
            return max(0.0,
                       time.perf_counter() - self._waiting[0].enqueued)

    # -- the loop -----------------------------------------------------------
    @staticmethod
    def _run(ref):
        """Thread body.  Holds the scheduler (and through it the
        runner + device-resident KV pool) only WEAKLY between
        iterations — a Server/scheduler dropped without shutdown()
        must become collectable, not be pinned forever by its own
        daemon thread (same contract as the vision Scheduler's
        weak runner ref)."""
        while True:
            sched = ref()
            if sched is None:
                return            # owner collected: wind down
            try:
                more = sched._loop_once()
            finally:
                del sched         # drop the strong ref before sleeping
            if not more:
                return

    def _loop_once(self):
        """One scheduling iteration; False means the loop must exit."""
        with self._cond:
            if self._closed:
                if not self._drain:
                    self._abort_locked()
                    return False
                if not self._waiting and not self._live:
                    return False
            if not self._waiting and not self._live:
                self._cond.wait(0.25)
                return True
        try:
            self._expire()
            self._maybe_install_runner()
            self._admit()
            if self._live:
                self._step()
            elif self._waiting:
                # waiting but nothing admissible yet (slots/pages held
                # by a draining swap, or breakers cooling): don't spin
                time.sleep(0.005)
        except BaseException:  # noqa: BLE001 - loop must survive
            trace.instant("serve_decode_loop_error", cat="serve")
            time.sleep(0.01)
        return True

    def _abort_locked(self):
        items, self._waiting = list(self._waiting), deque()
        live, self._live = list(self._live.values()), {}
        for req in items:
            if self._tenant is not None:
                self._tenant.note_dequeue(req.tenant)
            fail_request(req, ServerClosed(
                "server shut down before admission"), "cancelled")
            self._bump("cancelled")
        for seq in live:
            self._release(seq)
            fail_request(seq.req, ServerClosed(
                "server shut down mid-generation after %d token(s)"
                % len(seq.tokens)), "cancelled")
            self._bump("cancelled")
            self._record(seq, "cancelled")
        self._gauges()

    def _bump(self, reason):
        self.evictions[reason] = self.evictions.get(reason, 0) + 1
        if telemetry.ENABLED:
            telemetry.SERVE_DECODE_EVICTIONS.labels(reason=reason).inc()

    def _release(self, seq):
        runner = self._runner
        if seq.quota_pages is not None and self._tenant is not None:
            # return the tenant's quota share exactly once
            self._tenant.on_release(seq.tenant, seq.quota_pages)
            seq.quota_pages = None
        if seq.shared:
            # drop this sequence's references on its shared prefix
            # pages BEFORE releasing the private ledger — the pages
            # live in the pool's shared segment, not under the sid
            if runner.cache is not None:
                runner.cache.release(seq.shared)
            else:
                runner.pool.shared_unref(seq.shared)
            seq.shared = []
        if seq.pages is not None:
            runner.pool.release(seq.sid)
            seq.pages = None
        if runner.spec is not None and seq.dpages is not None:
            runner.spec.release(seq)

    def _record(self, seq, reason):
        self._recent.append({
            "request_id": seq.req.request_id,
            "joined_step": seq.joined_step,
            "left_step": self.steps,
            "prompt_tokens": len(seq.req.prompt),
            "generated": len(seq.tokens),
            "reason": reason,
        })

    def _gauges(self):
        if telemetry.ENABLED:
            telemetry.SERVE_DECODE_LIVE.set(len(self._live))
            with self._cond:
                telemetry.SERVE_DECODE_WAITING.set(len(self._waiting))
            pool = self._runner.pool
            telemetry.SERVE_KV_PAGES_IN_USE.set(pool.in_use)
            telemetry.SERVE_KV_PAGES_HIGH_WATER.set(pool.high_water)

    def _expire(self):
        now = time.perf_counter()
        with self._cond:
            keep = deque()
            for req in self._waiting:
                if req.expired(now):
                    if self._tenant is not None:
                        self._tenant.note_dequeue(req.tenant)
                    fail_request(req, RequestTimeout(
                        "deadline expired after %.1f ms waiting for "
                        "admission" % ((now - req.enqueued) * 1e3)),
                        "timeout")
                    self._bump("timeout")
                else:
                    keep.append(req)
            self._waiting = keep
        with self._cond:
            dead = [s for s, q in self._live.items()
                    if q.req.expired(now)]
        for sid in dead:
            with self._cond:
                seq = self._live.pop(sid)
            self._release(seq)
            fail_request(seq.req, RequestTimeout(
                "deadline expired mid-generation after %d token(s)"
                % len(seq.tokens)), "timeout")
            self._bump("timeout")
            self._record(seq, "timeout")
        self._gauges()

    def _maybe_install_runner(self):
        with self._cond:
            if self._pending_runner is None or self._live:
                return
            old, self._runner = self._runner, self._pending_runner
            self._pending_runner = None
            self.config = self._runner.config
        if old.cache is not None:
            old.cache.clear()     # trie refs were the last holders
        old.pool.check()          # every page must have come home
        if telemetry.ENABLED:
            telemetry.SERVE_SWAPS.inc()
        trace.instant("serve_decode_swap", cat="serve",
                      args={"step": self._runner.step})

    def _evict_poisoned(self, seqs):
        """mx.resilience poison drill: sequences whose request id the
        armed ``MXNET_FAULTS`` plan marks (``serve_poison@<rid>``) are
        evicted ALONE — pages reclaimed, batch-mates untouched."""
        out = []
        for seq in seqs:
            if _inject.poisoned(seq.req.request_id):
                _inject.record_firing("serve_poison",
                                      seq.req.request_id, consume=True)
                with self._cond:
                    self._live.pop(seq.sid, None)
                self._release(seq)
                exc = InjectedFault(
                    "injected poison request %s" % seq.req.request_id,
                    site="serve_poison")
                if telemetry.ENABLED:
                    telemetry.SERVE_POISON.inc()
                fail_request(seq.req, exc, "poisoned")
                self._bump("poisoned")
                self._record(seq, "poisoned")
            else:
                out.append(seq)
        return out

    def _pages_needed(self, req):
        """The reservation one request admits with: full worst case
        (prompt + generation) normally; prompt-only for an export
        (generation happens on the importing replica); imported cursor
        + generation for a handoff."""
        if req.export_only:
            total = len(req.prompt)
        elif req.handoff is not None:
            total = int(req.handoff["length"]) + req.max_new_tokens
        else:
            total = len(req.prompt) + req.max_new_tokens
        return self._runner.page_config.pages_for(total)

    def _admit(self):
        """Fill free slots from the waiting queue: reserve the whole
        worst-case page count, prefill through the bucket path (or
        install a handed-off prefill), emit the first token.  Stops at
        the first request the pool cannot hold yet.  Admission order is
        arrival order (FIFO) without a tenant plane; with one, the WFQ
        picker chooses the backlogged tenant with the smallest virtual
        finish time whose quota admits — a tenant at quota is SKIPPED,
        never a head-of-line block."""
        plane = self._tenant
        while len(self._live) < self.config.max_live:
            with self._cond:
                if not self._waiting or self._pending_runner is not None:
                    return
                if plane is not None:
                    req = plane.select(self._waiting, self._pages_needed)
                    if req is None:
                        return    # every backlogged tenant is at quota
                else:
                    req = self._waiting[0]
                pool = self._runner.pool
                cache = self._runner.cache
                need = self._pages_needed(req)
                if cache is not None and not req.export_only and \
                        req.handoff is None:
                    # admission charges only the UNCACHED suffix: the
                    # matched prefix pages are shared, not reserved
                    _, hit_tok = cache.match(req.prompt)
                    need -= hit_tok // self.config.page_size
                if need > pool.capacity:
                    # submit() validated against the runner of its day;
                    # a hot swap may have shrunk the pool since.  Fail
                    # the request rather than head-of-line-block the
                    # queue waiting for pages that can never exist
                    self._waiting.remove(req)
                    if plane is not None:
                        plane.note_dequeue(req.tenant)
                    fail_request(req, PagePoolExhausted(
                        "request needs %d KV pages but the (swapped) "
                        "pool only has %d" % (need, pool.capacity)),
                        "error")
                    self._bump("error")
                    continue
                if not pool.can_alloc(need):
                    # pool pressure: reclaim cold (LRU) cached
                    # prefixes before giving up on this iteration
                    if cache is None or cache.evict(need) == 0 or \
                            not pool.can_alloc(need):
                        return    # wait for evictions to free pages
                self._waiting.remove(req)
                if plane is not None:
                    plane.note_dequeue(req.tenant)
                if telemetry.ENABLED:
                    telemetry.SERVE_DECODE_WAITING.set(len(self._waiting))
                sid = self._next_sid
                self._next_sid += 1
            seq = _Seq(req, sid)
            if _inject.poisoned(req.request_id):
                self._evict_poisoned([seq])
                continue
            if req.handoff is not None:
                self._admit_handoff(seq, need)
                continue
            hit_tok = 0
            if cache is not None and not req.export_only:
                try:
                    _inject.fire("serve_cache", seq=req.request_id)
                except (InjectedFault, InjectedIOError):
                    # corrupt/evict-under-reader drill: the matched
                    # prefix is declared poisoned — drop that subtree
                    # (live readers keep their refs) and prefill cold
                    cache.invalidate(req.prompt)
                shared, hit_tok, cls = cache.acquire(req.prompt)
                seq.cache_class = cls
                seq.prefix_len = hit_tok
                seq.shared = list(shared)
            try:
                t_bucket = self._runner.prefill_bucket(
                    len(req.prompt) - hit_tok)
            except DecodeError as exc:
                # same swap skew: the new runner's bucket table may not
                # cover a prompt the old one admitted — resolve the
                # future, never drop it on the floor
                self._release(seq)
                fail_request(req, exc, "error")
                self._bump("error")
                continue
            bclass = ("prefill", t_bucket)
            if self._breakers is not None and \
                    not self._breakers.allow(bclass):
                self._release(seq)
                fail_request(req, self._breakers.quarantine_error(bclass),
                             "quarantined")
                self._bump("quarantined")
                continue
            if req.tenant is not None and plane is not None:
                # per-adapter breaker gate (half-open probes admit one)
                # + the bank slot the sequence will decode with
                seq.adapter_slot = plane.slot_for(req.tenant)
                aclass = ("adapter", req.tenant)
                if seq.adapter_slot >= 0 and self._breakers is not None \
                        and not self._breakers.allow(aclass):
                    self._release(seq)
                    fail_request(req,
                                 self._breakers.quarantine_error(aclass),
                                 "quarantined")
                    self._bump("quarantined")
                    if telemetry.ENABLED:
                        telemetry.TENANT_REQUESTS.labels(
                            tenant=req.tenant,
                            result="quarantined").inc()
                    continue
            try:
                own = self._pages_needed(req) - len(seq.shared)
                seq.pages = list(seq.shared) + \
                    list(self._runner.pool.alloc(sid, own))
            except PagePoolExhausted as exc:
                # only reachable when the serve_cache drill invalidated
                # a prefix between reservation check and allocation
                self._release(seq)
                fail_request(req, exc, "error")
                self._bump("error")
                continue
            if plane is not None:
                # WFQ charge + quota ledger reservation (mirrors the
                # pool pages this sid really holds)
                plane.admit_granted(
                    req.tenant,
                    plane.cost_of(len(req.prompt), req.max_new_tokens),
                    own)
                if req.tenant is not None:
                    seq.quota_pages = own
            t0 = time.perf_counter()
            blabel = ("chunk:t%d" if hit_tok else "prefill:t%d") \
                % t_bucket
            try:
                with trace.use(req.trace), \
                        trace.span("serve_decode_prefill", hist=False,
                                   cat="serve",
                                   args={"bucket": blabel,
                                         "request_id": req.request_id}):
                    if hit_tok:
                        tok, bad = self._runner.prefill_cached(
                            seq, hit_tok)
                    else:
                        tok, bad = self._runner.prefill(seq)
            except BaseException as exc:  # noqa: BLE001 - per-request
                self._release(seq)
                if self._breakers is not None:
                    self._breakers.failure(bclass)
                if getattr(exc, "pool_lost", False):
                    self._evict_all_live(exc)
                fail_request(req, exc, "error")
                self._bump("error")
                continue
            if self._breakers is not None:
                self._breakers.success(bclass)
            seq.length = len(req.prompt)
            seq.joined_step = self.steps
            seq.t_prefill = time.perf_counter() - t0
            if telemetry.ENABLED:
                telemetry.SERVE_DECODE_PREFILLS.inc()
                telemetry.SERVE_DECODE_PREFILL_TOKENS.inc(
                    len(req.prompt) - hit_tok)
            with self._cond:
                self._live[sid] = seq
            self.admitted_total += 1
            if bad:
                self._evict_nonfinite(seq, bad)
                continue
            if cache is not None and not req.export_only:
                # only a HEALTHY prefill populates the trie; newly
                # adopted full-prompt pages move to the shared segment
                # with refcount 2 (trie + this reader)
                adopted = cache.insert(req.prompt, sid, seq.pages,
                                       hit_tok)
                if adopted:
                    seq.shared = list(
                        seq.pages[:len(seq.shared) + adopted])
            if req.export_only:
                self._finish_export(seq, int(tok))
                self._gauges()
                continue
            self._emit(seq, int(tok), t0)
            self._finish_if_done(seq)
            self._gauges()

    def _admit_handoff(self, seq, need):
        """Admit one imported sequence: reserve the (already
        re-validated) worst case, splice the blob's pages into the
        reservation, and emit the prefill replica's first token so the
        client stream is byte-identical to a colocated run."""
        from ..fleet import handoff as _handoff

        req = seq.req
        state = req.handoff
        seq.pages = self._runner.pool.alloc(seq.sid, need)
        t0 = time.perf_counter()
        try:
            with trace.use(req.trace), \
                    trace.span("serve_decode_handoff_install", hist=False,
                               cat="serve",
                               args={"pages": int(state["pages"]),
                                     "request_id": req.request_id}):
                _handoff.install_seq(self._runner, seq, state)
        except BaseException as exc:  # noqa: BLE001 - per-request
            self._release(seq)
            if getattr(exc, "pool_lost", False):
                self._evict_all_live(exc)
            fail_request(req, exc, "error")
            self._bump("error")
            return
        seq.length = int(state["length"])
        seq.joined_step = self.steps
        seq.t_prefill = time.perf_counter() - t0
        with self._cond:
            self._live[seq.sid] = seq
        self.admitted_total += 1
        self._emit(seq, int(state["first_token"]), t0)
        self._finish_if_done(seq)
        self._gauges()

    def _finish_export(self, seq, first_token):
        """Resolve an export_only sequence: snapshot its pages +
        cursor + first token as the handoff state, reclaim the pages,
        resolve the future with the state dict."""
        from ..fleet import handoff as _handoff

        with self._cond:
            self._live.pop(seq.sid, None)
        try:
            state = _handoff.export_seq(self._runner, seq, first_token)
        except BaseException as exc:  # noqa: BLE001 - per-request
            self._release(seq)
            fail_request(seq.req, exc, "error")
            self._bump("error")
            self._record(seq, "error")
            return
        self._release(seq)
        self._bump("exported")
        self._record(seq, "exported")
        done_t = time.perf_counter()
        try:
            seq.req.future.set_result(state)
        except InvalidStateError:
            return
        if telemetry.ENABLED:
            telemetry.SERVE_REQUESTS.labels(result="ok").inc()
            telemetry.SERVE_REQUEST_SECONDS.observe(
                done_t - seq.req.enqueued)

    def _evict_nonfinite(self, seq, bad):
        """The per-token output guard tripped: this sequence's logits
        went NaN/Inf.  Greedy-sampling a NaN row returns garbage, so
        the sequence fails alone instead of streaming poison."""
        with self._cond:
            self._live.pop(seq.sid, None)
        self._release(seq)
        if seq.tenant is not None and seq.adapter_slot >= 0:
            # attribute the poison to the tenant's ADAPTER: repeated
            # trips open the ("adapter", tenant) breaker and quarantine
            # that slot's traffic alone — batch-mates keep decoding
            if self._breakers is not None:
                self._breakers.failure(("adapter", seq.tenant))
            if telemetry.ENABLED:
                telemetry.TENANT_ADAPTER_POISON.labels(
                    tenant=seq.tenant).inc()
        if telemetry.ENABLED:
            telemetry.SERVE_NONFINITE_OUTPUTS.inc(int(bad))
            telemetry.SERVE_NONFINITE_BATCHES.inc()
            telemetry.SERVE_POISON.inc()
        trace.instant("serve_decode_nonfinite", cat="serve",
                      ctx=seq.req.trace,
                      args={"request_id": seq.req.request_id,
                            "elements": int(bad)})
        fail_request(seq.req, DecodeError(
            "sequence evicted: %d nonfinite logit element(s) at token "
            "%d (output guard)" % (int(bad), len(seq.tokens))),
            "poisoned")
        self._bump("poisoned")
        self._record(seq, "nonfinite")

    def _evict_all_live(self, exc):
        """KV storage was lost (donated pool consumed by a failed
        dispatch): no sequence's context survives."""
        with self._cond:
            doomed, self._live = list(self._live.values()), {}
        for seq in doomed:
            self._release(seq)
            fail_request(seq.req, exc, "error")
            self._bump("error")
            self._record(seq, "pool_lost")
        if self._runner.cache is not None:
            # the replacement pool arrays are zeros: every cached
            # prefix's content is gone with the storage
            self._runner.cache.clear()
        self._gauges()

    def _emit(self, seq, token, t_start):
        """One generated token: bookkeeping, telemetry, the per-token
        trace span on the request's own trace id, and the streaming
        callback."""
        now = time.perf_counter()
        seq.tokens.append(token)
        seq.last_token = token
        if seq.first_token_t is None:
            seq.first_token_t = now
            if telemetry.ENABLED:
                telemetry.SERVE_DECODE_TTFT_SECONDS.labels(
                    cache=seq.cache_class or "miss").observe(
                    now - seq.req.enqueued)
                if seq.tenant is not None:
                    telemetry.TENANT_TTFT_SECONDS.labels(
                        tenant=seq.tenant).observe(
                        now - seq.req.enqueued)
        if telemetry.ENABLED:
            telemetry.SERVE_DECODE_TOKENS.inc()
            if seq.tenant is not None:
                telemetry.TENANT_TOKENS.labels(tenant=seq.tenant).inc()
        if seq.tenant is not None and self._tenant is not None:
            self._tenant.note_tokens(seq.tenant)
        if trace.ENABLED and seq.req.trace is not None:
            trace.record_span(
                "serve_decode_token", t_start, now - t_start,
                ctx=seq.req.trace, cat="serve",
                args={"index": len(seq.tokens) - 1, "token": token,
                      "request_id": seq.req.request_id})
        cb = seq.req.on_token
        if cb is not None:
            try:
                cb(token, len(seq.tokens) - 1)
            except Exception:     # a sick consumer must not stall decode
                seq.req.on_token = None

    def _finish_if_done(self, seq):
        reason = seq.done_reason
        if reason is None:
            return False
        with self._cond:
            self._live.pop(seq.sid, None)
        self._release(seq)
        if seq.tenant is not None and seq.adapter_slot >= 0 and \
                self._breakers is not None:
            # a healthy adapter-applied completion closes the breaker's
            # failure window (and recovers a half-open quarantine)
            self._breakers.success(("adapter", seq.tenant))
        self._bump("finished")
        self._record(seq, reason)
        done_t = time.perf_counter()
        try:
            seq.req.future.set_result(
                {"tokens": list(seq.tokens), "finish_reason": reason})
        except InvalidStateError:
            return True
        if telemetry.ENABLED:
            telemetry.SERVE_REQUESTS.labels(result="ok").inc()
            if seq.tenant is not None:
                telemetry.TENANT_REQUESTS.labels(
                    tenant=seq.tenant, result="ok").inc()
            telemetry.SERVE_REQUEST_SECONDS.observe(
                done_t - seq.req.enqueued)
        if trace.ENABLED and seq.req.trace is not None:
            trace.record_span(
                "serve_request", seq.req.enqueued,
                done_t - seq.req.enqueued, ctx=seq.req.trace, root=True,
                cat="serve",
                args={"result": "ok", "request_id": seq.req.request_id,
                      "tokens": len(seq.tokens),
                      "finish_reason": reason})
        return True

    def _pick_bucket(self, n):
        """Smallest non-quarantined decode bucket covering ``n`` live
        sequences; falls back to the largest non-blocked smaller bucket
        (the live set steps in chunks while a bucket cools down).
        Returns None when every bucket is quarantined."""
        blocked = (lambda b: self._breakers is not None and
                   self._breakers.blocked(("decode", b)))
        for b in self.config.batch_sizes:
            if b >= n and not blocked(b):
                return b
        for b in reversed(self.config.batch_sizes):
            if b <= n and not blocked(b):
                return b
        return None

    def _step(self):
        """One continuous-batching iteration over the live set:
        speculative sequences advance K-at-a-time through the spec
        plane, everything else (and every fallback) through the
        normal one-token decode path."""
        live = self._evict_poisoned(list(self._live.values()))
        if not live:
            self._gauges()
            return
        spec = self._runner.spec
        if spec is not None:
            live = self._spec_round(live, spec)
        if live:
            self._step_normal(live)
        else:
            self._gauges()

    def _spec_round(self, live, spec):
        """Drive one plane round over the speculative slice of the
        live set; emits accepted tokens and returns the slice to step
        normally this iteration."""
        for seq in live:
            if seq.spec is None:
                # first sight of this sequence: offer it to the plane
                # (attach failure just leaves it decoding normally)
                if seq.req.export_only:
                    seq.spec = False
                else:
                    spec.attach(seq)
        normal = [s for s in live if not s.spec]
        cand = [s for s in live if s.spec]
        if not cand:
            return normal
        t0 = time.perf_counter()
        try:
            results, fallen = spec.round(cand, self._breakers)
        except BaseException as exc:  # noqa: BLE001 - classified
            if getattr(exc, "pool_lost", False):
                self._evict_all_live(exc)
                return []
            trace.instant("serve_spec_round_error", cat="serve")
            return normal + cand
        if results:
            self.steps += 1
            if telemetry.ENABLED:
                telemetry.SERVE_DECODE_STEPS.inc()
        for seq, emitted, bad in results:
            if bad:
                self._evict_nonfinite(seq, bad)
                continue
            for tok in emitted:
                seq.length += 1
                self._emit(seq, int(tok), t0)
                if self._finish_if_done(seq):
                    break
        self._gauges()
        return normal + fallen

    def _step_normal(self, live):
        bucket = self._pick_bucket(len(live))
        if bucket is None:
            time.sleep(0.005)     # every decode bucket cooling down
            return
        seqs = live[:bucket]
        bclass = ("decode", bucket)
        if self._breakers is not None and not self._breakers.allow(bclass):
            time.sleep(0.005)
            return
        t0 = time.perf_counter()
        head = seqs[0]
        try:
            _inject.fire("serve_dispatch")
        except (InjectedFault, InjectedIOError):
            # a transient injected dispatch fault: one breaker strike,
            # nobody evicted — sequences retry next iteration
            if self._breakers is not None:
                self._breakers.failure(bclass)
            return
        with trace.use(head.req.trace), \
                trace.span("serve_decode_step", hist=False, cat="serve",
                           args={"bucket": "decode:b%d" % bucket,
                                 "live": len(seqs)}), \
                trace.watchdog.watch("serve_dispatch"):
            pairs = self._step_split(seqs)
        self.steps += 1
        dt = time.perf_counter() - t0
        if telemetry.ENABLED:
            telemetry.SERVE_DECODE_STEPS.inc()
            telemetry.SERVE_DECODE_BATCH.observe(len(seqs))
            telemetry.SERVE_DECODE_TOKEN_SECONDS.observe(dt)
        failed = [p for p in pairs if p[3] is not None]
        if self._breakers is not None:
            (self._breakers.failure if failed
             else self._breakers.success)(bclass)
        any_ok = any(p[3] is None for p in pairs)
        pool_lost = next((p[3] for p in pairs
                          if getattr(p[3], "pool_lost", False)), None)
        if pool_lost is not None:
            self._evict_all_live(pool_lost)
            return
        for seq, tok, bad, exc, isolated in pairs:
            if exc is not None:
                poisoned = isolated and any_ok
                with self._cond:
                    self._live.pop(seq.sid, None)
                self._release(seq)
                if poisoned and telemetry.ENABLED:
                    telemetry.SERVE_POISON.inc()
                fail_request(seq.req, exc,
                             "poisoned" if poisoned else "error")
                self._bump("poisoned" if poisoned else "error")
                self._record(seq, "poisoned" if poisoned else "error")
                continue
            if bad:
                self._evict_nonfinite(seq, bad)
                continue
            seq.length += 1
            self._emit(seq, int(tok), t0)
            self._finish_if_done(seq)
        if len(seqs) < len(self._live):
            # chunked iteration (a larger bucket is cooling down):
            # rotate the just-stepped sequences behind the un-stepped
            # tail so every live sequence keeps making progress —
            # without this, live[:bucket] would starve the tail for
            # the whole breaker cooldown
            with self._cond:
                for seq in seqs:
                    if seq.sid in self._live:
                        self._live[seq.sid] = self._live.pop(seq.sid)
        self._gauges()

    def _step_split(self, seqs, depth=0):
        """Run one decode iteration for ``seqs``; on failure retry
        bisected down to single sequences so a poisoned sequence fails
        alone.  Returns ``[(seq, token, bad, exc, isolated)]``.
        Re-execution of a half is safe: a decode step writes each
        sequence's K/V at the same (page, slot) address it would have
        written the first time (idempotent), and sampling is greedy."""
        try:
            toks, bads = self._runner.decode_step(seqs)
        except BaseException as exc:  # noqa: BLE001 - contained
            if getattr(exc, "pool_lost", False) or len(seqs) == 1:
                isolated = depth > 0 or \
                    getattr(exc, "site", None) == "serve_poison"
                return [(seqs[0], None, None, exc, isolated)]
            if telemetry.ENABLED:
                telemetry.SERVE_BISECT_SPLITS.inc()
            trace.instant("serve_decode_bisect", cat="serve",
                          args={"sequences": len(seqs), "depth": depth,
                                "error": type(exc).__name__})
            mid = len(seqs) // 2
            return self._step_split(seqs[:mid], depth + 1) + \
                self._step_split(seqs[mid:], depth + 1)
        return [(s, int(toks[i]), int(bads[i]), None, False)
                for i, s in enumerate(seqs)]


# ---------------------------------------------------------------------------
# TinyDecoder — the reference decoder model (contract documentation)
# ---------------------------------------------------------------------------

from ..gluon import nn as _nn  # noqa: E402
from ..gluon.block import HybridBlock as _HybridBlock  # noqa: E402


class TinyDecoder(_HybridBlock):
    """A small, real transformer decoder implementing the decode-path
    model contract (module doc): pre-norm-free 2-layer MHA + MLP,
    sinusoidal absolute positions, causal chunk attention over a
    gathered paged context.  Reference model for tests / the smoke
    drill / the bench row — and executable documentation for bringing
    a real decoder onto ``mx.serve.decode``."""

    def __init__(self, vocab_size=64, num_layers=2, num_heads=2,
                 head_dim=8, hidden=None, eos_id=None, **kwargs):
        super().__init__(**kwargs)
        self.vocab_size = int(vocab_size)
        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.eos_id = eos_id
        units = self.num_kv_heads * self.head_dim
        self.units = units
        hidden = hidden or units * 2
        self.embed = _nn.Embedding(self.vocab_size, units)
        for layer in range(self.num_layers):
            for name in ("q", "k", "v", "o"):
                setattr(self, "%s%d" % (name, layer),
                        _nn.Dense(units, flatten=False, in_units=units))
            setattr(self, "up%d" % layer,
                    _nn.Dense(hidden, flatten=False, in_units=units))
            setattr(self, "down%d" % layer,
                    _nn.Dense(units, flatten=False, in_units=hidden))
        self.unembed = _nn.Dense(self.vocab_size, flatten=False,
                                 in_units=units)

    def _positional(self, positions):
        """Sinusoidal encoding of absolute positions [B, T] ->
        [B, T, units] (even dims sin, odd dims cos)."""
        from .. import ndarray as nd

        half = self.units // 2
        inv = nd.array(_np.asarray(
            1.0 / (10000.0 ** (_np.arange(half) / max(1, half))),
            dtype="float32"))
        ang = positions.expand_dims(2) * inv.reshape((1, 1, half))
        return nd.concat(nd.sin(ang), nd.cos(ang), dim=2)

    def forward(self, tokens, k_ctx, v_ctx, ctx_lengths, chunk_lengths):
        from .. import ndarray as nd

        B, T = tokens.shape
        S = k_ctx.shape[2]
        H, Dh, C = self.num_kv_heads, self.head_dim, self.units
        ctx_f = ctx_lengths.astype("float32").expand_dims(1)     # [B,1]
        steps = nd.arange(T, dtype="float32").expand_dims(0)     # [1,T]
        q_pos = ctx_f + steps                                    # [B,T]
        x = self.embed(tokens) + self._positional(q_pos)

        # one [B, T, S+T] additive attention bias shared by all layers:
        # context keys are valid while their position < ctx_length;
        # chunk keys are causal (key j attends-from query i when j <= i
        # — queries past chunk_length produce garbage that is never
        # read: their K/V scatter is dropped and the last-logit
        # selector picks index chunk_length-1)
        key_ctx_pos = nd.arange(S, dtype="float32").expand_dims(0)
        ctx_valid = (key_ctx_pos < ctx_f).astype("float32")       # [B,S]
        # invalid context keys take position +1e9 so they FAIL the
        # causal test below (key_pos <= q_pos) and are masked out; a
        # negative sentinel would pass it and dilute every softmax
        # with the scrubbed zero-K/V tail
        key_pos = nd.concat(
            ctx_valid * key_ctx_pos + (1.0 - ctx_valid) * 1e9,
            ctx_f + steps, dim=1) if S else (ctx_f + steps)       # [B,S+T]
        causal = (key_pos.expand_dims(1) <=
                  q_pos.expand_dims(2)).astype("float32")    # [B,T,S+T]
        bias = (1.0 - causal) * -1e9

        k_chunks, v_chunks = [], []
        for layer in range(self.num_layers):
            q = getattr(self, "q%d" % layer)(x).reshape((B, T, H, Dh))
            k = getattr(self, "k%d" % layer)(x).reshape((B, T, H, Dh))
            v = getattr(self, "v%d" % layer)(x).reshape((B, T, H, Dh))
            k_chunks.append(k.expand_dims(2))
            v_chunks.append(v.expand_dims(2))
            k_all = nd.concat(k_ctx[:, layer], k, dim=1) if S else k
            v_all = nd.concat(v_ctx[:, layer], v, dim=1) if S else v
            q2 = q.transpose((0, 2, 1, 3)).reshape((B * H, T, Dh))
            k2 = k_all.transpose((0, 2, 1, 3)).reshape((B * H, S + T, Dh))
            v2 = v_all.transpose((0, 2, 1, 3)).reshape((B * H, S + T, Dh))
            scores = nd.batch_dot(q2, k2, transpose_b=True) \
                / float(_np.sqrt(Dh))
            scores = (scores.reshape((B, H, T, S + T)) +
                      bias.expand_dims(1)).reshape((B * H, T, S + T))
            probs = nd.softmax(scores, axis=-1)
            att = nd.batch_dot(probs, v2).reshape((B, H, T, Dh)) \
                .transpose((0, 2, 1, 3)).reshape((B, T, C))
            x = x + getattr(self, "o%d" % layer)(att)
            x = x + getattr(self, "down%d" % layer)(
                nd.relu(getattr(self, "up%d" % layer)(x)))

        logits = self.unembed(x)                          # [B, T, V]
        sel = nd.one_hot((chunk_lengths - 1).astype("int32"), T) \
            .astype("float32")                            # [B, T]
        last = nd.sum(logits * sel.expand_dims(2), axis=1)  # [B, V]
        k_new = nd.concat(*k_chunks, dim=2) if self.num_layers > 1 \
            else k_chunks[0]
        v_new = nd.concat(*v_chunks, dim=2) if self.num_layers > 1 \
            else v_chunks[0]
        return last, k_new, v_new
