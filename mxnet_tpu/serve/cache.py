"""mx.serve.cache — radix prefix cache over PagePool pages.

Production decode traffic is massively redundant: shared system
prompts, multi-turn sessions and agent loops replay the same prompt
prefix thousands of times, and PR 12's decode plane prefills every
copy from scratch.  This module makes identical prefixes prefill ONCE
per replica: a radix trie keyed by **page-aligned token blocks**
(exactly ``page_size`` tokens per edge) whose nodes hold immutable
``PagePool`` pages in the pool's shared refcounted segment.

Design invariants:

- **Page-granular sharing.**  Only whole pages of prompt are ever
  cached, so a cache hit's suffix always begins on a page boundary
  and the hitting sequence's *writes* (suffix prefill + every decode
  step) land exclusively in its own private pages.  Shared pages are
  additionally write-protected in-program: the chunk/verify programs
  mask scatter positions below the sequence's ``prefix_len`` floor
  (the PR 12 scrub-guard discipline extended to copy-on-write).
- **Copy-on-write fork.**  Two sessions diverging mid-prefix simply
  match fewer blocks; the divergent tail is prefilled into private
  pages.  No shared page is ever mutated, so a fork costs only the
  uncached suffix.
- **Exact accounting.**  Pages enter the trie by *adoption* — moved
  out of the prefilling sequence's ledger into the pool's shared
  segment with refcount ``trie + readers`` — and leave by LRU
  eviction (``shared_unref``).  A page returns to the free list only
  at refcount 0, so an evicted prefix never yanks storage out from
  under a live reader, ``PagePool.check()`` still audits
  ``free + owned + shared == capacity``, and over-release raises.
- **Admission charges the suffix.**  The scheduler reserves
  ``pages_for(total) - matched_pages`` at admission, so a hot prefix
  multiplies effective pool capacity; under pool pressure
  ``evict()`` reclaims cold (LRU-by-last-hit) leaf prefixes.

The cache is single-writer (the decode loop thread); the lock exists
for cross-thread readers (``stats()`` / ``summary()`` from the HTTP
plane and the fleet Registrar's load digest).
"""
from __future__ import annotations

import hashlib
import threading

from .. import telemetry

__all__ = ["PrefixCache", "prefix_digest"]


def prefix_digest(tokens):
    """Stable short digest of a token block — the currency of fleet
    prefix affinity: replicas publish the digests of their trie root
    blocks in the load digest, and the Router hashes an incoming
    prompt's first block with the same function to find a replica
    already holding the prefix."""
    raw = ",".join(str(int(t)) for t in tokens).encode("ascii")
    return hashlib.sha1(raw).hexdigest()[:12]


class _Node:
    __slots__ = ("block", "page", "children", "last_hit")

    def __init__(self, block, page, clock):
        self.block = block          # tuple of page_size token ids
        self.page = int(page)       # shared PagePool page id
        self.children = {}          # block -> _Node
        self.last_hit = clock


class PrefixCache:
    """Radix trie of page-aligned prompt blocks (module doc)."""

    def __init__(self, pool):
        self.pool = pool
        self.page_size = pool.config.page_size
        self._root = {}             # block -> _Node
        self._lock = threading.Lock()
        self._clock = 0             # logical LRU clock (bumped per hit)
        self.hits = 0
        self.partials = 0
        self.misses = 0
        self.hit_tokens_total = 0
        self.evictions = 0
        self.inserted_pages = 0

    # -- internals ----------------------------------------------------------
    def _blocks(self, prompt):
        """The cacheable blocks of ``prompt``: whole pages only, and
        never the page containing the FINAL prompt token — the suffix
        prefill needs at least one token to produce the first output
        logit, and capping at ``len(prompt) - 1`` also keeps every
        write a hitting sequence performs off the shared pages."""
        ps = self.page_size
        n = max(0, (len(prompt) - 1) // ps)
        return [tuple(prompt[i * ps:(i + 1) * ps]) for i in range(n)]

    def _walk(self, blocks):
        """Longest matched node chain for ``blocks``."""
        chain, level = [], self._root
        for b in blocks:
            node = level.get(b)
            if node is None:
                break
            chain.append(node)
            level = node.children
        return chain

    def _count_nodes(self, level=None):
        level = self._root if level is None else level
        n = 0
        for node in level.values():
            n += 1 + self._count_nodes(node.children)
        return n

    # -- lookup / attach ----------------------------------------------------
    def match(self, prompt):
        """Peek: ``(pages, matched_tokens)`` for the longest cached
        prefix of ``prompt``.  Takes no references — admission calls
        this to size the reservation, then ``acquire`` to commit."""
        with self._lock:
            chain = self._walk(self._blocks(prompt))
            return ([n.page for n in chain],
                    len(chain) * self.page_size)

    def classify(self, prompt, matched_tokens):
        """The TTFT label class of one admission: ``hit`` when every
        cacheable block matched, ``partial`` for a shorter match,
        ``miss`` otherwise."""
        cacheable = max(0, (len(prompt) - 1) // self.page_size)
        if matched_tokens and \
                matched_tokens == cacheable * self.page_size:
            return "hit"
        return "partial" if matched_tokens else "miss"

    def acquire(self, prompt):
        """Commit a lookup: reference every matched page for the
        reading sequence and bump the chain's LRU clock.  Returns
        ``(pages, matched_tokens, cls)`` and counts the lookup."""
        with self._lock:
            chain = self._walk(self._blocks(prompt))
            self._clock += 1
            for node in chain:
                node.last_hit = self._clock
            pages = [n.page for n in chain]
            matched = len(chain) * self.page_size
            cls = self.classify(prompt, matched)
            if cls == "hit":
                self.hits += 1
            elif cls == "partial":
                self.partials += 1
            else:
                self.misses += 1
            self.hit_tokens_total += matched
        if pages:
            self.pool.shared_ref(pages)
        if telemetry.ENABLED:
            telemetry.SERVE_PREFIX_LOOKUPS.labels(result=cls).inc()
            if matched:
                telemetry.SERVE_PREFIX_HIT_TOKENS.inc(matched)
            telemetry.SERVE_PREFIX_SHARED_PAGES.set(
                self.pool.shared_pages)
        return pages, matched, cls

    def release(self, pages):
        """A reader (sequence) lets go of its shared prefix pages."""
        freed = self.pool.shared_unref(pages)
        if telemetry.ENABLED:
            telemetry.SERVE_PREFIX_SHARED_PAGES.set(
                self.pool.shared_pages)
        return freed

    # -- population ---------------------------------------------------------
    def insert(self, prompt, owner, table_pages, matched_tokens):
        """Adopt a freshly-prefilled sequence's full prompt pages into
        the trie.  ``table_pages`` is the sequence's combined page
        table (shared prefix first, then private pages) and
        ``matched_tokens`` how much of it was already cached at
        admission; blocks past the match are moved from ``owner``'s
        ledger into the shared segment with refcount 2 (trie + this
        reader).  Returns the number of pages adopted — the caller
        extends its shared-page list by exactly that many table
        slots."""
        blocks = self._blocks(prompt)
        start = matched_tokens // self.page_size
        adopted = 0
        with self._lock:
            level, chain = self._root, []
            for b in blocks[:start]:
                node = level.get(b)
                if node is None:    # matched chain evicted mid-flight
                    return adopted
                chain.append(node)
                level = node.children
            self._clock += 1
            for j in range(start, len(blocks)):
                b = blocks[j]
                if b in level:      # raced population: keep the first
                    break
                page = table_pages[j]
                self.pool.adopt_shared(owner, [page], readers=1)
                node = _Node(b, page, self._clock)
                level[b] = node
                level = node.children
                adopted += 1
                self.inserted_pages += 1
        if telemetry.ENABLED and adopted:
            telemetry.SERVE_PREFIX_SHARED_PAGES.set(
                self.pool.shared_pages)
        return adopted

    # -- eviction -----------------------------------------------------------
    def _leaves(self, level, parent):
        out = []
        for b, node in level.items():
            if node.children:
                out.extend(self._leaves(node.children, node.children))
            else:
                out.append((node, level, b))
        return out

    def evict(self, goal_pages):
        """LRU-by-last-hit eviction: drop cold leaf prefixes until
        ``goal_pages`` pages have actually returned to the free list
        (or nothing cold remains).  Only leaves whose page has no live
        reader (refcount 1 — the trie's own reference) are candidates,
        so eviction always frees real capacity and never strands a
        reader."""
        freed = 0
        while freed < goal_pages:
            with self._lock:
                refs = self.pool.shared_refs()
                leaves = [(node, level, b) for node, level, b
                          in self._leaves(self._root, self._root)
                          if refs.get(node.page) == 1]
                if not leaves:
                    break
                node, level, b = min(leaves,
                                     key=lambda t: t[0].last_hit)
                del level[b]
                self.evictions += 1
            freed += self.pool.shared_unref([node.page])
            if telemetry.ENABLED:
                telemetry.SERVE_PREFIX_EVICTIONS.inc()
        if telemetry.ENABLED:
            telemetry.SERVE_PREFIX_SHARED_PAGES.set(
                self.pool.shared_pages)
        return freed

    def _drop_subtree(self, node):
        for child in list(node.children.values()):
            self._drop_subtree(child)
        node.children.clear()
        self.pool.shared_unref([node.page])
        self.evictions += 1
        if telemetry.ENABLED:
            telemetry.SERVE_PREFIX_EVICTIONS.inc()

    def invalidate(self, prompt):
        """Drop the whole cached chain matching ``prompt`` (and every
        descendant) — the ``serve_cache`` corrupt-drill path: a prefix
        declared poisoned is re-prefilled from scratch by everyone.
        Live readers keep their references; storage follows the
        refcounts home.  Returns the number of nodes dropped."""
        with self._lock:
            blocks = self._blocks(prompt)
            if not blocks:
                return 0
            chain = self._walk(blocks)
            if not chain:
                return 0
            top = chain[0]
            before = self.evictions
            self._drop_subtree(top)
            del self._root[top.block]
            dropped = self.evictions - before
        if telemetry.ENABLED:
            telemetry.SERVE_PREFIX_SHARED_PAGES.set(
                self.pool.shared_pages)
        return dropped

    def clear(self):
        """Drop every node (pool storage lost or scheduler teardown)."""
        with self._lock:
            for node in list(self._root.values()):
                self._drop_subtree(node)
            self._root.clear()
        if telemetry.ENABLED:
            telemetry.SERVE_PREFIX_SHARED_PAGES.set(
                self.pool.shared_pages)

    # -- introspection ------------------------------------------------------
    def check(self):
        """Trie-side invariant audit: every trie page is in the pool's
        shared segment with refcount >= 1, no page appears twice, and
        the pool's own invariants hold."""
        from .batching import ServeError

        with self._lock:
            refs = self.pool.shared_refs()
            pages, stack = [], list(self._root.values())
            while stack:
                node = stack.pop()
                pages.append(node.page)
                stack.extend(node.children.values())
            if len(set(pages)) != len(pages):
                raise ServeError("prefix trie holds a duplicate page")
            for p in pages:
                if refs.get(p, 0) < 1:
                    raise ServeError(
                        "prefix trie page %d missing from the shared "
                        "segment" % p)
        return self.pool.check()

    def stats(self):
        with self._lock:
            nodes = self._count_nodes()
            return {
                "enabled": True,
                "block_tokens": self.page_size,
                "nodes": nodes,
                "shared_pages": self.pool.shared_pages,
                "hits": self.hits,
                "partials": self.partials,
                "misses": self.misses,
                "hit_tokens_total": self.hit_tokens_total,
                "inserted_pages": self.inserted_pages,
                "evictions": self.evictions,
            }

    def summary(self, roots_cap=32):
        """The load-digest view the fleet Registrar publishes: enough
        for Router prefix affinity (root-block digests) without
        shipping the trie."""
        with self._lock:
            roots = [prefix_digest(b)
                     for b in list(self._root)[:roots_cap]]
            return {
                "enabled": True,
                "block_tokens": self.page_size,
                "nodes": self._count_nodes(),
                "shared_pages": self.pool.shared_pages,
                "hits": self.hits,
                "roots": roots,
            }
