"""mx.serve.spec — speculative decoding: draft-propose, target-verify.

Decode latency is dominated by one-target-model-step-per-token.  This
plane breaks that coupling: a small **draft** decoder proposes K
tokens per round with K cheap steps, then the **target** model judges
all K in ONE batched dispatch — the ``("verify", (B, K))`` program
replicates every sequence K+1 times with chunk lengths ``1..K+1`` so
a single forward yields the target's argmax after every prefix of the
proposed chunk.  Greedy acceptance is *exact*: token j+1 of the chunk
is kept iff the draft's proposal equals the target's argmax after
token j, so the emitted stream is *bit-identical* to single-step
greedy decode — speculation changes wall-clock per token, never
tokens.  Acceptance averaging above 1 token per target step is pure
per-token-cost reduction.

Mechanics:

- **The draft is a full ``DecodeRunner``** over the same bucket /
  program / warm-up / compile-cache machinery as the target (its own
  ``PagePool``; ``max_context`` stretched by K+1 for speculative
  overshoot).  Steady state adds ZERO compiles: draft programs and the
  target's verify programs are all built at warm-up and restored from
  the ``mx.compile`` persistent cache across restarts.
- **Catch-up, not rewind.**  The draft cache is never rewritten after
  a rejected round; instead each round first *feeds the committed
  stream* (prompt + accepted tokens) from the draft's cursor ``dlen``
  forward, and a step's output only counts as a proposal once the
  catch-up queue is empty.  Rejected speculative K/V beyond ``dlen``
  is dead weight hidden by the draft's own scrub guard and is
  overwritten in place by later rounds.
- **Failure containment.**  Draft trouble NEVER costs correctness:
  pool pressure, a nonfinite draft row, a draft dispatch failure or an
  injected ``spec_verify@<rid>`` fault detaches the affected sequence
  alone back to non-speculative decode (a breaker strike on the
  ``("draft", bucket)`` class; batch-mates keep speculating), and a
  lost draft pool bumps the plane epoch so stale sequences detach
  lazily.  Only a TARGET pool loss propagates to the scheduler.
- **K is a structural autotune site** (``spec_k``): like
  ``decode_bucket`` it can never change tokens — only the
  acceptance-rate x K economics — so the idle tuner may commit a
  winner without a parity certificate beyond the structural proof.
"""
from __future__ import annotations

from types import SimpleNamespace

from .. import telemetry
from ..base import get_env
from ..resilience import inject as _inject
from ..resilience.inject import InjectedFault, InjectedIOError

__all__ = ["SpecPlane", "resolve_k"]

_K_DEFAULT = 4
_K_MAX = 16


def resolve_k(k, max_live):
    """The per-round proposal count: explicit argument >
    ``MXNET_SERVE_SPEC_K`` > the committed ``spec_k`` autotune winner
    for this ``max_live`` > 4.  Clamped to [1, 16]."""
    if k is None:
        env = get_env("MXNET_SERVE_SPEC_K", int, 0)
        if env > 0:
            k = env
    if k is None:
        from .. import autotune as _at

        if _at.is_enabled():
            cfg, prov = _at.lookup_info("spec_k", (int(max_live),),
                                        _K_DEFAULT)
            if prov == "tuned":
                try:
                    k = int(cfg)
                except (TypeError, ValueError):
                    _at.fallback("invalid_config")
    if k is None:
        k = _K_DEFAULT
    return max(1, min(_K_MAX, int(k)))


class SpecPlane:
    """Draft runner + verify programs + the accept/detach round loop.

    Owned by the target ``DecodeRunner`` (``DecodeRunner(...,
    draft=block)``); driven by the scheduler once per iteration with
    the speculative slice of the live set."""

    def __init__(self, target, draft, k=None, warm=True):
        from .decode import DecodeConfig, DecodeRunner

        cfg = target.config
        self.target = target
        self.k = resolve_k(k, cfg.max_live)
        draft_cfg = DecodeConfig(
            page_size=cfg.page_size, pool_pages=cfg.pool_pages,
            max_live=cfg.max_live, max_new_tokens=cfg.max_new_tokens,
            max_context=cfg.max_context + self.k + 1,
            prefill_lengths=cfg.prefill_lengths,
            batch_sizes=cfg.batch_sizes, queue_depth=cfg.queue_depth,
            eos_id=cfg.eos_id, dtype=cfg.dtype,
            prefix_cache=False, spec_k=0)
        self.draft = DecodeRunner(draft, config=draft_cfg, warm=False)
        self.epoch = 0            # bumped when the draft pool is lost
        self.rounds = 0
        self.verify_steps = 0
        self.proposed = 0
        self.accepted = 0
        self.emitted = 0
        self.fallbacks = {}
        self._warmed = False
        if warm:
            self.warm_up()

    @property
    def warmed(self):
        return self._warmed

    def warm_up(self):
        """Warm the draft's own program table and build ONE target
        verify program per decode batch bucket at this K (persistent
        compile cache first), so a speculative steady state adds zero
        compiles.  Returns fresh build count."""
        fresh = self.draft.warm_up()
        tgt = self.target
        for b in tgt.config.batch_sizes:
            key = ("verify", (b, self.k))
            with tgt._run_lock:
                if key in tgt._programs:
                    continue
                prog = tgt._build(key)
                if prog.provenance != "cache":
                    fresh += 1
                tgt._dispatch(prog, tgt._null_inputs(b, self.k + 1,
                                                     floors=True))
        self._warmed = True
        return fresh

    # -- per-sequence lifecycle ---------------------------------------------
    def attach(self, seq):
        """Adopt one admitted sequence onto the draft plane: reserve
        draft pages for its worst case (+K+1 speculative overshoot)
        and prefill the draft cache with its prompt.  Any failure
        leaves the sequence decoding normally (counted fallback)."""
        req = seq.req
        need = self.draft.page_config.pages_for(
            len(req.prompt) + req.max_new_tokens + self.k + 1)
        if need > self.draft.pool.capacity or \
                not self.draft.pool.can_alloc(need):
            self._fallback(seq, "draft_pool")
            return False
        seq.dpages = self.draft.pool.alloc(seq.sid, need)
        stand = SimpleNamespace(req=req, pages=seq.dpages)
        try:
            _tok, bad = self.draft.prefill(stand)
        except BaseException as exc:  # noqa: BLE001 - draft never fatal
            if getattr(exc, "pool_lost", False):
                self.epoch += 1
            self._release_draft(seq)
            self._fallback(seq, "draft_prefill")
            return False
        if bad:
            self._release_draft(seq)
            self._fallback(seq, "draft_nonfinite")
            return False
        seq.spec = True
        seq.dlen = len(req.prompt)
        seq.depoch = self.epoch
        return True

    def detach(self, seq, reason):
        """Degrade one sequence to non-speculative decode (reclaims
        its draft pages, counts the fallback)."""
        self._release_draft(seq)
        self._fallback(seq, reason)

    def release(self, seq):
        """Scheduler eviction path: reclaim draft pages silently — the
        sequence is leaving, not degrading."""
        self._release_draft(seq)
        seq.spec = False

    def _release_draft(self, seq):
        if seq.dpages is not None:
            self.draft.pool.release(seq.sid)
            seq.dpages = None

    def _fallback(self, seq, reason):
        seq.spec = False
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1
        if telemetry.ENABLED:
            telemetry.SERVE_SPEC_FALLBACKS.labels(reason=reason).inc()

    # -- the round ----------------------------------------------------------
    def round(self, seqs, breakers=None):
        """One speculative round over the attached live slice: K draft
        steps propose, ONE target verify dispatch judges, greedy
        acceptance emits.  Returns ``(results, fallen)`` where
        ``results`` is ``[(seq, emitted_tokens, nonfinite)]`` and
        ``fallen`` lists sequences the caller must step normally this
        iteration (detached / cooling).  Only a TARGET pool-lost error
        propagates."""
        results, fallen = [], []
        active = []
        for seq in seqs:
            if seq.depoch != self.epoch:
                self.detach(seq, "draft_lost")
                fallen.append(seq)
            else:
                active.append(seq)
        if not active:
            return results, fallen
        dbucket = self.draft.decode_bucket(len(active))
        if breakers is not None and breakers.blocked(("draft", dbucket)):
            return results, fallen + active
        self.rounds += 1
        # -- propose: K draft decode steps; catch the draft cache up
        # to the committed stream first (rollback-by-replay, see
        # module doc), a step's output is a proposal only once the
        # catch-up queue is empty
        queues, proposals, stands, last_out = {}, {}, {}, {}
        for seq in active:
            committed = seq.req.prompt + seq.tokens
            seq.dlen = min(seq.dlen, len(committed) - 1)
            queues[seq.sid] = committed[seq.dlen:]
            proposals[seq.sid] = []
            stands[seq.sid] = SimpleNamespace(pages=seq.dpages,
                                              last_token=0, length=0)
        for _ in range(self.k):
            batch = []
            for seq in active:
                q = queues[seq.sid]
                tok = q.pop(0) if q else last_out[seq.sid]
                st = stands[seq.sid]
                st.last_token = int(tok)
                st.length = seq.dlen
                batch.append(st)
            try:
                toks, bads = self.draft.decode_step(batch)
            except BaseException as exc:  # noqa: BLE001 - draft never fatal
                if getattr(exc, "pool_lost", False):
                    self.epoch += 1
                if breakers is not None:
                    breakers.failure(("draft", dbucket))
                for seq in active:
                    self.detach(seq, "draft_error")
                return results, fallen + active
            drop = []
            for i, seq in enumerate(active):
                seq.dlen += 1
                if int(bads[i]):
                    if breakers is not None:
                        breakers.failure(("draft", dbucket))
                    self.detach(seq, "draft_nonfinite")
                    fallen.append(seq)
                    drop.append(seq)
                    continue
                out = int(toks[i])
                last_out[seq.sid] = out
                if not queues[seq.sid]:
                    proposals[seq.sid].append(out)
            for seq in drop:
                active.remove(seq)
            if not active:
                return results, fallen
        # -- spec_verify drill: a poisoned draft degrades that
        # sequence ALONE to non-speculative decode (breaker strike on
        # the draft bucket; batch-mates verify normally)
        drop = []
        for seq in active:
            try:
                _inject.fire("spec_verify", seq=seq.req.request_id)
            except (InjectedFault, InjectedIOError):
                if breakers is not None:
                    breakers.failure(("draft", dbucket))
                self.detach(seq, "injected")
                fallen.append(seq)
                drop.append(seq)
        for seq in drop:
            active.remove(seq)
        if not active:
            return results, fallen
        # -- verify: chunk = [last committed token, proposals...],
        # truncated so scatter never passes the page reservation
        chunks = []
        for seq in active:
            remaining = (len(seq.req.prompt) + seq.req.max_new_tokens
                         - seq.length)
            ch = [seq.last_token] + proposals[seq.sid]
            chunks.append([int(t) for t in ch[:max(1, remaining)]])
        vbucket = self.target.decode_bucket(len(active))
        try:
            y, bad = self.target.verify_step(active, chunks, self.k)
        except BaseException as exc:  # noqa: BLE001 - classified
            if breakers is not None:
                breakers.failure(("spec", vbucket))
            if getattr(exc, "pool_lost", False):
                raise
            return results, fallen + active
        if breakers is not None:
            breakers.success(("spec", vbucket))
        self.verify_steps += 1
        # -- greedy acceptance: keep proposal j while it equals the
        # target's argmax after position j-1; always emit y[0] (the
        # token single-step decode would have produced)
        prop_n = acc_n = 0
        for i, seq in enumerate(active):
            if int(bad[i]):
                results.append((seq, [], int(bad[i])))
                continue
            ch = chunks[i]
            emitted = [int(y[i][0])]
            for j in range(1, len(ch)):
                if int(ch[j]) != emitted[-1]:
                    break
                emitted.append(int(y[i][j]))
            prop_n += len(ch) - 1
            acc_n += len(emitted) - 1
            self.emitted += len(emitted)
            results.append((seq, emitted, 0))
        self.proposed += prop_n
        self.accepted += acc_n
        if telemetry.ENABLED:
            telemetry.SERVE_SPEC_ROUNDS.inc()
            if prop_n:
                telemetry.SERVE_SPEC_PROPOSED.inc(prop_n)
            if acc_n:
                telemetry.SERVE_SPEC_ACCEPTED.inc(acc_n)
        return results, fallen

    # -- introspection ------------------------------------------------------
    def stats(self):
        vs = max(1, self.verify_steps)
        return {
            "enabled": True,
            "k": self.k,
            "draft_model": type(self.draft.block).__name__,
            "rounds": self.rounds,
            "verify_steps": self.verify_steps,
            "proposed": self.proposed,
            "accepted": self.accepted,
            "acceptance_rate": (float(self.accepted) / self.proposed)
            if self.proposed else 0.0,
            "accepted_per_step": float(self.emitted) / vs,
            "fallbacks": dict(self.fallbacks),
            "draft_pool": self.draft.pool.stats(),
            "epoch": self.epoch,
        }
