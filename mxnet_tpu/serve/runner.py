"""ModelRunner — checkpoint-backed, shape-bucketed model execution.

The runner owns everything shape-related on the serving path:

- **load**: builds the block (instance or zero-arg factory), restores
  parameters from an ``mx.checkpoint`` root (restore-with-resharding
  onto the serving ctx via ``Block.load_checkpoint``), hybridizes.
- **bucket table**: the cross product of ``batch_sizes`` and
  ``sample_shapes`` defines every input signature the compiled cache
  will ever see.  ``warm_up()`` pre-compiles all of them through
  ``HybridBlock.warm_up`` so steady-state serving triggers at most one
  compile per bucket — and that compile happens before readiness, not
  on the first live request (TVM-style compile-once/run-many; TPU
  latency is strongly shape-dependent).
- **pad / unpad**: incoming samples are zero-padded up to the smallest
  covering sample bucket, stacked, and the batch is zero-padded up to
  the smallest covering batch size; outputs are sliced back to each
  request's real extent.  Pad waste is metered
  (``serve_pad_elements_total`` / ``serve_pad_fraction``).

Unpadding rule: output axis ``a`` (sample axis ``a-1``) is sliced back
to the request's extent when its size equals the padded size of the
FIRST input's corresponding sample axis.  That is exact for
row/position-independent models (MLPs applied along the last dim,
masked sequence models); models whose outputs do not track input axes
can pass ``unpad=False`` and slice downstream.
"""
from __future__ import annotations

from threading import RLock

import numpy as _np

from .. import autograd, telemetry, trace
from ..gluon.block import Block, HybridBlock
from .batching import NoBucketError

__all__ = ["ModelRunner", "DEFAULT_BATCH_SIZES", "resolve_block",
           "count_nonfinite"]

DEFAULT_BATCH_SIZES = (1, 2, 4, 8)


def resolve_block(block, cls=Block, who="ModelRunner"):
    """Unwrap a zero-arg block factory and type-check the result — the
    shared front door of both serving runners (``ModelRunner`` and
    ``decode.DecodeRunner``)."""
    if not isinstance(block, Block) and callable(block):
        block = block()
    if not isinstance(block, cls):
        raise ValueError("%s needs a %s or a zero-arg factory returning "
                         "one, got %r" % (who, cls.__name__, block))
    return block


def count_nonfinite(arrays):
    """NaN/Inf elements across host float arrays (the mx.monitor serve
    output guard's scan; the decode plane computes the same count
    in-program per logits row)."""
    bad = 0
    for a in arrays:
        if getattr(a.dtype, "kind", "") == "f":
            bad += int(a.size) - int(_np.isfinite(a).sum())
    return bad


def _normalize_sample_shapes(sample_shapes):
    """-> list of per-input shape tuples, sorted by padded volume (the
    bucket chooser scans in order, so the smallest covering bucket
    wins).  Accepts bare shape tuples for single-input models."""
    out = []
    for sig in sample_shapes or ():
        if isinstance(sig, (tuple, list)) and \
                all(isinstance(d, int) for d in sig):
            sig = (tuple(sig),)
        out.append(tuple(tuple(s) for s in sig))
    out.sort(key=lambda sig: sum(int(_np.prod(s)) for s in sig))
    return out


def _bucket_label(batch, sig):
    return "%dx%s" % (batch, "|".join(
        ",".join(str(d) for d in s) for s in sig))


class ModelRunner:
    """Load-once, pad-and-run model executor (swapped atomically by
    ``Server.swap`` — a runner never mutates its model after init).

    Parameters
    ----------
    block : Block or callable — the model, or a zero-arg factory.
    root : str or None — ``mx.checkpoint`` root to restore from.
    step : int or None — checkpoint step (default: latest committed).
    ctx : Context or None — serving device; restore reshards onto it.
    batch_sizes : sorted batch buckets (batch dim padding targets).
    sample_shapes : per-request shape buckets; None disables padding
        (each distinct request shape becomes its own exact bucket and
        compiles on first sight — fine for dev, not for production).
    dtype : input dtype requests are cast to.
    warm : pre-compile the whole bucket table at construction.
    unpad : slice outputs back to each request's real extent.
    """

    def __init__(self, block, root=None, step=None, ctx=None,
                 batch_sizes=DEFAULT_BATCH_SIZES, sample_shapes=None,
                 dtype="float32", warm=True, unpad=True):
        block = resolve_block(block)
        self._block = block
        self._ctx = ctx
        self._dtype = dtype
        self._unpad = bool(unpad)
        self.root = root
        self.step = None
        if root is not None:
            self.step = block.load_checkpoint(root, step=step, ctx=ctx)
        if isinstance(block, HybridBlock) and not block._active:
            block.hybridize(True, clear=False)
        self._batch_sizes = tuple(sorted(set(int(b) for b in batch_sizes)))
        if not self._batch_sizes:
            raise ValueError("batch_sizes must be non-empty")
        self._sample_buckets = _normalize_sample_shapes(sample_shapes)
        self._warmed = False
        self._warm_provenance = {}
        self._run_lock = RLock()  # one compiled program at a time
        if warm:
            self.warm_up()

    # -- introspection ------------------------------------------------------
    @property
    def block(self):
        return self._block

    @property
    def warmed(self):
        return self._warmed

    @property
    def max_batch_size(self):
        return self._batch_sizes[-1]

    def bucket_table(self):
        """[(batch, sample_sig), ...] — every signature warm_up compiles."""
        return [(b, sig) for sig in (self._sample_buckets or [()])
                for b in self._batch_sizes]

    def stats(self):
        return {
            "step": self.step,
            "root": self.root,
            "warmed": self._warmed,
            "dtype": self._dtype,
            "batch_sizes": list(self._batch_sizes),
            "sample_shapes": [[list(s) for s in sig]
                              for sig in self._sample_buckets],
            "buckets": [_bucket_label(b, sig)
                        for b, sig in self.bucket_table()
                        if sig],
            "compiled_signatures": len(getattr(self._block, "_cached_ops",
                                               ())),
            # per-bucket compile provenance from the last warm_up():
            # fresh (XLA compiled here) / warm-start (installed from
            # disk by that warm_up's mx.compile.warm_start preamble) /
            # cache (restored from the persistent cache earlier in this
            # process) / cache-failed (restored but failed at call
            # time; the jit fallback compiled fresh) / warm (compiled
            # earlier in this process) — operators verify a
            # zero-compile restart here (/statz)
            "warm_provenance": dict(self._warm_provenance),
        }

    # -- warm-up ------------------------------------------------------------
    def warm_up(self):
        """Pre-compile every (batch_size x sample_shape) bucket.  Emits
        one ``serve_compile_total{bucket=...}`` per newly built
        signature; re-warming an already-hot runner is a no-op (cache
        hits).  Returns the number of new signatures this process built.

        When the mx.compile persistent cache is enabled, the whole
        bucket table is first ``warm_start``-ed from disk (a restarted
        server reaches readiness with zero fresh XLA compiles), and
        each bucket's provenance — cache / fresh / warm-start /
        cache-failed / warm —
        is recorded for ``stats()`` (surfaced at ``/statz``)."""
        built = 0
        self._warm_provenance = {}
        if not isinstance(self._block, HybridBlock):
            self._warmed = True  # nothing to compile
            return built
        from .. import compile as _compile

        pre_ws = set(self._block._cached_ops)
        ws_installed = set()
        if _compile.is_enabled():
            try:
                # scope the restore to THIS runner's buckets: a shared
                # cache may hold many other deployments' signatures for
                # the same model, and each install pays a pickle +
                # executable device-load
                sigs = [[((b,) + tuple(s), self._dtype) for s in sig]
                        for b, sig in self.bucket_table() if sig]
                # no sample buckets configured means lazy compile —
                # NOT "restore every signature the shared cache holds"
                if sigs:
                    _compile.warm_start(self._block, signatures=sigs)
                    # keys warm_start ACTUALLY installed — a bucket the
                    # live attach path restores later in this loop must
                    # report "cache", not "warm-start"
                    ws_installed = set(self._block._cached_ops) - pre_ws
            except Exception:  # the cache must never block readiness
                pass
        for b, sig in self.bucket_table():
            if not sig:
                continue  # no sample buckets configured: lazy compile
            label = _bucket_label(b, sig)
            n = self._block.warm_up(
                [[((b,) + s, self._dtype) for s in sig]])
            if n:
                # warm_up counts only fresh XLA compiles (disk restores
                # return 0), so n > 0 means this process built it
                built += n
                self._warm_provenance[label] = "fresh"
                if telemetry.ENABLED:
                    telemetry.SERVE_COMPILES.labels(bucket=label).inc(n)
            else:
                # provenance comes from THIS bucket's cache entry (not
                # telemetry deltas or global warm_start counts, which
                # misattribute when telemetry is off or other buckets
                # were the ones installed)
                key, centry = self._bucket_centry(b, sig)
                if centry is not None and \
                        getattr(centry, "provenance", "fresh") == "cache":
                    if centry.cfn is None:
                        # the restored executable failed at call time
                        # during this warm_up's execution pass and the
                        # jit fallback compiled fresh — reporting
                        # "warm-start"/0 compiles would be the exact
                        # false positive /statz exists to catch
                        self._warm_provenance[label] = "cache-failed"
                    else:
                        self._warm_provenance[label] = \
                            "warm-start" if key in ws_installed \
                            else "cache"
                else:
                    self._warm_provenance[label] = "warm"
        self._warmed = True
        # mx.autotune idle-time tuning (MXNET_AUTOTUNE=search): the
        # bucket table is compiled and no traffic has arrived — measure
        # each bucket's execute latency into the TuningStore
        # (serve_bucket records: cost-model features + diagnose
        # provenance).  Bounded by MXNET_AUTOTUNE_BUDGET_MS; ANY
        # failure degrades silently — warm-up readiness never depends
        # on tuning
        from .. import autotune as _autotune

        if _autotune.search_enabled():
            try:
                _autotune.measure.serve_idle_tune(self)
            except Exception:
                _autotune.fallback("serve_idle")
        return built

    def _bucket_centry(self, b, sig):
        """The hybridize cache (key, entry) serving this warm-up bucket:
        inference mode, flat-input avals matching the bucket's padded
        shapes.  (None, None) when not yet compiled."""
        avals = [((b,) + tuple(s), self._dtype) for s in sig]
        return self._block.find_cached_entry(avals, training=False)

    # -- output guard -------------------------------------------------------
    def _guard_outputs(self, outs_np, B, sig):
        """mx.monitor's serve-side guard: count nonfinite elements in
        the per-request (unpadded) outputs — already on host, the
        asnumpy sync paid for the scan — so a model serving NaN logits
        is visible at /statz (``serve_nonfinite_*`` totals) instead of
        silently poisoning clients.  Armed with the rest of the
        monitor plane (``MXNET_MONITOR=1``); detection only — requests
        still get their outputs (the client contract is the caller's
        call)."""
        from .. import monitor as _monitor

        if not _monitor.core.ENABLED:
            return
        bad = count_nonfinite(outs_np)
        if not bad:
            return
        if telemetry.ENABLED:
            telemetry.SERVE_NONFINITE_OUTPUTS.inc(bad)
            telemetry.SERVE_NONFINITE_BATCHES.inc()
        trace.instant("serve_nonfinite_outputs", cat="serve",
                      args={"elements": bad,
                            "bucket": _bucket_label(B, sig)
                            if sig else str(B)})

    # -- bucketing ----------------------------------------------------------
    def bucket_for(self, sample_shapes):
        """Map a request's per-input sample shapes to its bucket class.

        Returns the index of the smallest covering sample bucket (same
        rank per input, every dim >=).  Without a configured table the
        exact shape tuple is its own class.  Raises ``NoBucketError``
        when nothing covers the request — submit-time validation, so
        oversized inputs are rejected at the front door, not at
        dispatch."""
        sample_shapes = tuple(tuple(s) for s in sample_shapes)
        if not self._sample_buckets:
            return sample_shapes
        for i, sig in enumerate(self._sample_buckets):
            if len(sig) != len(sample_shapes):
                continue
            if all(len(b) == len(s) and
                   all(bd >= sd for bd, sd in zip(b, s))
                   for b, s in zip(sig, sample_shapes)):
                return i
        raise NoBucketError(
            "no shape bucket covers request input shapes %s "
            "(buckets: %s)" % (list(sample_shapes),
                               [list(map(list, s))
                                for s in self._sample_buckets]))

    def _batch_bucket(self, n):
        for b in self._batch_sizes:
            if b >= n:
                return b
        return self._batch_sizes[-1]

    def _target_sig(self, requests):
        cls = requests[0].bucket_class
        if isinstance(cls, int):
            return self._sample_buckets[cls]
        return cls  # exact-shape class: no sample padding

    # -- execution ----------------------------------------------------------
    def run_batch(self, requests):
        """Pad, stack, run, unpad.  ``requests`` are same-class
        ``batching.Request`` objects; returns one result per request
        (a bare array for single-input style requests, else a tuple).
        Batches larger than the biggest batch bucket are chunked."""
        results = []
        cap = self.max_batch_size
        for i in range(0, len(requests), cap):
            results.extend(self._run_chunk(requests[i:i + cap]))
        return results

    def _run_chunk(self, requests):
        from .. import ndarray as nd

        sig = self._target_sig(requests)
        n = len(requests)
        B = self._batch_bucket(n)
        # phase spans nest under the scheduler's serve_dispatch span
        # (the head request's trace context) — or stand alone when
        # run_batch is called directly
        with trace.span("serve_pad", hist=False, cat="serve",
                        args={"batch": B, "requests": n}):
            bufs, real = [], 0
            for j, bucket_shape in enumerate(sig):
                buf = _np.zeros((B,) + bucket_shape, dtype=self._dtype)
                for i, req in enumerate(requests):
                    a = req.inputs[j]
                    real += a.size
                    buf[(i,) + tuple(slice(0, d) for d in a.shape)] = a
                bufs.append(buf)
            total = sum(b.size for b in bufs)
            if telemetry.ENABLED and total:
                telemetry.SERVE_PAD_ELEMENTS.inc(total - real)
                telemetry.SERVE_PAD_FRACTION.observe(
                    (total - real) / total)

        cached = getattr(self._block, "_cached_ops", None)
        before = len(cached) if cached is not None else 0
        with trace.span("serve_execute", hist=False, cat="serve",
                        args={"bucket": _bucket_label(B, sig)
                              if sig else str(B)}):
            with self._run_lock, autograd.pause():
                if self._ctx is not None:
                    with self._ctx:
                        out = self._block(*[nd.array(b, ctx=self._ctx)
                                            for b in bufs])
                else:
                    out = self._block(*[nd.array(b) for b in bufs])
            outs = out if isinstance(out, tuple) else (out,)
            # asnumpy is the hard sync: device time lands in THIS span
            outs_np = [o.asnumpy() for o in outs]
        if cached is not None and len(cached) > before \
                and telemetry.ENABLED:
            # a compile escaped warm-up (unwarmed bucket or lazy mode)
            telemetry.SERVE_COMPILES.labels(
                bucket=_bucket_label(B, sig)).inc(len(cached) - before)

        with trace.span("serve_unpad", hist=False, cat="serve"):
            lead = sig[0] if sig else requests[0].inputs[0].shape
            results = []
            for i, req in enumerate(requests):
                orig = req.inputs[0].shape
                per_req = []
                for o in outs_np:
                    row = o[i]
                    if self._unpad:
                        slices = tuple(
                            slice(0, orig[a]) if a < len(lead)
                            and a < len(orig) and row.shape[a] == lead[a]
                            else slice(None)
                            for a in range(row.ndim))
                        row = row[slices]
                    per_req.append(row)
                results.append(per_req[0] if len(per_req) == 1
                               else tuple(per_req))
        # guard AFTER unpad: only values actually returned to clients
        # count — padding rows/regions may legitimately go nonfinite
        # (log/division on zero-fill) without the model being sick
        self._guard_outputs(
            [a for r in results
             for a in (r if isinstance(r, tuple) else (r,))], B, sig)
        return results
