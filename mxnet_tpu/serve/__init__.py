"""mx.serve — dynamic-batching TPU inference serving.

The serving counterpart of the training-side subsystems (telemetry,
checkpoint): compile-once/run-many execution behind a request queue.

- ``ModelRunner`` loads a HybridBlock from an ``mx.checkpoint`` root
  (restore-with-resharding onto the serving ctx), pre-warms the
  hybridize cache for a configured bucket table (batch sizes x sample
  shapes), and pads/bucketizes inputs so steady-state serving triggers
  at most ONE compile per bucket — XLA recompiles never land on the
  hot path.
- ``BatchQueue`` + ``Scheduler`` coalesce concurrent single requests
  into micro-batches under a ``max_batch_size`` / ``max_wait_us``
  policy, with bounded queue depth, per-request deadlines, and
  explicit backpressure: overload REJECTS with ``ServerOverloaded``
  instead of queueing unboundedly.
- ``Server`` is the thread-safe front end: ``submit()`` /
  ``submit_async()`` futures, graceful drain on ``shutdown()``, hot
  model swap via atomic runner replacement (``swap()``), and a
  minimal stdlib HTTP endpoint (``/predict``, ``/healthz``,
  ``/readyz``, ``/metrics``, ``/statz``).

Every stage is metered through ``mx.telemetry`` (``serve_*`` queue
wait, batch size, pad waste, compile count, latency, rejections) and
exported through the existing Prometheus/JSON exporters.  See README
"Serving" for the knobs and the hot-swap workflow.
"""
from __future__ import annotations

from .batching import (BatchQueue, NoBucketError, Request, RequestTimeout,
                       Scheduler, ServeError, ServerClosed, ServerOverloaded)
from .runner import DEFAULT_BATCH_SIZES, ModelRunner
from .server import ServeConfig, Server

__all__ = [
    "Server", "ServeConfig", "ModelRunner", "BatchQueue", "Scheduler",
    "Request", "ServeError", "ServerOverloaded", "ServerClosed",
    "RequestTimeout", "NoBucketError", "DEFAULT_BATCH_SIZES",
]
