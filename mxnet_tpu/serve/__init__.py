"""mx.serve — dynamic-batching TPU inference serving.

The serving counterpart of the training-side subsystems (telemetry,
checkpoint): compile-once/run-many execution behind a request queue.

- ``ModelRunner`` loads a HybridBlock from an ``mx.checkpoint`` root
  (restore-with-resharding onto the serving ctx), pre-warms the
  hybridize cache for a configured bucket table (batch sizes x sample
  shapes), and pads/bucketizes inputs so steady-state serving triggers
  at most ONE compile per bucket — XLA recompiles never land on the
  hot path.
- ``BatchQueue`` + ``Scheduler`` coalesce concurrent single requests
  into micro-batches under a ``max_batch_size`` / ``max_wait_us``
  policy, with bounded queue depth, per-request deadlines, and
  explicit backpressure: overload REJECTS with ``ServerOverloaded``
  instead of queueing unboundedly.
- ``Server`` is the thread-safe front end: ``submit()`` /
  ``submit_async()`` futures, graceful drain on ``shutdown()``, hot
  model swap via atomic runner replacement (``swap()``), and a
  minimal stdlib HTTP endpoint (``/predict``, ``/healthz``,
  ``/readyz``, ``/metrics``, ``/statz``).

- **graceful degradation** (mx.resilience): a failing batch is
  retried bisected down to singles so a poisoned request fails ALONE
  (``serve_poison_requests_total``); repeatedly-failing buckets are
  quarantined by per-bucket circuit breakers (``BucketQuarantined``,
  HTTP 503 + ``Retry-After``, state visible in ``/healthz`` and
  ``/statz``); overload maps to 503 + ``Retry-After`` and deadline
  expiry to 504, with ``X-Request-Id`` echoed on every response.

Every stage is metered through ``mx.telemetry`` (``serve_*`` queue
wait, batch size, pad waste, compile count, latency, rejections) and
exported through the existing Prometheus/JSON exporters.  See README
"Serving" for the knobs and the hot-swap workflow.
"""
from __future__ import annotations

from .batching import (BatchQueue, BucketQuarantined, NoBucketError,
                       Request, RequestTimeout, Scheduler, ServeError,
                       ServerClosed, ServerOverloaded)
from .breaker import BreakerBoard, CircuitBreaker
from .runner import DEFAULT_BATCH_SIZES, ModelRunner
from .server import ServeConfig, Server

__all__ = [
    "Server", "ServeConfig", "ModelRunner", "BatchQueue", "Scheduler",
    "Request", "ServeError", "ServerOverloaded", "ServerClosed",
    "RequestTimeout", "NoBucketError", "BucketQuarantined",
    "CircuitBreaker", "BreakerBoard", "DEFAULT_BATCH_SIZES",
]
