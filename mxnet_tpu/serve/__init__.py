"""mx.serve — dynamic-batching TPU inference serving.

The serving counterpart of the training-side subsystems (telemetry,
checkpoint): compile-once/run-many execution behind a request queue.

- ``ModelRunner`` loads a HybridBlock from an ``mx.checkpoint`` root
  (restore-with-resharding onto the serving ctx), pre-warms the
  hybridize cache for a configured bucket table (batch sizes x sample
  shapes), and pads/bucketizes inputs so steady-state serving triggers
  at most ONE compile per bucket — XLA recompiles never land on the
  hot path.
- ``BatchQueue`` + ``Scheduler`` coalesce concurrent single requests
  into micro-batches under a ``max_batch_size`` / ``max_wait_us``
  policy, with bounded queue depth, per-request deadlines, and
  explicit backpressure: overload REJECTS with ``ServerOverloaded``
  instead of queueing unboundedly.
- ``Server`` is the thread-safe front end: ``submit()`` /
  ``submit_async()`` futures, graceful drain on ``shutdown()``, hot
  model swap via atomic runner replacement (``swap()``), and a
  minimal stdlib HTTP endpoint (``/predict``, ``/healthz``,
  ``/readyz``, ``/metrics``, ``/statz``).

- **graceful degradation** (mx.resilience): a failing batch is
  retried bisected down to singles so a poisoned request fails ALONE
  (``serve_poison_requests_total``); repeatedly-failing buckets are
  quarantined by per-bucket circuit breakers (``BucketQuarantined``,
  HTTP 503 + ``Retry-After``, state visible in ``/healthz`` and
  ``/statz``); overload maps to 503 + ``Retry-After`` and deadline
  expiry to 504, with ``X-Request-Id`` echoed on every response.

- **autoregressive decode plane** (``decode.py`` + ``kvcache.py``):
  paged/blocked KV-cache as first-class serving state (``PagePool``:
  fixed-size pages, per-sequence page tables, admission-time
  worst-case reservation — OOM is a fast reject, never a mid-decode
  failure) and Orca-style continuous batching (``DecodeScheduler``:
  one jitted decode-step program per batch bucket runs every
  iteration over whichever sequences are live; sequences join freed
  slots mid-flight and leave — pages reclaimed — the same step),
  with per-token streaming through ``/predict?stream=1``, the
  in-program output guard, sequence-granular poison isolation and
  per-bucket breakers.

- **per-token-cost plane** (``cache.py`` + ``spec.py``): a radix
  prefix cache over PagePool pages (identical prompt prefixes prefill
  once per replica; admission charges only the uncached suffix;
  LRU-by-last-hit eviction under pool pressure; cached output
  bit-identical to cold) and speculative decoding (a draft decoder
  proposes K tokens, the target verifies all K in one batched
  dispatch; greedy acceptance keeps output bit-identical to
  single-step decode).  Both opt-in: ``DecodeConfig(
  prefix_cache=True)`` / ``MXNET_SERVE_PREFIX_CACHE=1`` and
  ``DecodeRunner(draft=...)``.

Every stage is metered through ``mx.telemetry`` (``serve_*`` queue
wait, batch size, pad waste, compile count, latency, rejections, and
the ``serve_decode_*`` / ``serve_kv_*`` decode-plane families) and
exported through the existing Prometheus/JSON exporters.  See README
"Serving" / "Autoregressive serving" for the knobs and workflows.
"""
from __future__ import annotations

from .batching import (BatchQueue, BucketQuarantined, NoBucketError,
                       Request, RequestTimeout, Scheduler, ServeError,
                       ServerClosed, ServerOverloaded, fail_request)
from .breaker import BreakerBoard, CircuitBreaker
from .cache import PrefixCache, prefix_digest
from .decode import (DecodeConfig, DecodeError, DecodeRequest,
                     DecodeRunner, DecodeScheduler, TinyDecoder)
from .kvcache import PageConfig, PagePool, PagePoolExhausted
from .spec import SpecPlane
from .runner import DEFAULT_BATCH_SIZES, ModelRunner
from .server import ServeConfig, Server

__all__ = [
    "Server", "ServeConfig", "ModelRunner", "BatchQueue", "Scheduler",
    "Request", "ServeError", "ServerOverloaded", "ServerClosed",
    "RequestTimeout", "NoBucketError", "BucketQuarantined",
    "CircuitBreaker", "BreakerBoard", "DEFAULT_BATCH_SIZES",
    "fail_request",
    # autoregressive decode plane (paged KV-cache + continuous batching)
    "DecodeConfig", "DecodeError", "DecodeRequest", "DecodeRunner",
    "DecodeScheduler", "TinyDecoder", "PageConfig", "PagePool",
    "PagePoolExhausted",
    # per-token-cost plane (prefix cache + speculative decoding)
    "PrefixCache", "prefix_digest", "SpecPlane",
]
