"""Server — the mx.serve front-end.

Thread-safe ``submit()`` / ``submit_async()`` (futures) over one
``BatchQueue`` + ``Scheduler``, with:

- **graceful drain**: ``shutdown()`` (default) stops intake, serves
  everything already queued, then joins the scheduler; ``drain=False``
  fails queued requests with ``ServerClosed`` instead.
- **hot model swap**: ``swap()`` builds and WARMS a whole new
  ``ModelRunner`` from a (new) checkpoint step off the serving path,
  then replaces the runner reference atomically.  The scheduler reads
  that reference once per batch, so every request runs entirely on the
  old model or entirely on the new one — no half-swapped state is
  observable, and readiness never flaps during a swap.
- **HTTP endpoint** (stdlib ``http.server``, threading): POST
  ``/predict``; GET ``/healthz`` (process up), ``/readyz`` (model
  loaded + buckets warmed -> 200, else 503), ``/metrics`` (Prometheus
  text), ``/statz`` (JSON: scheduler config, bucket table, queue
  depth, serve_* totals, nonfinite-output health block — what
  ``tools/diagnose.py --serve`` reads).
- **autoregressive decode plane** (``decode=`` a ``DecodeRunner`` or
  decoder block): ``submit_decode()`` futures over the paged-KV
  continuous-batching loop, ``{"tokens": [...]}`` payloads on
  ``/predict`` (collect mode), and chunked per-token streaming on
  ``/predict?stream=1`` — the streamed token sequence is bit-identical
  to the collect-mode result, and ``X-Request-Id`` is echoed on the
  streaming response headers too.  A server may carry either plane or
  both; ``/statz`` grows a ``decode`` block (live sequences, page-pool
  occupancy, per-bucket compile provenance).
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as _np

from .. import telemetry
from ..base import get_env
from ..gluon.block import Block
from ..ndarray.ndarray import NDArray
from ..resilience import preempt as _preempt
from .batching import (BatchQueue, BucketQuarantined, NoBucketError,
                       Request, RequestTimeout, Scheduler, ServeError,
                       ServerClosed, ServerOverloaded)
from .breaker import BreakerBoard
from .decode import DecodeError
from .kvcache import PagePoolExhausted
from .runner import DEFAULT_BATCH_SIZES, ModelRunner

__all__ = ["ServeConfig", "Server", "SERVE_STATZ_SCHEMA_VERSION"]

# /statz top-level schema version with an ADDITIVE-KEYS policy (README
# "Serving" / per-token cost): within a version, top-level keys may be
# ADDED but never renamed, removed, or retyped — fleet/obs scrapers
# must treat unknown keys as forward compatibility, and
# test_serve.py locks the REQUIRED subset + this version.  Bump the
# version only on a breaking change (rename/remove/retype).  v2 added
# "cache" and "spec" (the per-token-cost plane).
SERVE_STATZ_SCHEMA_VERSION = 2


class ServeConfig:
    """Batching-policy + bucket-spec knobs (see README "Serving").

    max_batch_size : dispatch as soon as this many same-bucket
        requests are queued (clamped to the largest batch bucket).
    max_wait_us : how long an incomplete batch waits for stragglers.
    queue_depth : bound on queued requests; beyond it submissions are
        rejected with ``ServerOverloaded`` (explicit backpressure).
    timeout_ms : default per-request deadline (None = no deadline).
    batch_sizes : batch-dim padding targets (default: powers of two up
        to ``max_batch_size``).
    sample_shapes : per-request shape buckets — a list of shape tuples
        (single-input) or tuples of per-input shapes.  None = exact
        shapes, compile-per-new-shape (dev only).
    dtype : request arrays are cast to this dtype.
    breaker_threshold : consecutive failed dispatches that open a
        bucket's circuit breaker (``MXNET_SERVE_BREAKER_THRESHOLD``,
        default 5; <= 0 disables breakers).
    breaker_cooldown_s : quarantine seconds before the half-open trial
        (``MXNET_SERVE_BREAKER_COOLDOWN``, default 30).
    retry_after_s : the ``Retry-After`` the HTTP front-end advertises
        on overload 503s (``MXNET_SERVE_RETRY_AFTER``, default 1).
    """

    def __init__(self, max_batch_size=8, max_wait_us=2000, queue_depth=64,
                 timeout_ms=None, batch_sizes=None, sample_shapes=None,
                 dtype="float32", breaker_threshold=None,
                 breaker_cooldown_s=None, retry_after_s=None):
        self.max_batch_size = int(max_batch_size)
        self.max_wait_us = int(max_wait_us)
        self.queue_depth = int(queue_depth)
        self.timeout_ms = timeout_ms
        self.breaker_threshold = get_env(
            "MXNET_SERVE_BREAKER_THRESHOLD", int, 5) \
            if breaker_threshold is None else int(breaker_threshold)
        self.breaker_cooldown_s = get_env(
            "MXNET_SERVE_BREAKER_COOLDOWN", float, 30.0) \
            if breaker_cooldown_s is None else float(breaker_cooldown_s)
        self.retry_after_s = get_env(
            "MXNET_SERVE_RETRY_AFTER", float, 1.0) \
            if retry_after_s is None else float(retry_after_s)
        if batch_sizes is None:
            batch_sizes = [b for b in DEFAULT_BATCH_SIZES
                           if b <= self.max_batch_size]
            while batch_sizes and batch_sizes[-1] < self.max_batch_size:
                batch_sizes.append(min(batch_sizes[-1] * 2,
                                       self.max_batch_size))
            batch_sizes = batch_sizes or [self.max_batch_size]
        self.batch_sizes = tuple(sorted(set(int(b) for b in batch_sizes)))
        self.sample_shapes = sample_shapes
        self.dtype = dtype
        self.max_batch_size = min(self.max_batch_size, self.batch_sizes[-1])

    def as_dict(self):
        return {
            "max_batch_size": self.max_batch_size,
            "max_wait_us": self.max_wait_us,
            "queue_depth": self.queue_depth,
            "timeout_ms": self.timeout_ms,
            "batch_sizes": list(self.batch_sizes),
            "sample_shapes": None if self.sample_shapes is None else [
                [list(s) for s in (sig if not all(
                    isinstance(d, int) for d in sig) else [sig])]
                for sig in self.sample_shapes],
            "dtype": self.dtype,
            "breaker_threshold": self.breaker_threshold,
            "breaker_cooldown_s": self.breaker_cooldown_s,
            "retry_after_s": self.retry_after_s,
        }


class Server:
    """Dynamic-batching inference server over one ModelRunner and/or a
    continuous-batching decode plane (``decode=`` a ``DecodeRunner`` or
    a decoder block following the ``serve/decode.py`` contract)."""

    def __init__(self, block=None, root=None, step=None, ctx=None,
                 config=None, runner=None, decode=None,
                 decode_config=None, tenant=None):
        from .decode import DecodeRunner, DecodeScheduler

        self._config = config or ServeConfig()
        self._ctx = ctx
        # keep the factory (not just the instance) so swap() can build
        # a FRESH block: loading new params into the live block would
        # be observable mid-load
        self._block_factory = block if block is not None and \
            not isinstance(block, Block) and callable(block) else None
        if runner is None and block is not None:
            runner = ModelRunner(
                block, root=root, step=step, ctx=ctx,
                batch_sizes=self._config.batch_sizes,
                sample_shapes=self._config.sample_shapes,
                dtype=self._config.dtype)
        if runner is None and decode is None:
            raise ValueError("Server needs a block (or factory), a "
                             "pre-built runner, or a decode= plane")
        self._runner = runner
        self._root = root if root is not None else \
            (runner.root if runner is not None else None)
        self._breakers = BreakerBoard(
            self._config.breaker_threshold,
            self._config.breaker_cooldown_s) \
            if self._config.breaker_threshold > 0 else None
        # -- decode plane (serve/decode.py) ---------------------------------
        if decode is not None and not isinstance(decode, DecodeRunner):
            decode = DecodeRunner(decode, root=root, step=step, ctx=ctx,
                                  config=decode_config, tenant=tenant)
        elif decode_config is not None and decode is not None:
            raise ValueError(
                "decode_config= only applies when decode= is a raw "
                "decoder block; a pre-built DecodeRunner already "
                "carries its own config — pass it there instead of "
                "having this one silently ignored")
        # mx.tenant plane: explicit tenant= wins, else a pre-built
        # DecodeRunner's own plane (built with tenant=) carries through
        self._tenant = tenant if tenant is not None else \
            getattr(decode, "tenant", None)
        self._decode = DecodeScheduler(decode, breakers=self._breakers,
                                       tenant=self._tenant) \
            if decode is not None else None
        # -- micro-batch plane ----------------------------------------------
        self._queue = None
        self._scheduler = None
        # the scheduler (and its daemon thread) hold the server WEAKLY:
        # a Server dropped without shutdown() must become collectable —
        # its dispatch loop sees the dead ref and winds itself down —
        # rather than being pinned for the process lifetime by its own
        # thread.  The per-batch ref() read keeps the hot-swap
        # atomicity point: one runner read per batch.
        import weakref

        ref = weakref.ref(self)
        if runner is not None:
            self._queue = BatchQueue(self._config.queue_depth)

            def _current_runner():
                srv = ref()
                return None if srv is None else srv._runner

            self._scheduler = Scheduler(
                self._queue, _current_runner,
                max_batch_size=self._config.max_batch_size,
                max_wait_us=self._config.max_wait_us,
                breakers=self._breakers)
            self._scheduler.start()
        self._swap_lock = threading.Lock()
        self._httpd = None
        self._closed = False
        # mx.fleet: discovery registrar, rollout drain flag, and the
        # live-stream counter graceful drain waits on (streaming
        # handler threads are daemon threads; without the count a
        # drain could close the listener under a half-written stream)
        self._registrar = None
        self._draining = False
        self._streams = 0
        self._stream_cv = threading.Condition()
        # preemption (mx.resilience): SIGTERM drains this server's
        # queue before the process exits — in-flight answers beat a
        # dropped queue every time.  Weak for the same reason as the
        # scheduler: the module-global hook list must not pin dead
        # servers (a zombie drain would eat grace budget on a real
        # preemption); stale hooks self-remove.
        self._preempt_hook = "serve-drain-%d" % id(self)

        def _drain(hook=self._preempt_hook):
            srv = ref()
            if srv is None or srv._closed:
                _preempt.remove_shutdown_hook(hook)
                return
            srv.shutdown(drain=True, timeout=10.0)

        _preempt.add_shutdown_hook(self._preempt_hook, _drain)

    # -- introspection ------------------------------------------------------
    @property
    def config(self):
        return self._config

    @property
    def runner(self):
        return self._runner

    @property
    def decode(self):
        """The decode plane's ``DecodeScheduler`` (None without one)."""
        return self._decode

    @property
    def tenant(self):
        """The multi-tenant plane (``tenant.TenantPlane``; None when
        this server is single-tenant)."""
        return self._tenant

    @property
    def step(self):
        if self._runner is not None:
            return self._runner.step
        return self._decode.runner.step if self._decode is not None \
            else None

    def healthy(self):
        """Liveness: every configured dispatch loop is running.  (An
        open circuit breaker does NOT make the process unhealthy —
        other buckets still serve; breaker state rides in the /healthz
        body.)"""
        if self._closed:
            return False
        if self._scheduler is not None and not self._scheduler.alive:
            return False
        if self._decode is not None and not self._decode.alive:
            return False
        return True

    def breakers(self):
        """{bucket_label: breaker state} — open breakers mean that
        bucket's traffic is quarantined (503 + Retry-After) until the
        cooldown's half-open trial succeeds."""
        return self._breakers.snapshot() \
            if self._breakers is not None else {}

    def ready(self):
        """Readiness: healthy AND every configured plane finished
        warm-up (each bucket compiled) — traffic sent now will not hit
        a cold-compile stall."""
        if not self.healthy():
            return False
        if self._runner is not None and not self._runner.warmed:
            return False
        if self._decode is not None and not self._decode.runner.warmed:
            return False
        return True

    def queue_depth(self):
        return len(self._queue) if self._queue is not None else 0

    def queue_age_s(self):
        """Seconds the oldest queued request (either plane) has
        waited — the router's primary load signal: depth alone reads
        the same for a fast-draining and a stuck queue."""
        age = 0.0
        if self._queue is not None:
            age = self._queue.oldest_age()
        if self._decode is not None:
            age = max(age, self._decode.oldest_waiting_age())
        return age

    @property
    def draining(self):
        """True while a fleet rollout is draining this replica: the
        router stops NEW dispatches; in-flight work finishes."""
        return self._draining

    def set_draining(self, flag=True):
        """Flip the rollout drain flag and push it to discovery
        immediately (a rollout must not wait a publish interval for
        routers to notice)."""
        self._draining = bool(flag)
        if self._registrar is not None:
            self._registrar.publish()
        return self._draining

    def load_digest(self):
        """The compact load digest the fleet registrar publishes on
        every heartbeat (all derivable from /statz, but /statz is a
        full stats walk — this is the cheap per-beat subset the
        router's power-of-two-choices scoring reads)."""
        digest = {
            "queue_depth": self.queue_depth(),
            "queue_capacity": self._config.queue_depth,
            "queue_age_s": round(self.queue_age_s(), 4),
            "decode_waiting": 0,
            "decode_live": 0,
            "decode_queue_depth": 0,
            "decode_max_live": 0,
            "pages_free": 0,
            "pages_total": 0,
            "breakers_open": 0,
            "breakers_half_open": 0,
        }
        if self._decode is not None:
            pool = self._decode.runner.pool
            with self._decode._cond:
                digest["decode_waiting"] = len(self._decode._waiting)
                digest["decode_live"] = len(self._decode._live)
            digest["decode_queue_depth"] = \
                self._decode.config.queue_depth
            digest["decode_max_live"] = self._decode.config.max_live
            digest["pages_free"] = pool.available
            digest["pages_total"] = pool.capacity
            cache = self._decode.runner.cache
            if cache is not None:
                # prefix-affinity signal (fleet/router.py): the root
                # block digests let the router route a session to the
                # replica already holding its prefix
                digest["prefix_cache"] = cache.summary(roots_cap=16)
        if self._tenant is not None:
            # adapter-residency signal (fleet/router.py): which
            # tenants' adapters this replica already holds resident
            digest["tenants"] = self._tenant.residency()
        for b in self.breakers().values():
            if b["state"] == "open":
                digest["breakers_open"] += 1
            elif b["state"] == "half_open":
                digest["breakers_half_open"] += 1
        return digest

    def stats(self):
        serve_totals = {k: v for k, v in telemetry.totals().items()
                        if k.startswith("serve_")}
        by_result = {}
        req = telemetry.get_metric("serve_requests_total")
        if req is not None:
            for values, child in req._samples():
                if values:
                    by_result[values[0]] = child.value
        from .. import monitor as _monitor

        return {
            # the stable schema contract external parsers key on (the
            # fleet router's digest, scrapers): top-level keys are
            # locked by test_serve.py against this version
            "schema_version": SERVE_STATZ_SCHEMA_VERSION,
            "ready": self.ready(),
            "healthy": self.healthy(),
            "draining": self.draining,
            "queue_depth": self.queue_depth(),
            "queue_age_s": round(self.queue_age_s(), 4),
            "config": self._config.as_dict(),
            "runner": self._runner.stats()
            if self._runner is not None else None,
            # the decode plane: live sequences, page-pool occupancy /
            # high water, per-bucket compile provenance, evictions —
            # what tools/diagnose.py --serve renders as the decode table
            "decode": self._decode.stats()
            if self._decode is not None else None,
            "requests": by_result,
            "totals": serve_totals,
            # mx.resilience serve degradation: per-bucket circuit
            # breaker states (open = quarantined)
            "breakers": self.breakers(),
            # mx.monitor output guard: nonfinite logits served (the
            # serve-side face of the training-health plane; counts also
            # appear in totals as serve_nonfinite_*)
            "health": {
                "monitor": _monitor.core.ENABLED,
                "nonfinite_output_elems": telemetry.value(
                    "serve_nonfinite_outputs_total"),
                "nonfinite_batches": telemetry.value(
                    "serve_nonfinite_batches_total"),
            },
            # mx.obs SLO engine: per-objective OK/WARN/PAGE + burn
            # rates (None when no objectives are registered)
            "slo": self._slo_states(),
            # the per-token-cost plane (serve/cache.py + serve/spec.py;
            # {"enabled": False} when not armed) — schema v2 additions
            "cache": self._cache_stats(),
            "spec": self._spec_stats(),
            # mx.tenant multi-tenant plane ({"enabled": False} when
            # single-tenant) — schema v2 additive-keys addition
            "tenants": self._tenant_stats(),
        }

    def _tenant_stats(self):
        if self._tenant is not None:
            return self._tenant.stats()
        return {"enabled": False}

    def _cache_stats(self):
        if self._decode is not None:
            cache = self._decode.runner.cache
            if cache is not None:
                return cache.stats()
        return {"enabled": False}

    def _spec_stats(self):
        if self._decode is not None:
            spec = self._decode.runner.spec
            if spec is not None:
                return spec.stats()
        return {"enabled": False}

    @staticmethod
    def _slo_states():
        """Evaluated SLO results for /statz and /healthz, or None
        when the obs plane is off / nothing registered.  Fail-soft:
        a sick SLO engine must not take the stats endpoint down."""
        try:
            from ..obs import slo_engine

            if not slo_engine.registered():
                return None
            return slo_engine.evaluate()
        except Exception:  # noqa: BLE001
            return None

    # -- submission ---------------------------------------------------------
    def _normalize(self, inputs):
        """-> (tuple of numpy arrays, single_flag).  A tuple means
        multi-input; anything else (array/NDArray/nested list) is one
        input."""
        single = not isinstance(inputs, tuple)
        seq = (inputs,) if single else inputs
        arrays = []
        for x in seq:
            if isinstance(x, NDArray):
                x = x.asnumpy()
            arrays.append(_np.asarray(x, dtype=self._config.dtype))
        return tuple(arrays), single

    def submit_async(self, inputs, timeout_ms=None, request_id=None):
        """Enqueue one request; returns a ``concurrent.futures.Future``
        resolving to the (unpadded) model output.  Raises
        ``ServerOverloaded`` when the queue is full, ``NoBucketError``
        when no shape bucket covers the input, ``ServerClosed`` after
        shutdown.  ``request_id`` (the HTTP front-end passes
        X-Request-Id) becomes the request's trace id in the flight
        record."""
        if self._closed:
            raise ServerClosed("server is shut down")
        if self._scheduler is None:
            raise ServeError("this server has no micro-batch plane "
                             "(decode-only); use submit_decode()")
        arrays, single = self._normalize(inputs)
        cls = self._runner.bucket_for(tuple(a.shape for a in arrays))
        if self._breakers is not None and self._breakers.blocked(cls):
            # fast-reject at the front door (same philosophy as the
            # queue-depth backpressure): an open breaker means this
            # bucket's dispatches keep failing — don't queue more.
            # Counted like every other rejection, or the incident the
            # breaker surfaces would read as vanishing traffic
            if telemetry.ENABLED:
                telemetry.SERVE_REQUESTS.labels(
                    result="quarantined").inc()
            raise self._breakers.quarantine_error(cls)
        timeout_ms = self._config.timeout_ms if timeout_ms is None \
            else timeout_ms
        deadline = None if timeout_ms is None \
            else time.perf_counter() + float(timeout_ms) / 1e3
        req = Request(arrays, cls, deadline=deadline, single=single,
                      request_id=request_id)
        self._queue.put(req)
        return req.future

    def submit(self, inputs, timeout_ms=None, request_id=None):
        """Synchronous ``submit_async``: blocks for the result (the
        scheduler resolves every future — ok, timeout, or error — so
        this cannot hang on a dead deadline)."""
        return self.submit_async(inputs, timeout_ms=timeout_ms,
                                 request_id=request_id).result()

    # -- decode plane -------------------------------------------------------
    def submit_decode(self, tokens, max_new_tokens=None, eos_id=None,
                      timeout_ms=None, request_id=None, on_token=None,
                      tenant=None):
        """Enqueue one autoregressive generation request on the decode
        plane; returns a future resolving to ``{"tokens": [...],
        "finish_reason": ...}``.  ``on_token(token_id, index)`` streams
        each token as it is emitted (bit-identical to the future's
        ``tokens``).  ``tenant`` bills the request to a registered
        tenant (mx.tenant: WFQ weight, quota, adapter).  Raises
        ``ServeError`` without a decode plane."""
        if self._closed:
            raise ServerClosed("server is shut down")
        if self._decode is None:
            raise ServeError("this server has no decode plane "
                             "(construct with decode=DecodeRunner(...))")
        return self._decode.submit(
            tokens, max_new_tokens=max_new_tokens, eos_id=eos_id,
            timeout_ms=timeout_ms, request_id=request_id,
            on_token=on_token, tenant=tenant)

    def submit_decode_export(self, tokens, max_new_tokens=None,
                             eos_id=None, timeout_ms=None,
                             request_id=None):
        """Prefill-only submission (mx.fleet disaggregation): the
        future resolves to the ``fleet.handoff`` state dict the
        ``/fleet/handoff/export`` endpoint packs onto the wire."""
        if self._closed:
            raise ServerClosed("server is shut down")
        if self._decode is None:
            raise ServeError("this server has no decode plane")
        return self._decode.submit_export(
            tokens, max_new_tokens=max_new_tokens, eos_id=eos_id,
            timeout_ms=timeout_ms, request_id=request_id)

    def submit_decode_handoff(self, state, timeout_ms=None,
                              request_id=None, on_token=None):
        """Import a handed-off prefill (mx.fleet disaggregation):
        admission reservation math re-runs against THIS pool before
        any page content lands."""
        if self._closed:
            raise ServerClosed("server is shut down")
        if self._decode is None:
            raise ServeError("this server has no decode plane")
        return self._decode.submit_handoff(
            state, timeout_ms=timeout_ms, request_id=request_id,
            on_token=on_token)

    # -- fleet registration -------------------------------------------------
    def register_fleet(self, membership, role=None, replica_id=None,
                       interval=None):
        """Register this replica in the mx.fleet discovery plane: its
        endpoint + role + live load digest ride every membership
        heartbeat under ``fleet/<gen>/<replica-id>``.  Requires
        ``start_http()`` first (the record is an endpoint).  Returns
        the attached ``fleet.discovery.Registrar``."""
        if self._httpd is None:
            raise ServeError("register_fleet needs start_http() first "
                             "(the discovery record is an endpoint)")
        if self._registrar is not None:
            return self._registrar
        from ..fleet import discovery as _discovery

        host, port = self._httpd.server_address[:2]
        self._registrar = _discovery.register(
            self, membership, "%s:%d" % (host, port), role=role,
            replica_id=replica_id, interval=interval)
        return self._registrar

    def swap_decode(self, new_runner):
        """Repoint the decode plane at a new ``DecodeRunner``: live
        sequences finish on the old runner's pool, new admissions start
        on the new one once the running batch drains."""
        if self._decode is None:
            raise ServeError("this server has no decode plane")
        self._decode.swap(new_runner)

    # -- hot swap -----------------------------------------------------------
    def swap(self, root=None, step=None, block=None):
        """Atomically repoint serving at a new checkpoint step.

        Builds a NEW runner (fresh block from ``block``/the factory
        given at construction), restores ``step`` (default: latest
        committed) from ``root`` (default: the serving root), warms
        every bucket, then replaces the runner reference.  In-flight
        batches finish on the old model; requests dispatched after the
        swap run on the new one.  Returns the restored step."""
        with self._swap_lock:
            factory = block if block is not None else self._block_factory
            if factory is None:
                raise ServeError(
                    "hot swap needs a fresh block: construct the Server "
                    "with a block FACTORY (callable), or pass block= "
                    "here — reloading params into the live block would "
                    "not be atomic")
            new_block = factory() if not isinstance(factory, Block) and \
                callable(factory) else factory
            root = self._root if root is None else root
            if root is None:
                raise ServeError("hot swap needs a checkpoint root")
            new_runner = ModelRunner(
                new_block, root=root, step=step, ctx=self._ctx,
                batch_sizes=self._config.batch_sizes,
                sample_shapes=self._config.sample_shapes,
                dtype=self._config.dtype)
            self._runner = new_runner  # the atomic publication point
            self._root = root
            if telemetry.ENABLED:
                telemetry.SERVE_SWAPS.inc()
            return new_runner.step

    # -- lifecycle ----------------------------------------------------------
    def _stream_begin(self):
        with self._stream_cv:
            self._streams += 1

    def _stream_end(self):
        with self._stream_cv:
            self._streams -= 1
            self._stream_cv.notify_all()

    def _wait_streams(self, timeout):
        """Block until every in-flight streaming response has written
        its terminator (bounded).  Returns True when none remain."""
        deadline = time.monotonic() + (30.0 if timeout is None
                                       else float(timeout))
        with self._stream_cv:
            while self._streams > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._stream_cv.wait(left)
        return True

    def shutdown(self, drain=True, timeout=None):
        """Stop intake and join the scheduler.  With ``drain`` (the
        default) queued requests are served first AND in-flight
        streaming responses finish before the listener closes — the
        planes drain first (resolving every future feeding a stream),
        then the stream count reaches zero, then the socket goes away.
        ``drain=False`` fails queued requests fast with
        ``ServerClosed`` and tears the listener down immediately."""
        self._closed = True
        _preempt.remove_shutdown_hook(self._preempt_hook)
        if self._registrar is not None:
            try:
                self._registrar.close()
            except Exception:  # noqa: BLE001 - discovery is best-effort
                pass
            self._registrar = None
        if not drain and self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        ok = True
        if self._decode is not None:
            ok = self._decode.stop(drain=drain, timeout=timeout) and ok
        if self._scheduler is not None:
            ok = self._scheduler.stop(drain=drain, timeout=timeout) and ok
        if drain:
            ok = self._wait_streams(timeout) and ok
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd = None
        return ok

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- HTTP surface -------------------------------------------------------
    def start_http(self, host="127.0.0.1", port=0):
        """Start the stdlib HTTP endpoint on a daemon thread; returns
        ``(host, port)`` (port 0 picks a free one)."""
        if self._httpd is not None:
            return self._httpd.server_address[:2]
        httpd = ThreadingHTTPServer((host, port), _Handler)
        httpd.daemon_threads = True
        httpd.mx_server = self
        self._httpd = httpd
        t = threading.Thread(target=httpd.serve_forever, daemon=True,
                             name="mx-serve-http")
        t.start()
        return httpd.server_address[:2]


class _Handler(BaseHTTPRequestHandler):
    server_version = "mx-serve/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs to logging
        import logging

        logging.getLogger("mxnet_tpu.serve.http").debug(fmt, *args)

    def _send(self, code, body, content_type="application/json",
              headers=()):
        data = body if isinstance(body, bytes) else \
            json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        srv = self.server.mx_server
        if self.path == "/healthz":
            # liveness + the degradation picture: an open breaker is
            # visible here (status "degraded") but the process is still
            # alive — only a dead scheduler is a 503
            if srv.healthy():
                breakers = srv.breakers()
                degraded = any(b["state"] != "closed"
                               for b in breakers.values())
                body = {"status": "degraded" if degraded else "ok",
                        "breakers": breakers}
                # an SLO past WARN degrades liveness the same way an
                # open breaker does: alive, but tell the router
                slo = srv._slo_states()
                if slo is not None:
                    worst = max((s.get("state", "OK") for s in
                                 slo.values()),
                                key=lambda st: {"OK": 0, "WARN": 1,
                                                "PAGE": 2}.get(st, 0))
                    body["slo"] = {k: s.get("state", "OK")
                                   for k, s in slo.items()}
                    if worst != "OK":
                        body["status"] = "degraded"
                self._send(200, body)
            else:
                self._send(503, {"status": "down",
                                 "breakers": srv.breakers()})
        elif self.path == "/readyz":
            ready = srv.ready()
            self._send(200 if ready else 503,
                       {"ready": ready, "step": srv.step})
        elif self.path == "/metrics":
            self._send(200, telemetry.prometheus().encode(),
                       content_type="text/plain; version=0.0.4")
        elif self.path == "/statz":
            self._send(200, srv.stats())
        elif self.path == "/fleetz":
            from .. import obs as _obs

            self._send(200, _obs.fleetz())
        else:
            self._send(404, {"error": "unknown path %s" % self.path})

    def do_POST(self):  # noqa: N802
        import urllib.parse

        srv = self.server.mx_server
        parts = urllib.parse.urlsplit(self.path)
        if parts.path not in ("/predict", "/drainz",
                              "/fleet/handoff/export",
                              "/fleet/handoff/import"):
            self._send(404, {"error": "unknown path %s" % self.path})
            return
        query = urllib.parse.parse_qs(parts.query)
        # X-Request-Id: accepted, attached to the request as its trace
        # id, and ECHOED on every /predict response (success or error)
        # so clients and the flight record agree on the correlation id.
        # The SAME sanitizer the trace id uses: echoing raw client
        # bytes into send_header is a response-splitting vector
        # (obs-folded CRLF survives Python's header parser verbatim)
        from .. import trace

        rid = trace.sanitize_request_id(
            self.headers.get("X-Request-Id"))
        echo = (("X-Request-Id", rid),) if rid else ()

        def send(code, body, extra=()):
            # X-Request-Id rides on EVERY response — success, 503, 504
            self._send(code, body, headers=echo + tuple(extra))

        from ..fleet.handoff import HandoffError

        try:
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n)
            if parts.path == "/drainz":
                # mx.fleet rollout: flip the drain flag (body
                # {"draining": bool}, default true) — discovery
                # republishes immediately so routers stop dispatching
                flag = srv.set_draining(
                    json.loads(raw or b"{}").get("draining", True))
                send(200, {"draining": flag})
                return
            if parts.path == "/fleet/handoff/export":
                self._do_handoff_export(srv, json.loads(raw or b"{}"),
                                        rid, echo)
                return
            if parts.path == "/fleet/handoff/import":
                self._do_handoff_import(srv, raw, query, rid, echo,
                                        send)
                return
            payload = json.loads(raw or b"{}")
            if "tokens" in payload:
                self._do_decode(srv, payload, query, rid, echo, send)
                return
            inputs = payload["inputs"]
            if payload.get("multi"):
                inputs = tuple(inputs)
            out = srv.submit(inputs,
                             timeout_ms=payload.get("timeout_ms"),
                             request_id=rid)
            if isinstance(out, tuple):
                body = {"outputs": [o.tolist() for o in out]}
            else:
                body = {"outputs": out.tolist()}
            body["step"] = srv.step
            send(200, body)
        except BucketQuarantined as exc:
            # the bucket's circuit breaker is open: tell the client
            # when the half-open trial will admit traffic again
            send(503, {"error": str(exc)},
                 extra=(("Retry-After", "%d" % max(
                     1, round(exc.retry_after or 1))),))
        except ServerOverloaded as exc:
            # overload is a server state, not a client error: 503 with
            # an explicit Retry-After so well-behaved clients back off
            # instead of hammering the full queue
            send(503, {"error": str(exc)},
                 extra=(("Retry-After", "%d" % max(
                     1, round(srv.config.retry_after_s))),))
        except RequestTimeout as exc:
            # distinct from a generic 500: the deadline expired before
            # dispatch — the model never saw the request
            send(504, {"error": str(exc)})
        except ServerClosed as exc:
            send(503, {"error": str(exc)})
        except HandoffError as exc:
            # a corrupt / geometry-skewed handoff blob: the sender's
            # problem (router retries on a different replica or fails
            # the request) — never a reason to poison this pool
            if telemetry.ENABLED:
                telemetry.FLEET_HANDOFFS.labels(
                    result="checksum_mismatch").inc()
            send(400, {"error": str(exc), "type": "HandoffError"})
        except (DecodeError, PagePoolExhausted) as exc:
            # static decode-plane limits (context/prompt/vocab bounds,
            # a reservation that can never fit the pool): client error,
            # not server pressure — retrying identical input cannot
            # win.  EXCEPT pool_lost: the server's KV storage died
            # under the sequence (a transient device fault) — that is
            # a 500 a retry may well win
            send(500 if getattr(exc, "pool_lost", False) else 400,
                 {"error": str(exc)})
        except (KeyError, ValueError, NoBucketError) as exc:
            send(400, {"error": str(exc)})
        except Exception as exc:  # noqa: BLE001 - surfaced to the client
            send(500, {"error": str(exc)})

    def _do_decode(self, srv, payload, query, rid, echo, send):
        """POST /predict with a ``tokens`` payload: route to the decode
        plane.  ``?stream=1`` (or ``"stream": true``) streams each
        token as a chunked NDJSON event — same engine, same greedy
        sampling, so the streamed ids are bit-identical to collect
        mode — ending with a ``done`` summary (or an ``error`` event
        if the sequence failed mid-generation).  Pre-admission errors
        (overload, quarantine, validation) raise into ``do_POST``'s
        normal status-code mapping before any response bytes go out."""
        if srv.decode is None:
            send(400, {"error": "this server has no decode plane"})
            return
        stream = payload.get("stream")
        if stream is None:
            stream = query.get("stream", ["0"])[0] \
                not in ("", "0", "false")
        kwargs = dict(max_new_tokens=payload.get("max_new_tokens"),
                      eos_id=payload.get("eos_id"),
                      timeout_ms=payload.get("timeout_ms"),
                      request_id=rid,
                      tenant=payload.get("tenant"))
        # provenance of generated tokens is the DECODE runner's
        # checkpoint step (a dual-plane server's vision runner may sit
        # at a different step)
        dstep = srv.decode.runner.step
        if not stream or not srv.decode.config.stream:
            res = srv.submit_decode(payload["tokens"], **kwargs).result()
            send(200, {"tokens": res["tokens"],
                       "finish_reason": res["finish_reason"],
                       "step": dstep})
            return
        import queue as _queue

        events = _queue.Queue()
        # count the stream BEFORE submitting: a drain racing this
        # request must either see the stream (and wait for its
        # terminator) or reject the submit — never close the listener
        # between admission and the first header byte
        srv._stream_begin()
        try:
            fut = srv.submit_decode(
                payload["tokens"],
                on_token=lambda tok, i: events.put((tok, i)), **kwargs)
        except BaseException:
            srv._stream_end()
            raise
        fut.add_done_callback(lambda _f: events.put(None))
        try:
            self._stream_events(fut, events, dstep, echo)
        finally:
            srv._stream_end()

    def _stream_events(self, fut, events, dstep, echo):
        """Write one chunked NDJSON token stream: per-token events from
        ``events`` (None = future resolved), then the ``done`` summary
        (or in-stream ``error``), then the chunked terminator.  Shared
        by ``/predict?stream=1`` and ``/fleet/handoff/import?stream=1``."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        for k, v in echo:
            self.send_header(k, v)
        try:
            self.end_headers()

            def chunk(obj):
                data = json.dumps(obj).encode() + b"\n"
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")

            while True:
                item = events.get()
                if item is None:
                    break
                chunk({"token": item[0], "index": item[1]})
            try:
                res = fut.result()
                chunk({"done": True, "tokens": res["tokens"],
                       "finish_reason": res["finish_reason"],
                       "step": dstep})
            except Exception as exc:  # noqa: BLE001 - surfaced in-stream
                chunk({"error": str(exc), "type": type(exc).__name__})
            self.wfile.write(b"0\r\n\r\n")
        except Exception:  # noqa: BLE001 - client gone mid-stream
            # the 200 + chunked headers are already on the wire: do NOT
            # fall back into do_POST's error mapping (a second status
            # line inside a chunked body is protocol corruption on a
            # half-open socket) — just drop the connection; the decode
            # engine finishes the sequence regardless (callbacks feed a
            # queue, never this socket)
            self.close_connection = True

    def _do_handoff_export(self, srv, payload, rid, echo):
        """POST /fleet/handoff/export (mx.fleet disaggregation): run
        the prompt on this PREFILL replica and return the sequence's
        pages + cursor + first token as one checksummed blob.
        Pre-admission errors raise into do_POST's status mapping."""
        from ..fleet import handoff as _handoff

        state = srv.submit_decode_export(
            payload["tokens"],
            max_new_tokens=payload.get("max_new_tokens"),
            eos_id=payload.get("eos_id"),
            timeout_ms=payload.get("timeout_ms"),
            request_id=rid).result()
        blob = _handoff.pack(state)
        if telemetry.ENABLED:
            telemetry.FLEET_HANDOFFS.labels(result="ok").inc()
            telemetry.FLEET_HANDOFF_BYTES.observe(len(blob))
        self._send(200, blob, content_type="application/octet-stream",
                   headers=echo)

    def _do_handoff_import(self, srv, raw, query, rid, echo, send):
        """POST /fleet/handoff/import: unpack (checksum + geometry
        verified), re-run admission reservation on THIS pool, decode.
        ``?stream=1`` streams tokens exactly like /predict?stream=1 —
        the first event is the prefill replica's token 0, so the
        client-visible stream is byte-identical to a colocated run."""
        from ..fleet import handoff as _handoff

        state = _handoff.unpack(raw)      # HandoffError -> 400 ladder
        stream = query.get("stream", ["0"])[0] not in ("", "0", "false")
        dstep = srv.decode.runner.step if srv.decode is not None else None
        if not stream or srv.decode is None or \
                not srv.decode.config.stream:
            res = srv.submit_decode_handoff(state, request_id=rid) \
                .result()
            if telemetry.ENABLED:
                telemetry.FLEET_HANDOFFS.labels(result="ok").inc()
            send(200, {"tokens": res["tokens"],
                       "finish_reason": res["finish_reason"],
                       "step": dstep})
            return
        import queue as _queue

        events = _queue.Queue()
        srv._stream_begin()
        try:
            fut = srv.submit_decode_handoff(
                state, request_id=rid,
                on_token=lambda tok, i: events.put((tok, i)))
        except BaseException:
            srv._stream_end()
            raise
        fut.add_done_callback(lambda _f: events.put(None))
        if telemetry.ENABLED:
            telemetry.FLEET_HANDOFFS.labels(result="ok").inc()
        try:
            self._stream_events(fut, events, dstep, echo)
        finally:
            srv._stream_end()
