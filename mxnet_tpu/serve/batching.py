"""Request queue + micro-batching scheduler for mx.serve.

The serving hot path is a single bounded FIFO (``BatchQueue``) drained
by one ``Scheduler`` thread.  The scheduler coalesces concurrent
single-sample requests into micro-batches under a
``max_batch_size`` / ``max_wait_us`` policy: a batch is dispatched as
soon as ``max_batch_size`` requests of the SAME bucket class are
queued, or when the oldest of them has waited ``max_wait_us``.
Batches are homogeneous per bucket class (requests padding to
different shape buckets never mix), so every dispatch hits exactly one
pre-warmed compiled signature.

Overload policy is explicit backpressure: a full queue REJECTS with
``ServerOverloaded`` immediately — requests never queue unboundedly
and callers never hang.  Each request carries an optional deadline;
expired requests are failed with ``RequestTimeout`` before dispatch
and never reach the model.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError

from .. import telemetry, trace
from ..base import MXNetError
from ..resilience import inject as _inject
from ..resilience.inject import InjectedFault

__all__ = ["ServeError", "ServerOverloaded", "ServerClosed", "fail_request",
           "RequestTimeout", "NoBucketError", "BucketQuarantined",
           "Request", "BatchQueue", "Scheduler"]


class ServeError(MXNetError):
    """Root of mx.serve errors."""


class ServerOverloaded(ServeError):
    """The batch queue is full: the request was rejected, not queued.
    Clients should back off and retry (HTTP surface: 503 +
    ``Retry-After``)."""


class ServerClosed(ServeError):
    """The server is shut down (or shutting down without drain)."""


class RequestTimeout(ServeError, TimeoutError):
    """The request's deadline expired before it was dispatched."""


class NoBucketError(ServeError, ValueError):
    """No configured shape bucket can hold the request's input shapes."""


class BucketQuarantined(ServeError):
    """The request's shape bucket is quarantined by an open circuit
    breaker (repeated dispatch failures); other buckets still serve.
    Clients should retry after ``retry_after`` seconds (HTTP surface:
    503 + ``Retry-After``)."""

    def __init__(self, msg, retry_after=None):
        super().__init__(msg)
        self.retry_after = retry_after


def fail_request(req, exc, result):
    """Resolve a request exceptionally (idempotent) + count the outcome.

    Shared by the micro-batch scheduler AND the decode path: anything
    with the ``Request`` resolution surface (``future`` / ``enqueued``
    / ``trace`` / ``request_id``) resolves through here so the
    ``serve_requests_total{result=...}`` taxonomy and the per-request
    root trace span stay consistent across both serving planes."""
    try:
        req.future.set_exception(exc)
    except InvalidStateError:
        return
    if telemetry.ENABLED:
        telemetry.SERVE_REQUESTS.labels(result=result).inc()
        tenant = getattr(req, "tenant", None)
        if tenant is not None:
            # mx.tenant: attribute the failure to the billing tenant
            # (per-tenant error-rate SLOs read this family)
            telemetry.TENANT_REQUESTS.labels(
                tenant=tenant, result=result).inc()
    if trace.ENABLED and req.trace is not None:
        trace.record_span(
            "serve_request", req.enqueued,
            time.perf_counter() - req.enqueued, ctx=req.trace,
            root=True, cat="serve",
            args={"result": result, "request_id": req.request_id})
    if result == "timeout":
        # deadline-miss bursts are the stalled-backend signature: the
        # monitor dumps the flight record when they cluster
        trace.anomaly.deadline_miss()


class Request:
    """One queued inference request.

    ``inputs`` is a tuple of numpy arrays (one per model input);
    ``bucket_class`` is the hashable bucket the runner assigned (only
    same-class requests are batched together); ``deadline`` is a
    monotonic timestamp or None; ``request_id`` is the client's
    correlation id (X-Request-Id) — when tracing is on it becomes the
    request's trace id, so its flight-record spans are greppable by
    the id the client logged."""

    __slots__ = ("inputs", "single", "bucket_class", "future",
                 "enqueued", "deadline", "request_id", "trace")

    def __init__(self, inputs, bucket_class, deadline=None, single=True,
                 request_id=None):
        self.inputs = tuple(inputs)
        self.single = single
        self.bucket_class = bucket_class
        self.future = Future()
        self.enqueued = time.perf_counter()
        self.deadline = deadline
        self.request_id = request_id
        self.trace = trace.new_request(request_id)  # None when disabled
        if self.trace is not None:
            trace.instant("serve_enqueue", cat="serve", ctx=self.trace,
                          args={"request_id": request_id})

    def expired(self, now=None):
        return self.deadline is not None and \
            (time.perf_counter() if now is None else now) >= self.deadline


class BatchQueue:
    """Bounded FIFO with class-grouped batch collection.

    ``put`` never blocks: it raises ``ServerOverloaded`` when ``depth``
    requests are already queued (reject-early backpressure) and
    ``ServerClosed`` after ``close()``.  ``collect`` is the scheduler's
    side: it blocks for the next micro-batch, expiring dead requests
    along the way, and returns None once the queue is closed AND
    drained."""

    def __init__(self, depth):
        self._depth = int(depth)
        self._items = deque()
        self._cond = threading.Condition()
        self._closed = False

    def __len__(self):
        return len(self._items)

    def oldest_age(self):
        """Seconds the head-of-line request has waited (0.0 when
        empty) — the queue-age signal the fleet router's load-aware
        dispatch weighs (depth alone hides a stuck scheduler)."""
        with self._cond:
            if not self._items:
                return 0.0
            return max(0.0, time.perf_counter() - self._items[0].enqueued)

    @property
    def closed(self):
        return self._closed

    def put(self, req):
        with self._cond:
            if self._closed:
                raise ServerClosed("server is shut down")
            if len(self._items) >= self._depth:
                if telemetry.ENABLED:
                    telemetry.SERVE_REQUESTS.labels(result="rejected").inc()
                raise ServerOverloaded(
                    "batch queue full (%d queued, depth=%d): retry with "
                    "backoff" % (len(self._items), self._depth))
            self._items.append(req)
            if telemetry.ENABLED:
                telemetry.SERVE_QUEUE_DEPTH.set(len(self._items))
            self._cond.notify_all()

    def close(self):
        """Stop accepting requests; ``collect`` drains what is queued."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def cancel_pending(self):
        """Fail every queued request with ServerClosed (abort path)."""
        with self._cond:
            items, self._items = list(self._items), deque()
            if telemetry.ENABLED:
                telemetry.SERVE_QUEUE_DEPTH.set(0)
            self._cond.notify_all()
        for req in items:
            fail_request(req, ServerClosed("server shut down before dispatch"),
                  "cancelled")

    def _expire_locked(self):
        if not self._items:
            return
        now = time.perf_counter()
        live = deque(r for r in self._items if not r.expired(now))
        if len(live) != len(self._items):
            dead = [r for r in self._items if r.expired(now)]
            self._items = live
            if telemetry.ENABLED:
                telemetry.SERVE_QUEUE_DEPTH.set(len(self._items))
            for req in dead:
                fail_request(req, RequestTimeout(
                    "deadline expired after %.1f ms in queue"
                    % ((now - req.enqueued) * 1e3)), "timeout")

    def collect(self, max_batch, max_wait):
        """Block for the next micro-batch: up to ``max_batch`` queued
        requests of the head request's bucket class, waiting at most
        ``max_wait`` seconds from the head's ENQUEUE for stragglers — a
        request that already sat out its window while the scheduler ran
        the previous batch dispatches immediately.  Returns None when
        closed and drained."""
        max_batch = max(1, int(max_batch))
        with self._cond:
            while True:
                self._expire_locked()
                if not self._items:
                    if self._closed:
                        return None
                    self._cond.wait(timeout=0.5)
                    continue
                cls = self._items[0].bucket_class
                t_end = self._items[0].enqueued + max_wait
                while not self._closed:
                    n = sum(1 for r in self._items
                            if r.bucket_class == cls)
                    if n >= max_batch:
                        break
                    remaining = t_end - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                    self._expire_locked()
                    if not self._items:
                        break
                    if not any(r.bucket_class == cls
                               for r in self._items):
                        cls = self._items[0].bucket_class
                        t_end = self._items[0].enqueued + max_wait
                batch, rest = [], deque()
                for r in self._items:
                    if r.bucket_class == cls and len(batch) < max_batch:
                        batch.append(r)
                    else:
                        rest.append(r)
                self._items = rest
                if telemetry.ENABLED:
                    telemetry.SERVE_QUEUE_DEPTH.set(len(self._items))
                if batch:
                    return batch


class Scheduler:
    """The single dispatch loop: collect a micro-batch, hand it to the
    CURRENT model runner, resolve futures.

    ``runner_fn`` is called once per batch — that one read is the hot
    model swap's atomicity point: a batch runs either entirely on the
    old runner or entirely on the new one.

    Failure containment (mx.resilience): a batch whose execution
    raises is retried **bisected** down to singles, so a poisoned
    request fails alone and its batch-mates still get answers
    (``serve_poison_requests_total``); repeated failed dispatches of
    one bucket open that bucket's circuit breaker (``breakers``, a
    ``breaker.BreakerBoard``) and its requests are quarantined with
    ``BucketQuarantined`` until the cooldown's half-open trial
    succeeds.  Every path resolves every future — the scheduler
    thread itself never dies to a model error."""

    def __init__(self, queue, runner_fn, max_batch_size=8, max_wait_us=2000,
                 breakers=None):
        self._queue = queue
        self._runner_fn = runner_fn
        self._max_batch = int(max_batch_size)
        self._max_wait = float(max_wait_us) / 1e6
        self._breakers = breakers
        self._thread = None

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="mx-serve-scheduler")
        self._thread.start()

    @property
    def alive(self):
        return self._thread is not None and self._thread.is_alive()

    def _loop(self):
        while True:
            try:
                batch = self._queue.collect(self._max_batch, self._max_wait)
            except BaseException:  # collect must never kill the loop
                continue
            if batch is None:
                return
            self._dispatch(batch)

    def _dispatch(self, batch):
        # deadline re-check: time passed between collect and dispatch
        now = time.perf_counter()
        live = []
        for req in batch:
            if req.expired(now) or req.future.cancelled():
                if req.expired(now):
                    fail_request(req, RequestTimeout(
                        "deadline expired before dispatch"), "timeout")
                continue
            live.append(req)
        if not live:
            return
        if telemetry.ENABLED:
            telemetry.SERVE_BATCHES.inc()
            telemetry.SERVE_BATCH_SIZE.observe(len(live))
            for req in live:
                telemetry.SERVE_QUEUE_WAIT_SECONDS.observe(
                    now - req.enqueued)
        head = live[0]
        if trace.ENABLED:
            # queue-wait is reconstructed per request from its enqueue
            # timestamp: the span lived before any scheduler-thread
            # context existed for it
            for req in live:
                if req.trace is not None:
                    trace.record_span("serve_queue_wait", req.enqueued,
                                      now - req.enqueued, ctx=req.trace,
                                      cat="serve")
        cls = head.bucket_class
        if self._breakers is not None and not self._breakers.allow(cls):
            exc = self._breakers.quarantine_error(cls)
            for req in live:
                fail_request(req, exc, "quarantined")
            return
        runner = self._runner_fn()
        if runner is None:
            # the owning Server was garbage-collected (dropped without
            # shutdown): fail whatever is queued and wind the loop down
            exc = ServerClosed("server was dropped without shutdown")
            for req in live:
                fail_request(req, exc, "cancelled")
            self._queue.close()
            self._queue.cancel_pending()
            return
        try:
            # batch-level spans (pad/execute/unpad inside the runner)
            # adopt the HEAD request's trace context — for a batch the
            # other members are linked through the `requests` arg list
            with trace.use(head.trace), \
                    trace.span("serve_dispatch", hist=False, cat="serve",
                               args={"batch": len(live),
                                     "requests": [
                                         r.trace.trace_id for r in live
                                         if r.trace is not None]}), \
                    trace.watchdog.watch("serve_dispatch"):
                _inject.fire("serve_dispatch")
                pairs = self._run_split(runner, live)
        except BaseException as exc:  # noqa: BLE001 - surfaced per-request
            for req in live:
                fail_request(req, exc, "error")
            if self._breakers is not None:
                self._breakers.failure(cls)
            return
        failed = [p for p in pairs if p[2] is not None]
        if self._breakers is not None:
            # one strike per DISPATCH (not per request): the bisect
            # already confined the damage; the breaker watches for the
            # whole bucket going repeatedly bad
            if failed:
                self._breakers.failure(cls)
            else:
                self._breakers.success(cls)
        # "poisoned" means the request failed ALONE while at least one
        # batch-mate was served — a bucket-wide systemic failure (every
        # single fails after bisection) is an "error" story, not a
        # poison one, and must not inflate the poison counter
        any_ok = any(p[2] is None for p in pairs)
        with trace.use(head.trace), \
                trace.span("serve_respond", hist=False, cat="serve"):
            done_t = time.perf_counter()
            for req, res, exc, isolated in pairs:
                if exc is not None:
                    poisoned = isolated and any_ok
                    if poisoned and telemetry.ENABLED:
                        telemetry.SERVE_POISON.inc()
                    fail_request(req, exc,
                          "poisoned" if poisoned else "error")
                    continue
                try:
                    req.future.set_result(res)
                except InvalidStateError:
                    continue
                if telemetry.ENABLED:
                    telemetry.SERVE_REQUESTS.labels(result="ok").inc()
                    telemetry.SERVE_REQUEST_SECONDS.observe(
                        done_t - req.enqueued)
                if trace.ENABLED and req.trace is not None:
                    # the request's root span: enqueue -> result set
                    trace.record_span(
                        "serve_request", req.enqueued,
                        done_t - req.enqueued, ctx=req.trace, root=True,
                        cat="serve", args={"result": "ok",
                                           "request_id": req.request_id})

    def _run_split(self, runner, reqs, depth=0):
        """Run ``reqs``; on failure retry bisected until single
        requests, so one poisoned request cannot fail its batch-mates.
        Returns ``[(req, result, exc, isolated)]`` aligned with
        ``reqs`` — ``exc`` set for failures, ``isolated`` True when
        the failure was pinned to a single request by bisection.  At
        most ``2n - 1`` executions for a batch of n (and only when
        something actually fails)."""
        try:
            bad = [r for r in reqs
                   if _inject.poisoned(r.request_id)]
            if bad:
                if len(reqs) == 1:
                    _inject.record_firing("serve_poison",
                                          bad[0].request_id,
                                          consume=True)
                raise InjectedFault(
                    "injected poison request %s"
                    % [r.request_id for r in bad],
                    site="serve_poison")
            results = runner.run_batch(reqs)
        except BaseException as exc:  # noqa: BLE001 - contained below
            if len(reqs) == 1:
                isolated = depth > 0 or \
                    getattr(exc, "site", None) == "serve_poison"
                return [(reqs[0], None, exc, isolated)]
            if telemetry.ENABLED:
                telemetry.SERVE_BISECT_SPLITS.inc()
            trace.instant("serve_bisect", cat="serve",
                          args={"requests": len(reqs),
                                "depth": depth,
                                "error": type(exc).__name__})
            mid = len(reqs) // 2
            return self._run_split(runner, reqs[:mid], depth + 1) + \
                self._run_split(runner, reqs[mid:], depth + 1)
        return [(req, res, None, False)
                for req, res in zip(reqs, results)]

    def stop(self, drain=True, timeout=None):
        """Close the queue and join the loop.  With ``drain`` (default)
        queued requests are served first; otherwise they fail with
        ServerClosed immediately."""
        self._queue.close()
        if not drain:
            self._queue.cancel_pending()
        if self._thread is not None:
            self._thread.join(timeout)
        return not self.alive
