"""Paged KV-cache storage for mx.serve.decode.

Decoder-LLM serving state is the KV cache, and the KV cache is why
fixed-shape batching fails for autoregressive traffic: every sequence
has a different length at every step, and a contiguous
``[batch, max_len, ...]`` allocation wastes ``O(max_len)`` device
memory per sequence from token one.  The fix (vLLM's PagedAttention)
is blocked storage: the cache is a pool of fixed-size **pages**
(``page_size`` token slots each), and each sequence owns a *page
table* — an ordered list of physical page ids its logical positions
map onto.  Admission reserves a sequence's whole worst case
(``ceil((prompt + max_new_tokens) / page_size)`` pages) up front, so a
running sequence can NEVER hit an allocation failure mid-decode: OOM
is a fast, explicit reject at the admission door, not a crash three
hundred tokens in.

``PagePool`` owns:

- the device-resident cache arrays — one K and one V array shaped
  ``[layers, num_pages, page_size, kv_heads, head_dim]``, threaded
  through the jitted decode-step program with buffer donation (the
  pool is updated in place, never copied per step);
- exact occupancy accounting: ``alloc`` / ``release`` / ``reset`` with
  a free list, per-owner page ledger, in-use / high-water counters,
  and hard invariants (double-free and unknown-owner release raise —
  a leaked page is a serving-capacity leak that compounds forever);
- the **shared segment** backing the mx.serve.cache radix prefix
  cache: ``adopt_shared`` moves immutable prefix pages out of one
  owner's ledger into a refcounted shared pool (``shared_ref`` /
  ``shared_unref``), so identical prompt prefixes are stored once and
  read by many sequences copy-on-write.  A shared page returns to the
  free list only when its LAST reference drops — an evicted prefix
  never yanks storage out from under a live reader — and ``check()``
  audits ``free + owned + shared == capacity`` with the same
  double-free-raises discipline.

The jax-side page-table address arithmetic lives here too so the
decode-step program and the pool agree on the layout by construction:
``gather_pages`` materializes a sequence's pages as a contiguous
``[B, L, S, H, D]`` context (clamp-mode gather: table slots past a
sequence's allocation read garbage that the attention length mask
provably ignores), and ``scatter_pages`` writes the step's fresh K/V
into ``(page, slot)`` addresses (drop-mode scatter: padded batch slots
and padded prompt positions carry an out-of-bounds page id and write
nowhere).
"""
from __future__ import annotations

import threading

from .batching import ServeError

__all__ = ["PageConfig", "PagePool", "PagePoolExhausted",
           "gather_pages", "scatter_pages"]


class PagePoolExhausted(ServeError):
    """Not enough free pages for the requested reservation.  Raised at
    ADMISSION time (fast OOM-reject) — never mid-decode, because
    admission reserves a sequence's whole worst case up front."""


class PageConfig:
    """Geometry of one paged KV pool: pool shape (``page_size`` token
    slots per page x ``num_pages`` pages) plus the per-token cache
    shape of the model it serves (``num_layers`` x ``num_kv_heads`` x
    ``head_dim``, ``dtype``).  ``max_context`` bounds any single
    sequence (prompt + generated); it must fit the pool."""

    def __init__(self, page_size, num_pages, num_layers, num_kv_heads,
                 head_dim, max_context, dtype="float32"):
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.max_context = int(max_context)
        self.dtype = dtype
        if self.page_size < 1 or self.num_pages < 1:
            raise ValueError("page_size and num_pages must be >= 1, got "
                             "%d / %d" % (self.page_size, self.num_pages))
        if self.max_context < 1:
            raise ValueError("max_context must be >= 1")
        if self.pages_per_seq > self.num_pages:
            raise ValueError(
                "max_context=%d needs %d pages/sequence but the pool "
                "only has %d pages total" % (
                    self.max_context, self.pages_per_seq, self.num_pages))

    @property
    def pages_per_seq(self):
        """Page-table width: the worst-case pages one sequence can own."""
        return -(-self.max_context // self.page_size)

    def pages_for(self, total_tokens):
        """Pages a sequence of ``total_tokens`` (prompt + max new) must
        reserve at admission."""
        return max(1, -(-int(total_tokens) // self.page_size))

    @property
    def page_bytes(self):
        import numpy as _np

        return (self.num_layers * self.page_size * self.num_kv_heads *
                self.head_dim * _np.dtype(self.dtype).itemsize * 2)

    def as_dict(self):
        return {"page_size": self.page_size, "num_pages": self.num_pages,
                "num_layers": self.num_layers,
                "num_kv_heads": self.num_kv_heads,
                "head_dim": self.head_dim,
                "max_context": self.max_context,
                "pages_per_seq": self.pages_per_seq,
                "dtype": str(self.dtype),
                "pool_bytes": self.num_pages * self.page_bytes}


class PagePool:
    """Blocked KV-cache storage + exact page accounting (module doc).

    The device arrays ``k`` / ``v`` are plain attributes the decode
    loop re-binds after every donated step dispatch; accounting is
    host-side and lock-protected (admission runs on submitter threads,
    release on the decode loop)."""

    def __init__(self, config, mesh=None):
        import jax.numpy as jnp

        self.config = config
        c = config
        shape = (c.num_layers, c.num_pages, c.page_size,
                 c.num_kv_heads, c.head_dim)
        # mx.shard phase 2: on a mesh with an mdl axis the pool shards
        # over the KV-HEAD axis (per-head attention state is
        # independent, so a head split never slices a page row) — each
        # device holds 1/mdl of the cache, which is what makes
        # multi-chip decode residency real.  Indivisible head counts
        # stay replicated (correct, just not smaller).
        self.sharding = None
        if mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            raw = getattr(mesh, "mesh", mesh)   # GlobalMesh or raw Mesh
            axes = dict(getattr(raw, "shape", {}) or {})
            mdl = int(axes.get("mdl", 1))
            spec = P(None, None, None, "mdl", None) \
                if mdl > 1 and c.num_kv_heads % mdl == 0 else P()
            self.sharding = NamedSharding(raw, spec)
            self.k = jax.device_put(jnp.zeros(shape, dtype=c.dtype),
                                    self.sharding)
            self.v = jax.device_put(jnp.zeros(shape, dtype=c.dtype),
                                    self.sharding)
        else:
            self.k = jnp.zeros(shape, dtype=c.dtype)
            self.v = jnp.zeros(shape, dtype=c.dtype)
        self._lock = threading.Lock()
        self._free = list(range(c.num_pages - 1, -1, -1))  # pop() -> 0,1,2..
        self._owned = {}                 # owner -> [page ids]
        self._shared = {}                # page id -> refcount (>= 1)
        self.high_water = 0
        self.alloc_total = 0
        self.oom_rejects = 0

    # -- accounting ---------------------------------------------------------
    @property
    def capacity(self):
        return self.config.num_pages

    @property
    def available(self):
        with self._lock:
            return len(self._free)

    @property
    def in_use(self):
        with self._lock:
            return self.config.num_pages - len(self._free)

    @property
    def null_page(self):
        """Out-of-bounds page id padded page-table slots carry: the
        drop-mode scatter writes addressed to it write nowhere."""
        return self.config.num_pages

    def can_alloc(self, n):
        with self._lock:
            return n <= len(self._free)

    def alloc(self, owner, n):
        """Reserve ``n`` pages for ``owner`` (all-or-nothing).  Raises
        ``PagePoolExhausted`` without touching anything when fewer than
        ``n`` pages are free — the fast OOM-reject admission control
        leans on."""
        n = int(n)
        if n < 1:
            raise ValueError("alloc needs n >= 1, got %d" % n)
        with self._lock:
            if owner in self._owned:
                raise ServeError("owner %r already holds pages" % (owner,))
            if n > len(self._free):
                self.oom_rejects += 1
                raise PagePoolExhausted(
                    "KV page pool exhausted: %d page(s) requested, %d free "
                    "of %d (page_size=%d); admission must wait for "
                    "evictions" % (n, len(self._free),
                                   self.config.num_pages,
                                   self.config.page_size))
            pages = [self._free.pop() for _ in range(n)]
            self._owned[owner] = pages
            self.alloc_total += n
            used = self.config.num_pages - len(self._free)
            if used > self.high_water:
                self.high_water = used
            return list(pages)

    def release(self, owner):
        """Return every page ``owner`` holds.  Unknown owners raise —
        a silent no-op would hide the double-free/leak bugs the
        accounting exists to catch."""
        with self._lock:
            pages = self._owned.pop(owner, None)
            if pages is None:
                raise ServeError("release of unknown page owner %r"
                                 % (owner,))
            for p in pages:
                self._free.append(p)
            return len(pages)

    def owners(self):
        with self._lock:
            return {o: list(p) for o, p in self._owned.items()}

    def pages_by_group(self, group_of):
        """Live private-page counts rolled up by ``group_of(owner)``
        (e.g. the owning tenant) — how mx.tenant audits per-tenant KV
        residency against its quota ledger.  ``group_of`` returning
        None buckets the owner under ``None`` (base traffic); shared
        prefix pages are global, not attributed."""
        out = {}
        with self._lock:
            items = [(o, len(p)) for o, p in self._owned.items()]
        for owner, n in items:
            key = group_of(owner)
            out[key] = out.get(key, 0) + n
        return out

    # -- shared segment (mx.serve.cache radix prefix cache) -----------------
    def adopt_shared(self, owner, pages, readers=1):
        """Move ``pages`` (a subset of ``owner``'s ledger) into the
        shared segment as immutable prefix storage.  Each page's
        refcount starts at ``1 + readers``: one structural reference
        for the adopting cache plus one per live reader that already
        holds the page in its table.  The owner keeps its remaining
        (private) pages; totals are unchanged — adoption is a ledger
        move, never an allocation."""
        pages = [int(p) for p in pages]
        with self._lock:
            owned = self._owned.get(owner)
            if owned is None:
                raise ServeError(
                    "adopt_shared from unknown page owner %r" % (owner,))
            for p in pages:
                if p not in owned:
                    raise ServeError(
                        "adopt_shared: page %d is not owned by %r"
                        % (p, owner))
                if p in self._shared:
                    raise ServeError(
                        "adopt_shared: page %d is already shared" % p)
            for p in pages:
                owned.remove(p)
                self._shared[p] = 1 + int(readers)

    def shared_ref(self, pages):
        """Take one reference per page (a cache hit attaching a reader
        to an existing prefix).  Unknown pages raise — referencing a
        page that is not in the shared segment is the read half of a
        use-after-free."""
        pages = [int(p) for p in pages]
        with self._lock:
            for p in pages:
                if p not in self._shared:
                    raise ServeError(
                        "shared_ref of non-shared page %d" % p)
            for p in pages:
                self._shared[p] += 1

    def shared_unref(self, pages):
        """Drop one reference per page; pages reaching refcount 0
        return to the free list.  Over-release raises (the shared
        segment's double-free guard).  Returns the number of pages
        actually freed."""
        freed = 0
        with self._lock:
            for p in [int(p) for p in pages]:
                n = self._shared.get(p)
                if not n:
                    raise ServeError(
                        "shared double-free of page %d" % p)
                n -= 1
                if n == 0:
                    del self._shared[p]
                    self._free.append(p)
                    freed += 1
                else:
                    self._shared[p] = n
        return freed

    @property
    def shared_pages(self):
        with self._lock:
            return len(self._shared)

    def shared_refs(self):
        with self._lock:
            return dict(self._shared)

    def reset(self):
        """Free everything (scheduler teardown); storage is reused."""
        with self._lock:
            self._owned.clear()
            self._shared.clear()
            self._free = list(range(self.config.num_pages - 1, -1, -1))

    def check(self):
        """Invariant audit: free + owned + shared == capacity, no
        duplicates, every shared refcount >= 1.  Raises ``ServeError``
        on violation; returns True."""
        with self._lock:
            owned = [p for pages in self._owned.values() for p in pages]
            shared = list(self._shared)
            seen = self._free + owned + shared
            if len(seen) != self.config.num_pages or \
                    len(set(seen)) != len(seen):
                raise ServeError(
                    "page accounting corrupt: %d free + %d owned + %d "
                    "shared != %d capacity (or duplicate ids)" % (
                        len(self._free), len(owned), len(shared),
                        self.config.num_pages))
            if any(n < 1 for n in self._shared.values()):
                raise ServeError("shared page with refcount < 1")
        return True

    def device_bytes(self):
        """Bytes of the K+V arrays resident on ONE device — the number
        the sharded-decode residency bound asserts (1/mdl of the pool
        when head-sharded, the full pool otherwise)."""
        from ..shard import device_bytes as _db

        return _db([self.k, self.v])

    def stats(self):
        with self._lock:
            free = len(self._free)
            owners = len(self._owned)
            shared = len(self._shared)
        cap = self.config.num_pages
        return {
            "kv_sharding": None if self.sharding is None
            else str(self.sharding.spec),
            "kv_device_bytes": self.device_bytes()
            if self.sharding is not None else None,
            "capacity_pages": cap,
            "in_use_pages": cap - free,
            "free_pages": free,
            "shared_pages": shared,
            "high_water_pages": self.high_water,
            "occupancy": round((cap - free) / cap, 4),
            "owners": owners,
            "alloc_total": self.alloc_total,
            "oom_rejects": self.oom_rejects,
            "config": self.config.as_dict(),
        }


# ---------------------------------------------------------------------------
# jax-side page address arithmetic (traced inside the decode-step program)
# ---------------------------------------------------------------------------

def gather_pages(pool, tables):
    """Materialize each sequence's paged cache as a contiguous context.

    ``pool`` is ``[L, N, page, H, D]``; ``tables`` is ``[B, P]`` int32
    physical page ids.  Returns ``[B, L, P * page, H, D]``.  Gather is
    clamp-mode (jax default under jit): table slots past a sequence's
    allocation may read arbitrary pages, but those positions are
    ``>= length`` and the attention mask discards them."""
    import jax.numpy as jnp

    g = pool[:, jnp.clip(tables, 0, pool.shape[1] - 1)]
    lyr, b, p, page, h, d = g.shape
    return jnp.transpose(g, (1, 0, 2, 3, 4, 5)).reshape(
        b, lyr, p * page, h, d)


def scatter_pages(pool, tables, positions, valid, new):
    """Write one chunk's fresh K or V rows into their pages.

    ``new`` is ``[B, T, L, H, D]`` (the model's per-position cache
    rows), ``positions`` ``[B, T]`` absolute token positions, ``valid``
    ``[B, T]`` bool.  Invalid positions (prompt padding, padded batch
    slots) are redirected to the out-of-bounds null page and dropped by
    the scatter mode — the pool is only ever written at addresses the
    owning sequence reserved."""
    import jax.numpy as jnp

    page_size = pool.shape[2]
    npages = pool.shape[1]
    logical = jnp.clip(positions // page_size, 0, tables.shape[1] - 1)
    phys = jnp.take_along_axis(tables, logical, axis=1)       # [B, T]
    phys = jnp.where(valid, phys, npages)                     # OOB -> drop
    slot = positions % page_size                              # [B, T]
    rows = jnp.transpose(new, (2, 0, 1, 3, 4))                # [L,B,T,H,D]
    return pool.at[:, phys, slot].set(rows, mode="drop")
