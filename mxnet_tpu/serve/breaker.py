"""Per-bucket circuit breakers for mx.serve.

A bucket whose dispatches keep failing (a poisoned input class, a
compiled signature that traps, a shape-specific model bug) must not be
allowed to burn scheduler time and batch-mates forever.  Each bucket
class gets a classic three-state breaker:

- **closed** — normal traffic; consecutive failed dispatches are
  counted, successes reset the count.
- **open** — after ``threshold`` consecutive failures the bucket is
  quarantined: submissions and dispatches are rejected immediately
  (HTTP 503 + ``Retry-After``) for ``cooldown`` seconds.  Other
  buckets are untouched.
- **half-open** — after the cooldown EXACTLY ONE trial dispatch is
  admitted; while it is in flight every other submit/dispatch keeps
  fast-rejecting (a thundering herd re-probing a sick bucket
  concurrently is indistinguishable from no breaker at all).  Success
  closes the breaker, failure re-opens it for a fresh cooldown, and a
  trial that never resolves (its dispatch path died without recording
  an outcome) self-heals: a new trial is allowed one cooldown after
  the stuck one was admitted.

State is surfaced in ``/healthz`` and ``/statz`` (and the
``serve_breaker_state`` gauge: 0 closed / 1 half-open / 2 open), so an
operator sees "bucket 8x128,16 quarantined" instead of a mystery
throughput dip.  Failures are counted per *dispatch*, not per request:
one poison-heavy batch is one strike, and the bisect retry (see
``batching.Scheduler``) has already confined the damage to the
poisoned request itself.
"""
from __future__ import annotations

import threading
import time

from .. import telemetry

__all__ = ["CLOSED", "OPEN", "HALF_OPEN", "CircuitBreaker",
           "BreakerBoard"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """One bucket's breaker (see module doc).  ``clock`` is injectable
    for deterministic tests."""

    def __init__(self, threshold=5, cooldown=30.0, clock=time.monotonic,
                 label=None):
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown)
        self._clock = clock
        self._label = label
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0          # consecutive, while closed
        self._opened_at = None
        self._trial_at = None       # half-open trial admission time
        self.trips = 0              # lifetime closed/half-open -> open

    def _set_state(self, state):
        self._state = state
        if state != HALF_OPEN:
            self._trial_at = None
        if telemetry.ENABLED and self._label is not None:
            telemetry.SERVE_BREAKER_STATE.labels(
                bucket=self._label).set(_STATE_GAUGE[state])

    def _maybe_half_open_locked(self, now):
        if self._state == OPEN and \
                now - self._opened_at >= self.cooldown:
            self._set_state(HALF_OPEN)

    def _trial_inflight_locked(self, now):
        # a trial that was admitted but whose outcome never landed
        # (its dispatch path died) expires after one cooldown, so the
        # bucket cannot be stuck half-open-and-rejecting forever
        return self._trial_at is not None and \
            now - self._trial_at < self.cooldown

    def blocked(self):
        """Non-mutating probe for submit-time fast-reject: True while
        OPEN with cooldown remaining, and while the half-open trial is
        in flight (only the single trial may probe the bucket; every
        other concurrent request keeps fast-rejecting)."""
        with self._lock:
            now = self._clock()
            self._maybe_half_open_locked(now)
            if self._state == OPEN:
                return True
            return self._state == HALF_OPEN and \
                self._trial_inflight_locked(now)

    def allow(self):
        """Dispatch-time gate.  CLOSED admits; OPEN rejects until the
        cooldown elapses; HALF_OPEN admits EXACTLY ONE caller — the
        first ``allow()`` after the cooldown is the trial, and every
        other caller is rejected until that trial resolves via
        ``record_success``/``record_failure``."""
        with self._lock:
            now = self._clock()
            self._maybe_half_open_locked(now)
            if self._state == OPEN:
                return False
            if self._state == HALF_OPEN:
                if self._trial_inflight_locked(now):
                    return False
                self._trial_at = now   # this caller IS the trial
            return True

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._trial_at = None
            if self._state != CLOSED:
                self._set_state(CLOSED)
                self._opened_at = None

    def record_failure(self):
        """One failed dispatch; returns True when this strike opened
        (or re-opened) the breaker."""
        with self._lock:
            now = self._clock()
            self._maybe_half_open_locked(now)
            if self._state == HALF_OPEN:
                tripped = True          # the trial failed: re-open
                self._trial_at = None
            else:
                self._failures += 1
                tripped = self._state == CLOSED and \
                    self._failures >= self.threshold
            if tripped:
                self._set_state(OPEN)
                self._opened_at = now
                self._failures = 0
                self.trips += 1
        if tripped and telemetry.ENABLED and self._label is not None:
            telemetry.SERVE_BREAKER_TRIPS.labels(
                bucket=self._label).inc()
        return tripped

    def retry_after(self):
        """Seconds until the next half-open trial (0 when admitting)."""
        with self._lock:
            if self._state != OPEN or self._opened_at is None:
                return 0.0
            return max(0.0, self.cooldown -
                       (self._clock() - self._opened_at))

    def state(self):
        with self._lock:
            now = self._clock()
            self._maybe_half_open_locked(now)
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "trial_inflight": self._state == HALF_OPEN
                and self._trial_inflight_locked(now),
                "trips": self.trips,
                "retry_after_seconds": round(
                    max(0.0, self.cooldown -
                        (self._clock() - self._opened_at))
                    if self._state == OPEN and self._opened_at
                    is not None else 0.0, 3),
            }


class BreakerBoard:
    """The per-bucket breaker registry one Server owns.  Bucket classes
    are the scheduler's hashable classes (sample-bucket index or exact
    shape tuple); breakers are created lazily on first traffic."""

    def __init__(self, threshold=5, cooldown=30.0, clock=time.monotonic):
        self.threshold = int(threshold)
        self.cooldown = float(cooldown)
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers = {}

    @staticmethod
    def label(cls):
        return str(cls)

    def _get(self, cls):
        with self._lock:
            b = self._breakers.get(cls)
            if b is None:
                b = self._breakers[cls] = CircuitBreaker(
                    self.threshold, self.cooldown, clock=self._clock,
                    label=self.label(cls))
            return b

    def _peek(self, cls):
        with self._lock:
            return self._breakers.get(cls)

    # read probes NEVER allocate: only a recorded failure creates a
    # breaker, so the board grows with failing buckets, not with
    # traffic — in exact-shape mode bucket classes are client-
    # controlled shape tuples and a per-request allocating probe would
    # let clients grow the board without bound

    def blocked(self, cls):
        b = self._peek(cls)
        return False if b is None else b.blocked()

    def allow(self, cls):
        b = self._peek(cls)
        return True if b is None else b.allow()

    def success(self, cls):
        b = self._peek(cls)
        if b is not None:
            b.record_success()

    def failure(self, cls):
        return self._get(cls).record_failure()

    def retry_after(self, cls):
        b = self._peek(cls)
        return 0.0 if b is None else b.retry_after()

    def quarantine_error(self, cls):
        """The one consistent ``BucketQuarantined`` for this bucket —
        a single ``retry_after`` read feeds both the message and the
        attribute (two reads could disagree across the cooldown
        boundary), and submit/dispatch share the wording."""
        from .batching import BucketQuarantined

        ra = self.retry_after(cls)
        return BucketQuarantined(
            "bucket %r quarantined by its circuit breaker (repeated "
            "dispatch failures); retry after %.1fs" % (cls, ra),
            retry_after=ra)

    def snapshot(self):
        with self._lock:
            items = list(self._breakers.items())
        return {self.label(cls): b.state() for cls, b in items}
