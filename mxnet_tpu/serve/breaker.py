"""Per-bucket circuit breakers for mx.serve.

A bucket whose dispatches keep failing (a poisoned input class, a
compiled signature that traps, a shape-specific model bug) must not be
allowed to burn scheduler time and batch-mates forever.  Each bucket
class gets a classic three-state breaker:

- **closed** — normal traffic; consecutive failed dispatches are
  counted, successes reset the count.
- **open** — after ``threshold`` consecutive failures the bucket is
  quarantined: submissions and dispatches are rejected immediately
  (HTTP 503 + ``Retry-After``) for ``cooldown`` seconds.  Other
  buckets are untouched.
- **half-open** — after the cooldown ONE trial dispatch is let
  through; success closes the breaker, failure re-opens it for a
  fresh cooldown.

State is surfaced in ``/healthz`` and ``/statz`` (and the
``serve_breaker_state`` gauge: 0 closed / 1 half-open / 2 open), so an
operator sees "bucket 8x128,16 quarantined" instead of a mystery
throughput dip.  Failures are counted per *dispatch*, not per request:
one poison-heavy batch is one strike, and the bisect retry (see
``batching.Scheduler``) has already confined the damage to the
poisoned request itself.
"""
from __future__ import annotations

import threading
import time

from .. import telemetry

__all__ = ["CLOSED", "OPEN", "HALF_OPEN", "CircuitBreaker",
           "BreakerBoard"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """One bucket's breaker (see module doc).  ``clock`` is injectable
    for deterministic tests."""

    def __init__(self, threshold=5, cooldown=30.0, clock=time.monotonic,
                 label=None):
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown)
        self._clock = clock
        self._label = label
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0          # consecutive, while closed
        self._opened_at = None
        self.trips = 0              # lifetime closed/half-open -> open

    def _set_state(self, state):
        self._state = state
        if telemetry.ENABLED and self._label is not None:
            telemetry.SERVE_BREAKER_STATE.labels(
                bucket=self._label).set(_STATE_GAUGE[state])

    def _maybe_half_open_locked(self, now):
        if self._state == OPEN and \
                now - self._opened_at >= self.cooldown:
            self._set_state(HALF_OPEN)

    def blocked(self):
        """Non-mutating probe for submit-time fast-reject: True only
        while OPEN with cooldown remaining.  (Half-open admits traffic
        so the trial dispatch can happen.)"""
        with self._lock:
            self._maybe_half_open_locked(self._clock())
            return self._state == OPEN

    def allow(self):
        """Dispatch-time gate.  CLOSED/HALF_OPEN admit (the half-open
        admission IS the trial); OPEN rejects until the cooldown
        elapses."""
        with self._lock:
            self._maybe_half_open_locked(self._clock())
            return self._state != OPEN

    def record_success(self):
        with self._lock:
            self._failures = 0
            if self._state != CLOSED:
                self._set_state(CLOSED)
                self._opened_at = None

    def record_failure(self):
        """One failed dispatch; returns True when this strike opened
        (or re-opened) the breaker."""
        with self._lock:
            now = self._clock()
            self._maybe_half_open_locked(now)
            if self._state == HALF_OPEN:
                tripped = True          # the trial failed: re-open
            else:
                self._failures += 1
                tripped = self._state == CLOSED and \
                    self._failures >= self.threshold
            if tripped:
                self._set_state(OPEN)
                self._opened_at = now
                self._failures = 0
                self.trips += 1
        if tripped and telemetry.ENABLED and self._label is not None:
            telemetry.SERVE_BREAKER_TRIPS.labels(
                bucket=self._label).inc()
        return tripped

    def retry_after(self):
        """Seconds until the next half-open trial (0 when admitting)."""
        with self._lock:
            if self._state != OPEN or self._opened_at is None:
                return 0.0
            return max(0.0, self.cooldown -
                       (self._clock() - self._opened_at))

    def state(self):
        with self._lock:
            self._maybe_half_open_locked(self._clock())
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "trips": self.trips,
                "retry_after_seconds": round(
                    max(0.0, self.cooldown -
                        (self._clock() - self._opened_at))
                    if self._state == OPEN and self._opened_at
                    is not None else 0.0, 3),
            }


class BreakerBoard:
    """The per-bucket breaker registry one Server owns.  Bucket classes
    are the scheduler's hashable classes (sample-bucket index or exact
    shape tuple); breakers are created lazily on first traffic."""

    def __init__(self, threshold=5, cooldown=30.0, clock=time.monotonic):
        self.threshold = int(threshold)
        self.cooldown = float(cooldown)
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers = {}

    @staticmethod
    def label(cls):
        return str(cls)

    def _get(self, cls):
        with self._lock:
            b = self._breakers.get(cls)
            if b is None:
                b = self._breakers[cls] = CircuitBreaker(
                    self.threshold, self.cooldown, clock=self._clock,
                    label=self.label(cls))
            return b

    def _peek(self, cls):
        with self._lock:
            return self._breakers.get(cls)

    # read probes NEVER allocate: only a recorded failure creates a
    # breaker, so the board grows with failing buckets, not with
    # traffic — in exact-shape mode bucket classes are client-
    # controlled shape tuples and a per-request allocating probe would
    # let clients grow the board without bound

    def blocked(self, cls):
        b = self._peek(cls)
        return False if b is None else b.blocked()

    def allow(self, cls):
        b = self._peek(cls)
        return True if b is None else b.allow()

    def success(self, cls):
        b = self._peek(cls)
        if b is not None:
            b.record_success()

    def failure(self, cls):
        return self._get(cls).record_failure()

    def retry_after(self, cls):
        b = self._peek(cls)
        return 0.0 if b is None else b.retry_after()

    def quarantine_error(self, cls):
        """The one consistent ``BucketQuarantined`` for this bucket —
        a single ``retry_after`` read feeds both the message and the
        attribute (two reads could disagree across the cooldown
        boundary), and submit/dispatch share the wording."""
        from .batching import BucketQuarantined

        ra = self.retry_after(cls)
        return BucketQuarantined(
            "bucket %r quarantined by its circuit breaker (repeated "
            "dispatch failures); retry after %.1fs" % (cls, ra),
            retry_after=ra)

    def snapshot(self):
        with self._lock:
            items = list(self._breakers.items())
        return {self.label(cls): b.state() for cls, b in items}
