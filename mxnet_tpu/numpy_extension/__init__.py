"""``mx.npx`` — numpy-extension ops (reference python/mxnet/
numpy_extension/: the non-numpy "neural" ops usable with mx.np arrays +
np-mode switches)."""
from __future__ import annotations

from ..ndarray import (Activation, BatchNorm, Convolution, Deconvolution,
                       Embedding, FullyConnected, LayerNorm, Pooling,
                       dropout, one_hot, pick, relu, sigmoid, softmax,
                       log_softmax, topk, gamma, erf, erfinv,
                       sequence_mask, gather_nd, reshape, batch_dot,
                       leaky_relu, smooth_l1, group_norm, instance_norm,
                       rms_norm, l2_normalization, ctc_loss,
                       multi_head_attention, quantize, quantize_v2,
                       dequantize, requantize, sort, argsort,
                       take_along_axis, scatter_nd, sequence_last,
                       sequence_reverse, cast)
from ..ndarray.contrib import (foreach, while_loop, cond, isfinite, isnan,
                               isinf, arange_like, index_copy, index_array,
                               boolean_mask)
from ..operator import Custom  # noqa: F401  (npx.Custom)
from ..util import (is_np_array, is_np_shape, reset_np, set_np, use_np,
                    use_np_array, use_np_shape)
from ..context import cpu, current_context, gpu, num_gpus, num_tpus, tpu
from .. import random  # noqa: F401
from ..base import get_env  # noqa: F401
from ..ndarray import image  # noqa: F401  (npx.image op namespace)

fully_connected = FullyConnected
convolution = Convolution
pooling = Pooling
batch_norm = BatchNorm
layer_norm = LayerNorm
embedding = Embedding
activation = Activation


def seed(s):
    random.seed(s)


def waitall():
    from ..ndarray.ndarray import waitall as _w

    return _w()


def load(fname):
    from .. import ndarray as nd

    return nd.load(fname)


def save(fname, data):
    from .. import ndarray as nd

    return nd.save(fname, data)
