"""Weight initializers (reference python/mxnet/initializer.py, 713 LoC:
Xavier/MSRA/Orthogonal/Uniform/Normal/Constant + registry + InitDesc)."""
from __future__ import annotations

import math
import re

import numpy as _np

from .base import MXNetError

__all__ = ["Initializer", "Uniform", "Normal", "Constant", "Zero", "One",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias",
           "Mixed", "InitDesc", "register", "create"]

_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


_ALIASES = {"zeros": "zero", "ones": "one", "gaussian": "normal",
            "msra": "msraprelu", "xavier": "xavier"}


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if callable(name):
        return name
    key = name.lower()
    key = _ALIASES.get(key, key)
    if key not in _INIT_REGISTRY:
        raise MXNetError("unknown initializer %r" % name)
    return _INIT_REGISTRY[key](**kwargs)


class InitDesc(str):
    """Name + attrs describing what is being initialized (reference
    initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        self.init_weight(desc, arr)

    def init_weight(self, name, arr):
        name = str(name)
        if name.endswith("bias"):
            self._init_zero(arr)
        elif name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith("beta"):
            self._init_zero(arr)
        elif "running_mean" in name or "moving_mean" in name:
            self._init_zero(arr)
        elif "running_var" in name or "moving_var" in name:
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    def _init_zero(self, arr):
        _fill(arr, _np.zeros(arr.shape, arr.dtype))

    def _init_one(self, arr):
        _fill(arr, _np.ones(arr.shape, arr.dtype))

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return "%s(%s)" % (type(self).__name__, self._kwargs)


def _fill(arr, value):
    import jax.numpy as jnp

    arr._data = jnp.asarray(_np.asarray(value, dtype=arr.dtype))


def _rng():
    from . import random as mxrand
    import jax

    return mxrand, jax


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        mxrand, jax_ = _rng()
        key = mxrand.take_key()
        arr._data = jax_.random.uniform(key, arr.shape, minval=-self.scale,
                                        maxval=self.scale).astype(arr.dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        mxrand, jax_ = _rng()
        arr._data = (jax_.random.normal(mxrand.take_key(), arr.shape) *
                     self.sigma).astype(arr.dtype)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        _fill(arr, _np.full(arr.shape, self.value, arr.dtype))


@register
class Zero(Constant):
    def __init__(self):
        super().__init__(0.0)


@register
class One(Constant):
    def __init__(self):
        super().__init__(1.0)


@register
class Xavier(Initializer):
    """Reference initializer.py Xavier: rnd_type uniform/gaussian,
    factor_type avg/in/out."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError("Xavier requires ndim >= 2, got %s for %s"
                             % (shape, name))
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        mxrand, jax_ = _rng()
        key = mxrand.take_key()
        if self.rnd_type == "uniform":
            arr._data = jax_.random.uniform(
                key, shape, minval=-scale, maxval=scale).astype(arr.dtype)
        else:
            arr._data = (jax_.random.normal(key, shape) * scale).astype(
                arr.dtype)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        _fill(arr, (self.scale * q).reshape(arr.shape))


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        weight = _np.zeros(int(_np.prod(arr.shape)), dtype="float32")
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        _fill(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = _np.zeros(arr.shape, dtype="float32")
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        _fill(arr, b)


class Mixed:
    """Per-pattern initializer mux (reference initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(name, arr)
                return
        raise MXNetError("no initializer pattern matches %r" % str(name))
