"""Divergence detection over the monitor's stat stream.

Three signatures of a run going bad, each firing ONE flight-record +
chrome-trace dump through the PR 6 anomaly path (``trace/anomaly.py``,
reason ``divergence``, rate-limited like slow_step/deadline_burst):

- **nonfinite gradients** — reported by the sentinel the moment a stat
  vector shows ``g_nonfinite > 0``, with the offending group named
  (the first group in ascending-parameter order, i.e. the layer that
  diverged first).
- **grad-norm spike** — the global gradient norm exceeds
  ``MXNET_MONITOR_SPIKE_FACTOR`` x the trailing-window maximum
  (default 10; 0 disables).  The classic pre-NaN warning shot: loss
  still finite, gradients already exploding.
- **loss plateau / NaN** — ``observe_loss`` (fed by the estimator's
  ``TrainingHealthHandler`` or any training loop) dumps on a
  nonfinite loss immediately, and — when
  ``MXNET_MONITOR_PLATEAU_WINDOW`` > 0 — once per episode after that
  many observations without a new best.
"""
from __future__ import annotations

import math
import threading
from collections import deque

from ..base import get_env

__all__ = ["DivergenceDetector", "DETECTOR", "observe_loss"]


def _dump(extra):
    from ..trace import anomaly

    return anomaly.divergence(extra)


class DivergenceDetector:
    """Trailing-window detectors over grad norms and loss values."""

    def __init__(self, spike_factor=None, window=None, min_samples=8,
                 plateau_window=None):
        self._lock = threading.Lock()
        self._norms = deque(maxlen=2)
        self.min_samples = int(min_samples)
        self._configure(spike_factor, window, plateau_window)
        self.spikes = 0
        self.nonfinite_grad_steps = 0
        self.loss_best = None
        self.loss_last = None
        self.loss_nonfinite = 0
        self.plateaus = 0
        self._since_improve = 0
        self._in_plateau = False

    def _configure(self, spike_factor, window, plateau_window):
        if spike_factor is None:
            spike_factor = get_env("MXNET_MONITOR_SPIKE_FACTOR",
                                   float, 10.0)
        if window is None:
            window = get_env("MXNET_MONITOR_SPIKE_WINDOW", int, 64)
        if plateau_window is None:
            plateau_window = get_env("MXNET_MONITOR_PLATEAU_WINDOW",
                                     int, 0)
        self.spike_factor = float(spike_factor)
        self.plateau_window = int(plateau_window)
        window = max(2, int(window))
        with self._lock:
            if window != self._norms.maxlen:
                self._norms = deque(self._norms, maxlen=window)

    def refresh_env(self):
        """Re-read the MXNET_MONITOR_SPIKE_*/_PLATEAU_WINDOW knobs.
        The module-level ``DETECTOR`` is built at ``import mxnet_tpu``
        time, which would otherwise freeze env values set later;
        ``monitor.enable()`` calls this so the runtime-enable path sees
        the live environment (explicitly-constructed detectors are
        never refreshed — their arguments win)."""
        self._configure(None, None, None)

    # -- gradient stream ----------------------------------------------------
    def observe_grad_norm(self, norm, step=None):
        """Feed one global grad norm; returns the dump path when this
        observation tripped the spike detector, else None.  Nonfinite
        norms are counted but NOT windowed (they'd poison the trailing
        max) — the sentinel owns the nonfinite dump."""
        if not math.isfinite(norm):
            with self._lock:
                self.nonfinite_grad_steps += 1
            return None
        path = None
        with self._lock:
            # a window shorter than min_samples must still warm up (the
            # deque can never hold min_samples entries), else a small
            # MXNET_MONITOR_SPIKE_WINDOW silently disables detection
            warm = len(self._norms) >= min(self.min_samples,
                                           self._norms.maxlen)
            trailing_max = max(self._norms) if self._norms else 0.0
            spiked = (self.spike_factor > 0 and warm and trailing_max > 0
                      and norm > self.spike_factor * trailing_max)
            if spiked:
                self.spikes += 1
            self._norms.append(norm)
        if spiked:
            path = _dump({"kind": "grad_norm_spike", "step": step,
                          "grad_global_norm": round(norm, 6),
                          "trailing_max": round(trailing_max, 6),
                          "factor": self.spike_factor})
        return path

    def nonfinite(self, group, st, step=None, policy=None):
        """Sentinel trip -> the divergence dump naming the offending
        group.  Returns the dump path (None when rate-limited or the
        ring is empty)."""
        with self._lock:
            self.nonfinite_grad_steps += 1
        return _dump({"kind": "nonfinite_grads", "group": group,
                      "step": step, "policy": policy,
                      "grad_nonfinite": int(st["g_nonfinite"]),
                      "weight_nonfinite": int(st["w_nonfinite"]),
                      "grad_max_abs": round(st["g_max_abs"], 6)})

    # -- loss stream --------------------------------------------------------
    def observe_loss(self, value, step=None):
        """Feed one (host float) loss value; dumps on NaN/Inf, and once
        per plateau episode when the plateau window is armed."""
        value = float(value)
        if not math.isfinite(value):
            with self._lock:
                self.loss_nonfinite += 1
                self.loss_last = value
            return _dump({"kind": "loss_nonfinite", "step": step,
                          "loss": repr(value)})
        dump_plateau = False
        with self._lock:
            self.loss_last = value
            if self.loss_best is None or value < self.loss_best:
                self.loss_best = value
                self._since_improve = 0
                self._in_plateau = False
            else:
                self._since_improve += 1
                if (self.plateau_window > 0 and not self._in_plateau
                        and self._since_improve >= self.plateau_window):
                    self._in_plateau = True
                    self.plateaus += 1
                    dump_plateau = True
        if dump_plateau:
            return _dump({"kind": "loss_plateau", "step": step,
                          "loss": round(value, 6),
                          "best": round(self.loss_best, 6),
                          "window": self.plateau_window})
        return None

    # -- introspection ------------------------------------------------------
    def state(self):
        """Snapshot for ``tools/diagnose.py --monitor`` and tests."""
        with self._lock:
            return {
                "spike_factor": self.spike_factor,
                "window": self._norms.maxlen,
                "window_fill": len(self._norms),
                "trailing_max": max(self._norms) if self._norms else 0.0,
                "spikes": self.spikes,
                "nonfinite_grad_steps": self.nonfinite_grad_steps,
                "loss_last": self.loss_last,
                "loss_best": self.loss_best,
                "loss_nonfinite": self.loss_nonfinite,
                "plateau_window": self.plateau_window,
                "plateaus": self.plateaus,
                "since_improve": self._since_improve,
            }

    def reset(self):
        with self._lock:
            self._norms.clear()
            self.spikes = 0
            self.nonfinite_grad_steps = 0
            self.loss_best = None
            self.loss_last = None
            self.loss_nonfinite = 0
            self.plateaus = 0
            self._since_improve = 0
            self._in_plateau = False


DETECTOR = DivergenceDetector()


def observe_loss(value, step=None):
    """Module-level loss feed (works whether or not the monitor stat
    plane is enabled — it is pure host float math; the dump itself is
    still gated on mx.trace being enabled)."""
    return DETECTOR.observe_loss(value, step=step)
