"""mx.monitor — on-device training-health numerics.

The fourth observability layer (README "Training health"): telemetry
says how *fast*, trace says *where the time went*; monitor says whether
the numbers are still *healthy* — per-parameter-group gradient/weight
norms, max|x| and nonfinite counts, computed by ONE fused jitted
reduction program per multi-tensor group (zero hot-path retraces,
stats read the same buffers the update donates), fetched to the host
asynchronously, and acted on:

- **nonfinite sentinel** (``MXNET_MONITOR_SENTINEL=warn|skip_step|
  raise``): a step with NaN/Inf gradients is warned about, skipped
  whole (bit-identical to never calling ``step()`` — Adam bias
  correction never advances), or raised on.
- **divergence detector**: grad-norm spikes vs a trailing window,
  loss plateau/NaN — each fires one rate-limited flight-record +
  chrome-trace dump (reason ``divergence``) naming the offending
  group, through the mx.trace anomaly path.
- **exports**: telemetry gauges/histograms (``monitor_*``), an
  optional per-step JSONL stream (``MXNET_MONITOR_STREAM=<path>``),
  bench-row health columns, ``tools/diagnose.py --monitor``.

Off by default; arm with ``MXNET_MONITOR=1`` (and see the README's
"Training health" section for the tunnel-capture recipe).  This is the
MXNet ``mx.monitor.Monitor`` capability rebuilt TPU-native: per-layer
stat inspection without per-layer eager readbacks.
"""
from __future__ import annotations

from . import core, divergence, sentinel, stats
from .core import (disable, enable, flush, group_values, is_enabled,
                   observe_update, reset, stream_path, summary)
from .divergence import DETECTOR, DivergenceDetector, observe_loss

__all__ = [
    "enable", "disable", "is_enabled",
    "observe_update", "observe_loss",
    "flush", "summary", "group_values", "reset", "stream_path",
    "DETECTOR", "DivergenceDetector",
    "core", "divergence", "sentinel", "stats",
]


def __getattr__(name):
    # monitor.ENABLED mirrors core.ENABLED (a mutable module flag —
    # re-exporting the value at import would freeze it)
    if name == "ENABLED":
        return core.ENABLED
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
