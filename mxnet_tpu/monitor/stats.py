"""Fused on-device stat reduction programs for mx.monitor.

One jitted program per parameter group computes every health number the
monitor needs — weight/grad squared L2 norms, max|x|, and nonfinite
counts — and returns them as ONE tiny f32 vector, so the host fetch is
a 24-byte transfer, not a per-parameter readback (the Relay
whole-program argument, arXiv 1810.00952: measurement belongs INSIDE
the step program, not bolted on as eager op-by-op reads).

Program discipline mirrors ``optimizer/multi_tensor.py``: the jit
wrapper is cached by the exact (shape, dtype) signature of the group's
weight+grad lists, so monitor-on adds AT MOST one extra compiled
program per group and zero per-step retraces (asserted in tests via
``monitor_stat_builds_total``).  Nothing here donates buffers — the
stat program is dispatched BEFORE the fused update program consumes
its donated inputs, and its outputs are fresh buffers the async
publisher can fetch long after the update ran.

All accumulation is float32: the nonfinite count is exact up to 2^24
elements per program and saturates (not wraps) beyond — the sentinel
only needs ``count > 0``, and 16M nonfinite elements is diverged by
any reading.
"""
from __future__ import annotations

from .. import telemetry as _tel

__all__ = ["group_stats", "unpack", "programs", "clear", "STAT_FIELDS"]

# layout of the stat vector every program returns
STAT_FIELDS = ("w_sq_sum", "w_max_abs", "w_nonfinite",
               "g_sq_sum", "g_max_abs", "g_nonfinite")

# (weights signature, grads signature) -> jitted stat program.  One
# entry per live group signature; process-lifetime bounded by the
# number of distinct group shapes (the same bound the multi-tensor
# update cache has).
_PROGRAMS = {}


def _sig(arrays):
    return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)


def _stat_fn(weights, grads):
    import jax.numpy as jnp

    def reduce3(arrays):
        sq = jnp.float32(0.0)
        mx = jnp.float32(0.0)
        nf = jnp.float32(0.0)
        for a in arrays:
            af = a.astype(jnp.float32)
            finite = jnp.isfinite(af)
            clean = jnp.where(finite, af, jnp.float32(0.0))
            sq = sq + jnp.sum(clean * clean)
            mx = jnp.maximum(mx, jnp.max(jnp.abs(clean)))
            nf = nf + jnp.sum((~finite).astype(jnp.float32))
        return sq, mx, nf

    w_sq, w_mx, w_nf = reduce3(weights)
    g_sq, g_mx, g_nf = reduce3(grads)
    return jnp.stack([w_sq, w_mx, w_nf, g_sq, g_mx, g_nf])


def group_stats(w_arrs, g_arrs):
    """Dispatch the group's stat program over raw jax arrays; returns
    the (device, async) f32 stat vector ordered as ``STAT_FIELDS``.
    First call per signature traces+compiles (counted in
    ``monitor_stat_builds_total``); every later step is a cache hit."""
    import jax

    key = (_sig(w_arrs), _sig(g_arrs))
    fn = _PROGRAMS.get(key)
    if fn is None:
        fn = jax.jit(_stat_fn)
        _PROGRAMS[key] = fn
        if _tel.ENABLED:
            _tel.MONITOR_STAT_BUILDS.inc()
    if _tel.ENABLED:
        _tel.MONITOR_STAT_PROGRAMS.inc()
    return fn(list(w_arrs), list(g_arrs))


def unpack(vec):
    """Host-side stat vector -> named float dict (norms sqrt'd here:
    the device program ships squared sums so the global norm can be
    aggregated across groups without re-reading the device)."""
    import math

    vals = [float(v) for v in vec]
    out = dict(zip(STAT_FIELDS, vals))
    out["w_norm"] = math.sqrt(max(out["w_sq_sum"], 0.0))
    out["g_norm"] = math.sqrt(max(out["g_sq_sum"], 0.0))
    return out


def programs():
    """Number of live compiled stat programs (== distinct group
    signatures seen)."""
    return len(_PROGRAMS)


def clear():
    """Drop the program cache (tests; a shape churn would rebuild)."""
    _PROGRAMS.clear()
