"""mx.monitor core — orchestration of the training-health stat plane.

The hook (``observe_update``) sits inside ``optimizer/multi_tensor.
apply_updates``: it sees the SAME parameter groups the fused update
engine built (PR 5's partition — no second grouping pass), dispatches
one stat reduction program per group (``stats.py``) BEFORE any update
program consumes its donated buffers, and hands the resulting device
vectors to a bounded ring a background publisher thread drains:

- device->host fetch happens on the publisher, so ``Trainer.step``
  never blocks on stat readback (the ring drops oldest-first under
  pressure, counted in ``monitor_dropped_total``);
- EXCEPT when the sentinel policy is ``skip_step``/``raise``, which by
  definition must know the nonfinite count before the update launches
  — those fetch synchronously (``monitor_fetch_seconds`` meters it)
  and may veto the whole step (``sentinel.py``).

The publisher converts each entry into telemetry gauges/counters, the
optional per-step JSONL stream (``MXNET_MONITOR_STREAM``), the
divergence detector feed (``divergence.py``), and the run summary
(``summary()`` — what bench rows and diagnose read).

Disabled cost on the trainer hot path is one boolean check
(``core.ENABLED``), same discipline as telemetry/trace.  Enable with
``MXNET_MONITOR=1`` or ``mx.monitor.enable()``.
"""
from __future__ import annotations

import json
import logging
import math
import threading
import time

import numpy as _np

from .. import telemetry as _tel
from .. import trace as _trace
from ..base import MXNetError, get_env
from . import divergence, sentinel, stats

__all__ = ["ENABLED", "enable", "disable", "is_enabled",
           "observe_update", "observe_captured", "gate_and_publish",
           "flush", "summary", "health", "reset", "stream_path",
           "group_values"]

_LOGGER = logging.getLogger("mxnet_tpu.monitor")

ENABLED = get_env("MXNET_MONITOR", bool, False)

_COND = threading.Condition()
_QUEUE = []          # pending entries, oldest first
_BUSY = [False]      # publisher mid-publish (flush must wait it out)
_THREAD = [None]
_STREAM = [None, None]  # (path, file handle)

_SUM_LOCK = threading.Lock()


def _new_summary():
    return {"steps": 0, "grad_global_norm_last": 0.0,
            "grad_global_norm_max": 0.0, "nonfinite_steps": 0,
            "skipped_steps": 0, "dropped": 0}


_SUMMARY = _new_summary()
_LAST_GROUPS = {}  # label -> last host stat dict (diagnose table)


def enable():
    """Turn the monitor stat plane on (module-wide).  Re-reads the
    divergence-detector env knobs, so enabling at runtime after
    setting MXNET_MONITOR_SPIKE_*/_PLATEAU_WINDOW behaves like
    enabling at import."""
    global ENABLED
    divergence.DETECTOR.refresh_env()
    ENABLED = True


def disable():
    """Turn the monitor stat plane off; counters keep their values."""
    global ENABLED
    ENABLED = False


def is_enabled():
    return ENABLED


# ---------------------------------------------------------------------------
# the trainer hook
# ---------------------------------------------------------------------------

def _group_label(trainer, key, members):
    """Stable, human-greppable group name: optimizer class + the FIRST
    member's parameter name (+member count).  Ascending param index
    inside a group is guaranteed by partition(), so the label names
    the earliest layer of the group."""
    i0 = members[0][0]
    names = trainer._param_names
    name = str(names[i0]) if 0 <= i0 < len(names) else str(i0)
    label = "%s:%s" % (key[0] if isinstance(key, tuple) and key
                       else type(trainer._optimizer).__name__, name)
    if len(members) > 1:
        label += "+%d" % (len(members) - 1)
    return label


def _dense_eager(eager):
    # partition() already classified sparse members ("row_sparse" /
    # "stype" reasons) — reuse its verdict rather than re-inspecting;
    # sparse members stay unmonitored (the stat program is dense math)
    return [(i, p, g) for i, p, g, reason in eager
            if reason not in ("row_sparse", "stype")]


def observe_update(trainer, groups, eager):
    """Monitor one optimizer apply.  Returns ``"skip"`` when the
    sentinel vetoed the step (policy=skip_step and nonfinite grads
    found), else ``"ok"``.  May raise ``MXNetError`` under
    policy=raise.  Stat failures degrade to an unmonitored step —
    monitoring must never lose a step the update engine could run."""
    if not ENABLED:
        return "ok"
    step = trainer._step_count
    interval = get_env("MXNET_MONITOR_INTERVAL", int, 1)
    if interval > 1 and step % interval:
        return "ok"
    pol = sentinel.policy()  # validate even when nothing trips
    entries = []
    try:
        for key, members in groups.items():
            w = [p.data()._data for _, p, _ in members]
            g = [grad._data for _, _, grad in members]
            entries.append((_group_label(trainer, key, members),
                            stats.group_stats(w, g)))
        dense = _dense_eager(eager)
        if dense:
            w = [p.data()._data for _, p, _ in dense]
            g = [grad._data for _, _, grad in dense]
            entries.append(("%s:eager"
                            % type(trainer._optimizer).__name__,
                            stats.group_stats(w, g)))
    except Exception:
        _LOGGER.warning("mx.monitor: stat dispatch failed; step %d "
                        "runs unmonitored", step, exc_info=True)
        return "ok"
    if not entries:
        return "ok"
    return gate_and_publish(step, entries, pol)


def gate_and_publish(step, entries, pol):
    """Shared sentinel gate + ring handoff for one observed step.

    ``entries`` is ``[(label, stat_vec)]`` — device vectors (or
    pre-unpacked host dicts) in ascending-param-index group order.
    Sync policies fetch HERE (``monitor_fetch_seconds`` meters the
    wait) and may veto the step (``"skip"``) or raise (policy=raise);
    async policies enqueue without touching the device.  Both the
    stitched ``observe_update`` hook and the captured-step path
    (``observe_captured`` — stats computed INSIDE the step program)
    funnel through this, so trip counters, divergence feed, warn logs
    and the JSONL stream are identical across the two engines."""
    if pol in sentinel.SYNC_POLICIES:
        t0 = time.perf_counter()
        try:
            host = {label: vec if isinstance(vec, dict)
                    else stats.unpack(_np.asarray(vec))
                    for label, vec in entries}
        except Exception:
            _LOGGER.warning("mx.monitor: synchronous stat fetch failed; "
                            "sentinel cannot gate step %d", step,
                            exc_info=True)
            _enqueue(step, entries, pol, skipped=False, tripped=False)
            return "ok"
        if _tel.ENABLED:
            _tel.MONITOR_FETCH_SECONDS.observe(time.perf_counter() - t0)
        label, st = sentinel.first_offender(host)
        if label is not None:
            if _tel.ENABLED:
                _tel.MONITOR_SENTINEL_TRIPS.labels(policy=pol).inc()
                _tel.MONITOR_NONFINITE_STEPS.inc()
            divergence.DETECTOR.nonfinite(label, st, step=step,
                                          policy=pol)
            _trace.instant("monitor_sentinel_trip", cat="monitor",
                           args={"group": label, "policy": pol,
                                 "step": step,
                                 "grad_nonfinite":
                                     int(st["g_nonfinite"])})
            skipped = pol == "skip_step"
            if skipped and _tel.ENABLED:
                _tel.MONITOR_SKIPPED_STEPS.inc()
            _enqueue(step, list(host.items()), pol, skipped=skipped,
                     tripped=True)
            if skipped:
                _LOGGER.warning(
                    "mx.monitor: step %d SKIPPED — nonfinite gradients "
                    "in group %s (%d elements); parameters and "
                    "optimizer state untouched", step, label,
                    int(st["g_nonfinite"]))
                return "skip"
            raise MXNetError(
                "mx.monitor sentinel: nonfinite gradients in group %s "
                "at step %d (%d elements, policy=raise)"
                % (label, step, int(st["g_nonfinite"])))
        _enqueue(step, list(host.items()), pol, skipped=False,
                 tripped=False)
        return "ok"
    _enqueue(step, entries, pol, skipped=False, tripped=False)
    return "ok"


def observe_captured(trainer, step, entries):
    """Publish the fused stat vectors a captured step program (mx.step)
    computed INSIDE the one whole-step XLA program — health numerics
    with zero extra dispatches or readbacks beyond the program's own
    outputs.  Returns ``"skip"`` when the sentinel verdict is a veto
    (the program already where-selected no-op updates on device; the
    caller rewinds its host-side count bookkeeping), ``"ok"``
    otherwise; raises ``MXNetError`` under policy=raise.  Unlike the
    stitched hook, stats arrive every captured step regardless of
    ``MXNET_MONITOR_INTERVAL`` — they are free once fused."""
    if not ENABLED or not entries:
        return "ok"
    return gate_and_publish(step, entries, sentinel.policy())


# ---------------------------------------------------------------------------
# bounded ring + publisher thread
# ---------------------------------------------------------------------------

_SEQ = [0]  # monotonically-increasing observation counter: a skipped
# step and its retry share a trainer step id (the skip contract), so
# stream consumers need seq for an unambiguous x-axis


def _enqueue(step, entry_stats, pol, skipped, tripped):
    cap = max(1, get_env("MXNET_MONITOR_RING", int, 256))
    with _SUM_LOCK:
        _SEQ[0] += 1
        seq = _SEQ[0]
    entry = {"seq": seq, "step": step, "stats": entry_stats,
             "policy": pol, "skipped": skipped, "tripped": tripped,
             "time": time.time()}
    with _COND:
        if len(_QUEUE) >= cap:
            # prefer displacing an entry that carries no trip evidence;
            # fold the victim's step-level flags into the summary so
            # bench/summary stay consistent with the telemetry counters
            # incremented at observe time (per-group gauges/warn logs
            # for the victim are lost — that's the bounded-ring deal)
            victim_idx = next((j for j, e in enumerate(_QUEUE)
                               if not e["tripped"]), 0)
            victim = _QUEUE.pop(victim_idx)
            with _SUM_LOCK:
                _SUMMARY["dropped"] += 1
                _SUMMARY["steps"] += 1
                if victim["tripped"]:
                    _SUMMARY["nonfinite_steps"] += 1
                if victim["skipped"]:
                    _SUMMARY["skipped_steps"] += 1
            if _tel.ENABLED:
                _tel.MONITOR_DROPS.inc()
        _QUEUE.append(entry)
        t = _THREAD[0]
        if t is None or not t.is_alive():
            t = threading.Thread(target=_publisher_loop, daemon=True,
                                 name="mx-monitor-publish")
            _THREAD[0] = t
            t.start()
        _COND.notify_all()


def _publisher_loop():
    while True:
        with _COND:
            while not _QUEUE:
                _COND.notify_all()  # wake any flush() waiter
                _COND.wait()
            entry = _QUEUE.pop(0)
            _BUSY[0] = True
        try:
            _publish(entry)
        except Exception:  # noqa: BLE001 - the publisher must survive
            _LOGGER.exception("mx.monitor: publish failed")
        finally:
            with _COND:
                _BUSY[0] = False
                _COND.notify_all()


def _publish(entry):
    host = {}
    for label, vec in entry["stats"]:
        host[label] = vec if isinstance(vec, dict) \
            else stats.unpack(_np.asarray(vec))
    step = entry["step"]
    global_sq = sum(st["g_sq_sum"] for st in host.values())
    gnorm = math.sqrt(max(global_sq, 0.0))
    nonfinite_g = int(sum(st["g_nonfinite"] for st in host.values()))
    if _tel.ENABLED:
        for label, st in host.items():
            _tel.MONITOR_GRAD_NORM.labels(group=label).set(st["g_norm"])
            _tel.MONITOR_WEIGHT_NORM.labels(group=label).set(
                st["w_norm"])
            _tel.MONITOR_GRAD_MAX.labels(group=label).set(
                st["g_max_abs"])
            _tel.MONITOR_WEIGHT_MAX.labels(group=label).set(
                st["w_max_abs"])
            if st["g_nonfinite"]:
                _tel.MONITOR_NONFINITE.labels(
                    kind="grad", group=label).inc(st["g_nonfinite"])
            if st["w_nonfinite"]:
                _tel.MONITOR_NONFINITE.labels(
                    kind="weight", group=label).inc(st["w_nonfinite"])
        _tel.MONITOR_GRAD_GLOBAL_NORM.set(gnorm)
        _tel.MONITOR_GRAD_GLOBAL_NORM_HIST.observe(gnorm)
    if nonfinite_g and not entry["tripped"]:
        # async policies (warn/off) account their trips here, a step
        # or two after the fact — the price of never blocking step()
        if _tel.ENABLED:
            _tel.MONITOR_NONFINITE_STEPS.inc()
        label, st = sentinel.first_offender(host)
        if entry["policy"] == "warn":
            if _tel.ENABLED:
                _tel.MONITOR_SENTINEL_TRIPS.labels(policy="warn").inc()
            sentinel.warn_trip(label, st, step)
        divergence.DETECTOR.nonfinite(label, st, step=step,
                                      policy=entry["policy"])
    if not nonfinite_g:
        # a nonfinite step must not poison the spike window (its
        # cleaned norm under-reports), and its dump already fired
        divergence.DETECTOR.observe_grad_norm(gnorm, step=step)
    with _SUM_LOCK:
        _SUMMARY["steps"] += 1
        _SUMMARY["grad_global_norm_last"] = gnorm
        _SUMMARY["grad_global_norm_max"] = max(
            _SUMMARY["grad_global_norm_max"], gnorm)
        if nonfinite_g:
            _SUMMARY["nonfinite_steps"] += 1
        if entry["skipped"]:
            _SUMMARY["skipped_steps"] += 1
        _LAST_GROUPS.clear()
        _LAST_GROUPS.update(host)
    _stream_write(entry, host, gnorm)


# ---------------------------------------------------------------------------
# JSONL stream
# ---------------------------------------------------------------------------

def stream_path():
    """The per-step JSONL stream destination (``MXNET_MONITOR_STREAM``;
    None = off)."""
    return get_env("MXNET_MONITOR_STREAM", str, None)


def _stream_write(entry, host, gnorm):
    path = stream_path()
    if not path:
        return
    try:
        if _STREAM[0] != path:
            if _STREAM[1] is not None:
                _STREAM[1].close()
            _STREAM[0], _STREAM[1] = path, open(path, "a")
        line = {"seq": entry["seq"], "step": entry["step"],
                "time": round(entry["time"], 3),
                "skipped": entry["skipped"],
                "policy": entry["policy"],
                "grad_global_norm": round(gnorm, 8),
                "groups": {
                    label: {"grad_norm": round(st["g_norm"], 8),
                            "grad_max_abs": round(st["g_max_abs"], 8),
                            "weight_norm": round(st["w_norm"], 8),
                            "weight_max_abs": round(st["w_max_abs"], 8),
                            "nonfinite_grad": int(st["g_nonfinite"]),
                            "nonfinite_weight": int(st["w_nonfinite"])}
                    for label, st in host.items()}}
        _STREAM[1].write(json.dumps(line) + "\n")
        _STREAM[1].flush()
    except OSError:
        _LOGGER.warning("mx.monitor: stream write to %s failed", path,
                        exc_info=True)


# ---------------------------------------------------------------------------
# introspection / lifecycle
# ---------------------------------------------------------------------------

def flush(timeout=None):
    """Block until the publisher has drained every queued entry (tests,
    bench rows, smoke tools — anything that reads gauges right after a
    step).  Returns True when drained, False on timeout."""
    with _COND:
        _COND.notify_all()
        return _COND.wait_for(lambda: not _QUEUE and not _BUSY[0],
                              timeout)


def summary(reset_peak=False):
    """Run-level health summary: observed steps, last/max global grad
    norm, nonfinite/skipped step counts, ring drops.  With
    ``reset_peak`` the max restarts from ZERO — bench rows use it so
    each row's max covers only that row's own observations (a max of 0
    on a later read means nothing was observed since the reset, not a
    carried-over peak from a different model)."""
    with _SUM_LOCK:
        out = dict(_SUMMARY)
        if reset_peak:
            _SUMMARY["grad_global_norm_max"] = 0.0
    return out


def group_values():
    """Last published per-group stat dicts {label: stats} (the
    diagnose --monitor table)."""
    with _SUM_LOCK:
        return {k: dict(v) for k, v in _LAST_GROUPS.items()}


def health():
    """Compact numerics-health dict for the mx.obs per-rank payload:
    the summary() fields that matter across a fleet, plus the enabled
    flag (so the fleet table can say WHICH ranks are monitored)."""
    s = summary()
    return {"enabled": ENABLED,
            "steps": s["steps"],
            "nonfinite_steps": s["nonfinite_steps"],
            "skipped_steps": s["skipped_steps"],
            "grad_global_norm_last": s["grad_global_norm_last"]}


def reset(clear_programs=False):
    """Zero the summary, queue, and detector state (tests / between
    bench rows).  Compiled stat programs survive unless
    ``clear_programs`` — dropping them would force rebuilds."""
    global _SUMMARY
    with _COND:
        del _QUEUE[:]
    with _SUM_LOCK:
        _SUMMARY = _new_summary()
        _LAST_GROUPS.clear()
        _SEQ[0] = 0
    divergence.DETECTOR.reset()
    if clear_programs:
        stats.clear()
    if _STREAM[1] is not None:
        try:
            _STREAM[1].close()
        except OSError:
            pass
        _STREAM[0] = _STREAM[1] = None
