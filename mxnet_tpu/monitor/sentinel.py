"""Nonfinite sentinel: the policy layer that gates a trainer step on
the health of its gradients.

Policies (``MXNET_MONITOR_SENTINEL``):

- ``off``        — never fetch synchronously; stats stream async only.
- ``warn``       — default.  Stats stay async; the publisher thread
  logs a warning (and counts the trip) when a drained step shows
  nonfinite gradients.  Zero added sync points on the step path.
- ``skip_step``  — fetch the stat vectors synchronously BEFORE any
  update program launches; a step with >=1 nonfinite gradient element
  is skipped whole — no parameter touched, no optimizer-state slot
  written, no ``_index_update_count``/``num_update`` bump (the skip
  happens before PR 5's count bookkeeping, so Adam bias correction
  never advances; a skipped step is bit-identical to never calling
  ``step()``).  The standard bf16/loss-scaling survival move.
- ``raise``      — same synchronous check, but raise ``MXNetError``
  instead of skipping (CI / debugging: fail the run at the FIRST bad
  step, with the offending group named, instead of 40k steps later).

``skip_step``/``raise`` cost one device->host sync per observed step
(a ~24-byte fetch per group, but it waits for the grads to be
computed); ``warn``/``off`` cost nothing on the step path.
"""
from __future__ import annotations

import logging

from ..base import MXNetError, get_env

__all__ = ["POLICIES", "SYNC_POLICIES", "policy", "first_offender"]

_LOGGER = logging.getLogger("mxnet_tpu.monitor")

POLICIES = ("off", "warn", "skip_step", "raise")
# policies that need the nonfinite count ON THE TRAINING THREAD before
# the update programs may launch
SYNC_POLICIES = ("skip_step", "raise")


def policy():
    """The sentinel policy in force (validated; a typo'd value must
    fail loud — a silently-disabled guard is the worst outcome)."""
    p = get_env("MXNET_MONITOR_SENTINEL", str, "warn")
    if p not in POLICIES:
        raise MXNetError(
            "MXNET_MONITOR_SENTINEL=%r is not a sentinel policy "
            "(choose from %s)" % (p, "|".join(POLICIES)))
    return p


def first_offender(host_stats):
    """First group (insertion order == ascending param index) whose
    gradients contain nonfinite elements; ``(label, stats)`` or
    ``(None, None)``.  Insertion order matters: with several sick
    groups the EARLIEST parameters name the layer that diverged
    first."""
    for label, st in host_stats.items():
        if st["g_nonfinite"] > 0:
            return label, st
    return None, None


def warn_trip(label, st, step):
    """The async (policy=warn) trip report, called by the publisher."""
    _LOGGER.warning(
        "mx.monitor: nonfinite gradients at step %s in group %s "
        "(%d nonfinite elements, grad_norm=%g) — policy=warn, update "
        "was applied; set MXNET_MONITOR_SENTINEL=skip_step to drop "
        "such steps", step, label, int(st["g_nonfinite"]), st["g_norm"])
