"""Neural-net ops: dense, conv, pooling, normalization, activations, dropout.

Reference surface: src/operator/nn/ (31k LoC: convolution.cc,
fully_connected.cc, batch_norm.cc, layer_norm.cc, pooling.cc, softmax.cc,
dropout, activation + the cuDNN/MKLDNN dispatch trees).

TPU-native: each op is a single lax/jnp expression that XLA tiles onto the
MXU (conv/FC) or fuses into surrounding elementwise chains (activations,
norms).  The cuDNN/MKLDNN forks disappear — XLA:TPU is the one backend.
bf16 contractions rely on the MXU's native f32 accumulation — the
hardware's mixed-precision mode (see _amp_pair).
"""
# pylint: disable=redefined-builtin
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---- activations (reference nn/activation.cc, leaky_relu.cc) --------------


@register("relu")
def relu(x):
    return jnp.maximum(x, 0)


@register("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register("log_sigmoid")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@register("softrelu")
def softrelu(x):
    return jax.nn.softplus(x)


@register("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@register("hard_sigmoid")
def hard_sigmoid(x, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register("leaky_relu")
def leaky_relu(x, slope=0.25):
    return jnp.where(x >= 0, x, slope * x)


@register("prelu")
def prelu(x, gamma):
    return jnp.where(x >= 0, x, gamma * x)


@register("elu")
def elu(x, alpha=1.0):
    return jnp.where(x >= 0, x, alpha * jnp.expm1(x))


@register("selu")
def selu(x):
    return jax.nn.selu(x)


@register("gelu")
def gelu(x, approximate=True):
    return jax.nn.gelu(x, approximate=approximate)


@register("silu")
def silu(x):
    return jax.nn.silu(x)


swish = silu


@register("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@register("softmax")
def softmax(x, axis=-1, temperature=None, length=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        pos = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        mask = pos.reshape(shape) < length.reshape(
            length.shape + (1,) * (x.ndim - length.ndim))
        x = jnp.where(mask, x, -jnp.inf)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(x, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def softmin(x, axis=-1):
    return jax.nn.softmax(-x, axis=axis)


# ---- dense (reference nn/fully_connected.cc; MXU GEMM) --------------------


def _amp_pair(x, weight):
    """Mixed-precision dtype alignment: when exactly one side is bf16 (AMP
    casts weights, normalization keeps f32), compute the contraction in
    bf16 — the MXU accumulates bf16 products in f32 natively, so no
    explicit preferred_element_type is needed (and requesting one breaks
    the conv/dot transpose rules under value_and_grad)."""
    if x.dtype != weight.dtype and jnp.bfloat16 in (x.dtype, weight.dtype):
        return x.astype(jnp.bfloat16), weight.astype(jnp.bfloat16)
    return x, weight


@register("fully_connected")
def fully_connected(x, weight, bias=None, num_hidden=None, flatten=True,
                    no_bias=False):
    """y = x @ W^T + b.  Weight layout (out, in) matches the reference
    (fully_connected.cc shape conventions) and feeds the MXU directly."""
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    x, weight = _amp_pair(x, weight)
    # bf16 contractions accumulate in f32 on the MXU natively; an explicit
    # preferred_element_type=f32 breaks the conv/dot transpose rules under
    # value_and_grad (mixed-dtype cotangents), so rely on the hardware
    y = lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())))
    if bias is not None and not no_bias:
        y = y + bias.astype(y.dtype)
    return y


# ---- convolution (reference nn/convolution.cc / deconvolution.cc) ---------


def _conv_dims(ndim, layout):
    if layout is None:
        layout = {3: "NCW", 4: "NCHW", 5: "NCDHW"}[ndim]
    # weight layout: O I spatial... (reference convention)
    w_layout = {3: "OIW", 4: "OIHW", 5: "OIDHW"}[ndim]
    out_layout = layout
    return layout, w_layout, out_layout


def _tuned_conv_layout(x, weight, stride, layout):
    """PERF_PLAN H1 hook: for default-layout 2-D convs, consult the
    mx.autotune ``conv_layout`` site.  Only an explicit tuned "NHWC"
    winner changes anything (the conv runs with NHWC dimension numbers
    between a transpose-in/transpose-out pair — models stay NCHW);
    autotune off, a cold store, or any malformed record keeps today's
    NCHW path untouched."""
    if layout is not None or x.ndim != 4:
        return "NCHW"
    from .. import autotune as _at

    if not _at.is_enabled():
        return "NCHW"
    n, c, h, w = x.shape
    o, _i, kh, kw = weight.shape
    cfg = _at.lookup(
        "conv_layout",
        (n, c, h, w, o, kh, kw, int(stride[0]), str(x.dtype)), "NCHW")
    if cfg not in ("NCHW", "NHWC"):
        _at.fallback("invalid_config")
        return "NCHW"
    return cfg


@register("convolution")
def convolution(x, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                layout=None):
    nd = x.ndim
    nspatial = nd - 2
    stride = tuple(stride) if stride else (1,) * nspatial
    dilate = tuple(dilate) if dilate else (1,) * nspatial
    pad = tuple(pad) if pad else (0,) * nspatial
    dn_layout = _conv_dims(nd, layout)
    x, weight = _amp_pair(x, weight)
    # (see fully_connected) bf16 convs accumulate f32 on the MXU natively
    if _tuned_conv_layout(x, weight, stride, layout) == "NHWC":
        # H1 tuned winner: identical conv math through NHWC dimension
        # numbers — XLA folds the operand transposes into its layout
        # assignment where that pays
        dn = lax.conv_dimension_numbers(
            (x.shape[0], x.shape[2], x.shape[3], x.shape[1]),
            (weight.shape[2], weight.shape[3], weight.shape[1],
             weight.shape[0]),
            ("NHWC", "HWIO", "NHWC"))
        y = lax.conv_general_dilated(
            x.transpose(0, 2, 3, 1), weight.transpose(2, 3, 1, 0),
            window_strides=stride, padding=[(p, p) for p in pad],
            rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=num_group).transpose(0, 3, 1, 2)
    else:
        dn = lax.conv_dimension_numbers(
            x.shape, weight.shape, dn_layout[:2] + (dn_layout[2],))
        y = lax.conv_general_dilated(
            x, weight, window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=num_group)
    if bias is not None and not no_bias:
        lay = dn_layout[0]
        c_axis = lay.index("C")
        shape = [1] * nd
        shape[c_axis] = bias.shape[0]
        y = y + bias.reshape(shape).astype(y.dtype)
    return y


@register("deconvolution")
def deconvolution(x, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, num_filter=None, num_group=1,
                  no_bias=False, layout=None):
    """Transposed conv (reference nn/deconvolution.cc).  Implemented as the
    gradient of convolution — lax.conv_transpose with IO-swapped weights."""
    nd = x.ndim
    nspatial = nd - 2
    stride = tuple(stride) if stride else (1,) * nspatial
    pad = tuple(pad) if pad else (0,) * nspatial
    dilate = tuple(dilate) if dilate else (1,) * nspatial
    lay, wlay, olay = _conv_dims(nd, layout)
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape, (lay, wlay.replace("O", "X").replace("I", "O")
                                .replace("X", "I"), olay))
    y = lax.conv_transpose(
        x, jnp.swapaxes(weight, 0, 1) if num_group == 1 else weight,
        strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        transpose_kernel=True)
    if bias is not None and not no_bias:
        c_axis = lay.index("C")
        shape = [1] * nd
        shape[c_axis] = bias.shape[0]
        y = y + bias.reshape(shape)
    return y


# ---- pooling (reference nn/pooling.cc) ------------------------------------


@register("pooling")
def pooling(x, kernel=None, pool_type="max", stride=None, pad=None,
            global_pool=False, count_include_pad=True, layout=None):
    nd = x.ndim
    nspatial = nd - 2
    lay = layout or {3: "NCW", 4: "NCHW", 5: "NCDHW"}[nd]
    spatial_axes = [lay.index(c) for c in lay if c not in ("N", "C")]
    if global_pool:
        if pool_type == "max":
            return jnp.max(x, axis=tuple(spatial_axes), keepdims=True)
        return jnp.mean(x, axis=tuple(spatial_axes), keepdims=True)
    kernel = tuple(kernel)
    stride = tuple(stride) if stride else (1,) * nspatial
    pad = tuple(pad) if pad else (0,) * nspatial
    window = [1] * nd
    strides = [1] * nd
    padding = [(0, 0)] * nd
    for i, ax in enumerate(spatial_axes):
        window[ax] = kernel[i]
        strides[ax] = stride[i]
        padding[ax] = (pad[i], pad[i])
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else (
            jnp.iinfo(x.dtype).min)
        return lax.reduce_window(x, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return s / cnt
    if pool_type == "lp":
        p2 = lax.reduce_window(jnp.abs(x) ** 2, 0.0, lax.add, window,
                               strides, padding)
        return jnp.sqrt(p2)
    raise ValueError("unknown pool_type %s" % pool_type)


@register("adaptive_avg_pooling")
def adaptive_avg_pooling(x, output_size=1):
    """Reference: contrib/adaptive_avg_pooling.cc (NCHW)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    N, C, H, W = x.shape
    oh, ow = output_size
    # split into oh x ow near-equal windows via mean over reshaped blocks
    if H % oh == 0 and W % ow == 0:
        return x.reshape(N, C, oh, H // oh, ow, W // ow).mean(axis=(3, 5))
    hi = jnp.linspace(0, H, oh + 1).astype(jnp.int32)
    wi = jnp.linspace(0, W, ow + 1).astype(jnp.int32)
    rows = []
    for i in range(oh):
        cols = []
        for j in range(ow):
            cols.append(x[:, :, hi[i]:hi[i + 1], wi[j]:wi[j + 1]].mean(
                axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


# ---- normalization (reference nn/batch_norm.cc etc.) ----------------------


def _tuned_bn_stat_dtype(x, axis, stat_dtype):
    """PERF_PLAN H2 hook: the batch-stat reduction dtype.  Explicit
    ``stat_dtype`` wins; otherwise the mx.autotune ``bn_stat_dtype``
    winner — which under the bitwise numerics guard can only ever be a
    value that measured bit-identical to f32 — else today's f32.  The
    reduction ``axis`` is part of the key: bit-identity certified for
    one reduction geometry says nothing about another."""
    if stat_dtype is not None:
        return stat_dtype
    from .. import autotune as _at

    if not _at.is_enabled():
        return "float32"
    cfg = _at.lookup("bn_stat_dtype",
                     tuple(x.shape) + (int(axis), str(x.dtype)),
                     "float32")
    if cfg not in ("float32", "bfloat16"):
        _at.fallback("invalid_config")
        return "float32"
    return cfg


@register("batch_norm", num_outputs=3)
def batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-5,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               axis=1, training=False, stat_dtype=None):
    """Returns (out, new_moving_mean, new_moving_var).

    Reference: src/operator/nn/batch_norm.cc — the running-stat update is an
    op side effect there; here it is an explicit functional output that the
    Gluon layer writes back (XLA-friendly: no hidden state in the graph).

    ``stat_dtype`` (None -> mx.autotune ``bn_stat_dtype`` site, default
    "float32") is the dtype the batch mean/var reduce in — PERF_PLAN
    hypothesis H2.  The f32 default path is byte-for-byte today's code.
    """
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    # statistics and normalization math in f32 even under AMP (bf16 x with
    # f32 gamma/beta/running stats); output back in x's dtype
    xf = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    if training and not use_global_stats:
        sd = _tuned_bn_stat_dtype(x, axis, stat_dtype)
        if sd == "bfloat16":
            xs = xf.astype(jnp.bfloat16)
            m = jnp.mean(xs, axis=reduce_axes).astype(jnp.float32)
            v = jnp.var(xs, axis=reduce_axes).astype(jnp.float32)
        else:
            m = jnp.mean(xf, axis=reduce_axes)
            v = jnp.var(xf, axis=reduce_axes)
        new_mean = moving_mean * momentum + m.astype(moving_mean.dtype) * \
            (1 - momentum)
        new_var = moving_var * momentum + v.astype(moving_var.dtype) * \
            (1 - momentum)
    else:
        m, v = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    inv = lax.rsqrt(v.astype(jnp.float32) + eps)
    out = (xf - m.reshape(shape)) * (g * inv).reshape(shape) + \
        beta.reshape(shape)
    return out.astype(x.dtype), new_mean, new_var


@register("layer_norm")
def layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    """Reference: src/operator/nn/layer_norm.cc."""
    m = jnp.mean(x, axis=axis, keepdims=True)
    v = jnp.var(x, axis=axis, keepdims=True)
    out = (x - m) * lax.rsqrt(v + eps)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("group_norm")
def group_norm(x, gamma, beta, num_groups=1, eps=1e-5):
    """Reference: src/operator/nn/group_norm.cc (NC+ layout)."""
    N, C = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = x.reshape((N, num_groups, C // num_groups) + spatial)
    axes = tuple(range(2, xg.ndim))
    m = jnp.mean(xg, axis=axes, keepdims=True)
    v = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - m) * lax.rsqrt(v + eps)).reshape(x.shape)
    shape = (1, C) + (1,) * len(spatial)
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("instance_norm")
def instance_norm(x, gamma, beta, eps=1e-5):
    axes = tuple(range(2, x.ndim))
    m = jnp.mean(x, axis=axes, keepdims=True)
    v = jnp.var(x, axis=axes, keepdims=True)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    return (x - m) * lax.rsqrt(v + eps) * gamma.reshape(shape) + \
        beta.reshape(shape)


@register("rms_norm")
def rms_norm(x, gamma, axis=-1, eps=1e-6):
    """RMSNorm — modern-transformer staple (no reference equivalent)."""
    ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axis, keepdims=True)
    out = (x.astype(jnp.float32) * lax.rsqrt(ms + eps)).astype(x.dtype)
    return out * gamma


@register("l2_normalization")
def l2_normalization(x, eps=1e-10, mode="instance"):
    if mode == "instance":
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=tuple(range(1, x.ndim)),
                             keepdims=True) + eps)
    elif mode == "channel":
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
    else:
        n = jnp.sqrt(jnp.sum(jnp.square(x)) + eps)
    return x / n


@register("lrn")
def lrn(x, alpha=1e-4, beta=0.75, knorm=2, nsize=5):
    """Local response norm over channels (reference nn/lrn.cc, NCHW)."""
    sq = jnp.square(x)
    half = nsize // 2
    pad = [(0, 0), (half, half)] + [(0, 0)] * (x.ndim - 2)
    sqp = jnp.pad(sq, pad)
    window = [1, nsize] + [1] * (x.ndim - 2)
    s = lax.reduce_window(sqp, 0.0, lax.add, window, [1] * x.ndim,
                          [(0, 0)] * x.ndim)
    return x / jnp.power(knorm + alpha * s / nsize, beta)


# ---- dropout (reference nn/dropout.cc) ------------------------------------


@register("dropout", differentiable=True)
def dropout(x, key, p=0.5, mode="training", axes=None):
    if p <= 0.0:
        return x
    shape = x.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(x.shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape).astype(x.dtype) / keep
    return x * mask


# ---- resize / upsampling (reference nn/upsampling.cc, bilinear_resize) ----


@register("upsampling")
def upsampling(x, scale=2, sample_type="nearest"):
    N, C, H, W = x.shape
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    return jax.image.resize(x, (N, C, H * scale, W * scale), "bilinear")


@register("bilinear_resize")
def bilinear_resize(x, height=None, width=None, align_corners=False):
    N, C = x.shape[:2]
    method = "bilinear"
    return jax.image.resize(x, (N, C, height, width), method)


# ---- losses as ops (reference nn/softmax_output, smooth_l1, ctc) ----------


@register("softmax_cross_entropy")
def softmax_cross_entropy(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    lbl = labels.astype(jnp.int32)
    nll = -jnp.take_along_axis(logp, lbl[..., None], axis=-1)[..., 0]
    return jnp.sum(nll)


@register("smooth_l1")
def smooth_l1(x, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * jnp.square(x),
                     jnp.abs(x) - 0.5 / s2)


@register("ctc_loss")
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             blank_label="first"):
    """CTC forward-backward (reference nn/ctc_loss.cc + 3rdparty/ctc_include).

    data: (T, B, V) unnormalized activations; label: (B, L) padded with -1
    (or 0s counted via label_lengths).  Pure lax.scan dynamic program — XLA
    compiles the recurrence; no warp-ctc needed.
    """
    T, B, V = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    blank = 0 if blank_label == "first" else V - 1
    L = label.shape[1]
    lab = label.astype(jnp.int32)
    if label_lengths is None:
        label_lengths = jnp.sum((lab >= 0) & (lab != blank) if blank_label ==
                                "first" else (lab >= 0), axis=1)
        label_lengths = jnp.sum(lab > (0 if blank_label == "first" else -1),
                                axis=1) if blank_label == "first" else \
            label_lengths
    if data_lengths is None:
        data_lengths = jnp.full((B,), T, jnp.int32)
    S = 2 * L + 1
    # extended label sequence: blank l1 blank l2 ... blank
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    neg_inf = -1e30
    # alpha recursion
    same_as_prev2 = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)
    pos = jnp.arange(S)

    def step(alpha, logp_t):
        a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]],
                             axis=1)
        a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]],
                             axis=1)
        a2 = jnp.where(same_as_prev2 | (pos[None, :] % 2 == 0), neg_inf, a2)
        m = jnp.maximum(alpha, jnp.maximum(a1, a2))
        new = m + jnp.log(
            jnp.exp(alpha - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m))
        emit = jnp.take_along_axis(logp_t, ext, axis=1)
        new = new + emit
        return new, new

    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0])
    _, alphas = lax.scan(step, alpha0, logp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, S)
    # per-sample final frame: alpha at t = data_length - 1
    t_end = jnp.clip(data_lengths.astype(jnp.int32) - 1, 0, T - 1)
    alpha_T = alphas[t_end, jnp.arange(B)]                    # (B, S)
    end = 2 * label_lengths.astype(jnp.int32)
    a_end = jnp.take_along_axis(alpha_T, end[:, None], axis=1)[:, 0]
    a_end1 = jnp.take_along_axis(alpha_T, jnp.maximum(end - 1, 0)[:, None],
                                 axis=1)[:, 0]
    m = jnp.maximum(a_end, a_end1)
    ll = m + jnp.log(jnp.exp(a_end - m) + jnp.exp(a_end1 - m))
    return -ll


# ---- attention (reference contrib/transformer.cc interleaved matmuls) -----


@register("multi_head_attention")
def multi_head_attention(q, k, v, num_heads=1, mask=None, scale=None,
                         causal=False, impl="auto", attn_dropout=0.0,
                         dropout_key=None):
    """Batched SDPA: q,k,v (B, T, H*D).  Reference equivalent:
    _contrib_interleaved_matmul_selfatt_qk/valatt (contrib/transformer.cc:
    650-826) which exist only to feed cuBLAS strided GEMMs; on TPU one
    einsum chain fuses and lands on the MXU, and the Pallas flash kernel
    (mxnet_tpu/ops/pallas_attention.py) takes over for long sequences.

    impl: 'auto' | 'dense' | 'flash' (blockwise scan) | 'pallas'.
    attn_dropout (+ dropout_key) drops attention probabilities; every
    impl supports it — the Pallas kernel applies a per-tile PRNG mask
    inside fwd AND both backward kernels (regenerated, never stored), so
    auto-dispatch sends all long-sequence cases, dropout included, to
    'pallas'; 'flash' (blockwise) remains the pure-JAX fallback.
    """
    from ..base import MXNetError
    from . import pallas_attention as pa

    B, Tq, HD = q.shape
    Tk = k.shape[1]
    D = HD // num_heads
    qh = q.reshape(B, Tq, num_heads, D).transpose(0, 2, 1, 3)
    kh = k.reshape(B, Tk, num_heads, D).transpose(0, 2, 1, 3)
    vh = v.reshape(B, Tk, num_heads, D).transpose(0, 2, 1, 3)
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    if attn_dropout > 0.0 and dropout_key is None:
        raise MXNetError("attn_dropout > 0 requires dropout_key (draw one "
                         "with mxnet_tpu.random.take_key())")
    has_dropout = attn_dropout > 0.0
    if impl == "auto":
        # the Pallas kernel now covers dropout too (in-kernel per-tile
        # PRNG mask, fwd + both bwd kernels regenerate it)
        impl = "pallas" if pa.use_flash(Tq, Tk, D, mask is not None) \
            else "dense"
    if impl in ("pallas", "flash"):
        if mask is not None:
            raise MXNetError(
                "impl=%r does not support an arbitrary mask (only causal=); "
                "use impl='dense' or drop the mask" % impl)
        if impl == "pallas":
            out = pa.flash_attention(qh, kh, vh, causal, scale,
                                     dropout_p=attn_dropout,
                                     dropout_key=dropout_key)
        else:
            out = pa.blockwise_attention(qh, kh, vh, causal=causal,
                                         sm_scale=scale,
                                         dropout_p=attn_dropout,
                                         dropout_key=dropout_key)
        return out.transpose(0, 2, 1, 3).reshape(B, Tq, HD)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        cmask = jnp.tril(jnp.ones((Tq, Tk), bool))
        scores = jnp.where(cmask, scores, -1e30)
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(qh.dtype)
    if has_dropout:
        keep = 1.0 - attn_dropout
        dmask = jax.random.bernoulli(dropout_key, keep, w.shape)
        w = w * dmask.astype(w.dtype) / keep
    out = jnp.einsum("bhqk,bhkd->bhqd", w, vh)
    return out.transpose(0, 2, 1, 3).reshape(B, Tq, HD)
