"""NumPy-semantics long-tail operators.

Reference parity: the ``_npi_*`` registrations under
/root/reference/src/operator/numpy/ (216 ops — percentile, cross, pad,
unique, window functions, polynomial, insert/delete, nan-reductions,
bitwise family, ...).  Each op here is the jnp expression XLA fuses
directly; the point of registering them (vs. the mx.np jnp adapter) is
that they flow through the SAME invoke/record path as every other op —
autograd tape, deferred-compute tracing, profiler naming — and surface
under ``mx.nd`` / ``mx.npx`` with reference names.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

__all__ = []


def _reg(name, fn, differentiable=True, num_outputs=1):
    fn.__name__ = name
    register(name, num_outputs=num_outputs,
             differentiable=differentiable)(fn)


# ---- reductions / statistics ---------------------------------------------

_reg("percentile", lambda a, q=50.0, axis=None, keepdims=False:
     jnp.percentile(a, q, axis=axis, keepdims=keepdims))
_reg("quantile", lambda a, q=0.5, axis=None, keepdims=False:
     jnp.quantile(a, q, axis=axis, keepdims=keepdims))
_reg("median", lambda a, axis=None, keepdims=False:
     jnp.median(a, axis=axis, keepdims=keepdims))
_reg("average", lambda a, weights=None, axis=None:
     jnp.average(a, axis=axis, weights=weights))
_reg("cov", lambda m, y=None, rowvar=True, bias=False:
     jnp.cov(m, y, rowvar=rowvar, bias=bias))
_reg("corrcoef", lambda x, y=None, rowvar=True:
     jnp.corrcoef(x, y, rowvar=rowvar))
_reg("ptp", lambda a, axis=None, keepdims=False:
     jnp.ptp(a, axis=axis, keepdims=keepdims))
_reg("nanmax", lambda a, axis=None, keepdims=False:
     jnp.nanmax(a, axis=axis, keepdims=keepdims))
_reg("nanmin", lambda a, axis=None, keepdims=False:
     jnp.nanmin(a, axis=axis, keepdims=keepdims))
_reg("nansum", lambda a, axis=None, keepdims=False:
     jnp.nansum(a, axis=axis, keepdims=keepdims))
_reg("nanprod", lambda a, axis=None, keepdims=False:
     jnp.nanprod(a, axis=axis, keepdims=keepdims))
_reg("nanmean", lambda a, axis=None, keepdims=False:
     jnp.nanmean(a, axis=axis, keepdims=keepdims))
_reg("nanstd", lambda a, axis=None, ddof=0, keepdims=False:
     jnp.nanstd(a, axis=axis, ddof=ddof, keepdims=keepdims))
_reg("nanvar", lambda a, axis=None, ddof=0, keepdims=False:
     jnp.nanvar(a, axis=axis, ddof=ddof, keepdims=keepdims))
_reg("count_nonzero", lambda a, axis=None:
     jnp.count_nonzero(a, axis=axis), differentiable=False)
_reg("bincount", lambda x, weights=None, minlength=0:
     jnp.bincount(x, weights=weights, minlength=minlength),
     differentiable=False)
_reg("digitize", lambda x, bins, right=False:
     jnp.digitize(x, bins, right=right), differentiable=False)
_reg("searchsorted", lambda a, v, side="left":
     jnp.searchsorted(a, v, side=side), differentiable=False)

# ---- elementwise / math ---------------------------------------------------

_reg("interp", lambda x, xp, fp, left=None, right=None:
     jnp.interp(x, xp, fp, left=left, right=right))
_reg("nan_to_num", lambda x, nan=0.0, posinf=None, neginf=None:
     jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf))
_reg("heaviside", lambda x1, x2: jnp.heaviside(x1, x2))
_reg("copysign", lambda x1, x2: jnp.copysign(x1, x2))
_reg("ldexp", lambda x1, x2: jnp.ldexp(x1, x2))
_reg("signbit", lambda x: jnp.signbit(x), differentiable=False)
_reg("float_power", lambda x1, x2: jnp.float_power(x1, x2))
_reg("fmod", lambda x1, x2: jnp.fmod(x1, x2))
_reg("remainder", lambda x1, x2: jnp.remainder(x1, x2))
_reg("gcd", lambda x1, x2: jnp.gcd(x1, x2), differentiable=False)
_reg("lcm", lambda x1, x2: jnp.lcm(x1, x2), differentiable=False)
_reg("bitwise_and", lambda x1, x2: jnp.bitwise_and(x1, x2),
     differentiable=False)
_reg("bitwise_or", lambda x1, x2: jnp.bitwise_or(x1, x2),
     differentiable=False)
_reg("bitwise_xor", lambda x1, x2: jnp.bitwise_xor(x1, x2),
     differentiable=False)
_reg("bitwise_not", lambda x: jnp.bitwise_not(x), differentiable=False)
_reg("left_shift", lambda x1, x2: jnp.left_shift(x1, x2),
     differentiable=False)
_reg("right_shift", lambda x1, x2: jnp.right_shift(x1, x2),
     differentiable=False)
_reg("cross", lambda a, b, axis=-1: jnp.cross(a, b, axis=axis))
_reg("polyval", lambda p, x: jnp.polyval(p, x))
_reg("vander", lambda x, N=None, increasing=False:
     jnp.vander(x, N=N, increasing=increasing))
_reg("ediff1d", lambda a, to_end=None, to_begin=None:
     jnp.ediff1d(a, to_end=to_end, to_begin=to_begin))
_reg("diff", lambda a, n=1, axis=-1: jnp.diff(a, n=n, axis=axis))
_reg("trapz", lambda y, x=None, dx=1.0, axis=-1:
     jnp.trapezoid(y, x=x, dx=dx, axis=axis))
_reg("unwrap", lambda p, axis=-1: jnp.unwrap(p, axis=axis))
_reg("isclose", lambda a, b, rtol=1e-5, atol=1e-8, equal_nan=False:
     jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
     differentiable=False)
_reg("isposinf", lambda x: jnp.isposinf(x), differentiable=False)
_reg("isneginf", lambda x: jnp.isneginf(x), differentiable=False)

# ---- shape / assembly -----------------------------------------------------

_reg("hstack", lambda *arrays: jnp.hstack(arrays))
_reg("vstack", lambda *arrays: jnp.vstack(arrays))
_reg("dstack", lambda *arrays: jnp.dstack(arrays))
_reg("column_stack", lambda *arrays: jnp.column_stack(arrays))
_reg("atleast_1d", lambda a: jnp.atleast_1d(a))
_reg("atleast_2d", lambda a: jnp.atleast_2d(a))
_reg("atleast_3d", lambda a: jnp.atleast_3d(a))
_reg("moveaxis", lambda a, source, destination:
     jnp.moveaxis(a, source, destination))
_reg("rollaxis", lambda a, axis, start=0: jnp.rollaxis(a, axis, start))
_reg("append", lambda arr, values, axis=None:
     jnp.append(arr, values, axis=axis))
_reg("insert", lambda arr, obj, values, axis=None:
     jnp.insert(arr, obj, values, axis=axis))
_reg("delete", lambda arr, obj, axis=None:
     jnp.delete(arr, obj, axis=axis))
_reg("resize_array", lambda a, new_shape: jnp.resize(a, new_shape))
_reg("trim_zeros", lambda filt, trim="fb": jnp.trim_zeros(filt, trim=trim),
     differentiable=False)
_reg("flatnonzero", lambda a: jnp.flatnonzero(a), differentiable=False)
_reg("argwhere", lambda a: jnp.argwhere(a), differentiable=False)
_reg("compress", lambda condition, a, axis=None:
     jnp.compress(condition, a, axis=axis))
_reg("extract", lambda condition, arr: jnp.extract(condition, arr),
     differentiable=False)
_reg("choose", lambda a, *choices: jnp.choose(a, list(choices),
                                              mode="clip"))
_reg("unravel_index", lambda indices, shape:
     jnp.stack(jnp.unravel_index(indices, shape)), differentiable=False)
_reg("ravel_multi_index", lambda multi_index, dims:
     jnp.ravel_multi_index(tuple(multi_index), dims, mode="clip"),
     differentiable=False)
_reg("tri", lambda N, M=None, k=0: jnp.tri(N, M=M, k=k),
     differentiable=False)
_reg("fill_diagonal", lambda a, val:
     jnp.fill_diagonal(a, val, inplace=False))

# ---- window functions -----------------------------------------------------

_reg("hamming", lambda M: jnp.hamming(M), differentiable=False)
_reg("hanning", lambda M: jnp.hanning(M), differentiable=False)
_reg("blackman", lambda M: jnp.blackman(M), differentiable=False)
_reg("bartlett", lambda M: jnp.bartlett(M), differentiable=False)
_reg("kaiser", lambda M, beta=14.0: jnp.kaiser(M, beta),
     differentiable=False)
