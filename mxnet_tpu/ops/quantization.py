"""INT8 quantization operators.

Reference capability: src/operator/quantization/ — quantize/dequantize/
requantize ops plus quantized conv/FC kernels (MKLDNN int8 on CPU,
cuDNN int8 on GPU) and the calibration machinery (calibrate.cc).

TPU-native redesign: symmetric int8 quantization (zero-point 0) feeding
``lax.dot_general``/``lax.conv_general_dilated`` with
``preferred_element_type=int32`` — the layout XLA lowers onto the MXU's
int8 systolic path; scales stay per-tensor f32 scalars so the requantize
epilogue fuses into the matmul.  The graph-rewrite driver lives in
mxnet_tpu/contrib/quantization.py.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["quantize", "quantize_v2", "dequantize", "requantize",
           "quantized_fully_connected", "quantized_conv"]


def _scale_of(min_range, max_range, dtype):
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    amax = jnp.maximum(amax, 1e-12)
    qmax = 127.0 if dtype == "int8" else 255.0
    return qmax / amax


@register("quantize", differentiable=False, num_outputs=3)
def quantize(data, min_range, max_range, out_type="int8"):
    """f32 -> int8 with explicit range (reference quantize.cc).  Returns
    (quantized, min_range, max_range) like the reference's 3-output op."""
    scale = _scale_of(min_range, max_range, out_type)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, jnp.asarray(min_range, jnp.float32), jnp.asarray(
        max_range, jnp.float32)


@register("quantize_v2", differentiable=False, num_outputs=3)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """Range-auto quantize (reference quantize_v2.cc): calibrated range if
    given, else the tensor's observed min/max."""
    if min_calib_range is None or max_calib_range is None:
        amax = jnp.max(jnp.abs(data))
        mn, mx = -amax, amax
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    scale = _scale_of(mn, mx, out_type)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, jnp.asarray(mn, jnp.float32), jnp.asarray(mx, jnp.float32)


@register("dequantize", differentiable=False)
def dequantize(data, min_range, max_range, out_type="float32"):
    scale = _scale_of(min_range, max_range, "int8")
    return data.astype(jnp.float32) / scale


@register("requantize", differentiable=False, num_outputs=3)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator -> int8 (reference requantize.cc): rescale the
    wide accumulator into the calibrated int8 output range."""
    # data: int32 with implied scale (min_range..max_range per int32 unit)
    real = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / (2.0 ** 31))
    if min_calib_range is None:
        amax = jnp.max(jnp.abs(real))
        mn, mx = -amax, amax
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    scale = _scale_of(mn, mx, "int8")
    q = jnp.clip(jnp.round(real * scale), -127, 127).astype(jnp.int8)
    return q, mn, mx


@register("quantized_fully_connected", differentiable=False)
def quantized_fully_connected(x_q, w_q, bias, scale_x, scale_w,
                              num_hidden=None, flatten=True, no_bias=False):
    """int8 × int8 → int32 on the MXU, f32 epilogue (reference
    quantized_fully_connected.cc).  x_q: (N, K) int8; w_q: (O, K) int8;
    bias: f32 (unquantized — added after rescale); scales: f32 scalars."""
    if flatten and x_q.ndim > 2:
        x_q = x_q.reshape(x_q.shape[0], -1)
    acc = lax.dot_general(x_q, w_q, (((x_q.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) / (scale_x * scale_w)
    if bias is not None and not no_bias:
        y = y + bias
    return y


@register("quantized_conv", differentiable=False)
def quantized_conv(x_q, w_q, bias, scale_x, scale_w, kernel=None,
                   stride=None, dilate=None, pad=None, num_filter=None,
                   num_group=1, no_bias=False, layout=None):
    """int8 convolution, int32 accumulation (reference quantized_conv.cc);
    activation layout per ``layout`` (default NCHW), OIHW weights —
    mirrors ops/nn.py convolution's dimension handling."""
    from .nn import _conv_dims

    nd = x_q.ndim
    nspatial = nd - 2
    stride = tuple(stride) if stride else (1,) * nspatial
    dilate = tuple(dilate) if dilate else (1,) * nspatial
    pad = tuple(pad) if pad else (0,) * nspatial
    dn_layout = _conv_dims(nd, layout)
    dn = lax.conv_dimension_numbers(
        x_q.shape, w_q.shape, dn_layout[:2] + (dn_layout[2],))
    acc = lax.conv_general_dilated(
        x_q, w_q, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group, preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) / (scale_x * scale_w)
    if bias is not None and not no_bias:
        c_axis = dn_layout[0].index("C")
        shape = [1] * nd
        shape[c_axis] = bias.shape[0]
        y = y + bias.reshape(shape)
    return y
