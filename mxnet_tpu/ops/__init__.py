"""Operator layer: registry + op definitions.

Reference: src/operator/ (201k LoC across nn/tensor/numpy/contrib/random) —
here each op is a pure JAX function registered into mxnet_tpu.ops.registry
(see registry.py for the dispatch design).
"""
from . import core, nn, quantization  # noqa: F401  (registration effects)
from . import detection, linalg, np_tail  # noqa: F401  (registration)
from . import optimizer_ops, tensor_tail, legacy  # noqa: F401  (registration)
from . import random_ops, contrib_tail  # noqa: F401  (registration)
from . import image_ops  # noqa: F401  (registration: _image_* + samplers)
from . import parity  # noqa: F401  (reference-name parity tail; LAST —
#                        aliases resolve against everything above)
from .registry import Operator, apply_op, get_op, invoke, list_ops, register

__all__ = ["Operator", "register", "get_op", "list_ops", "invoke", "apply_op"]
