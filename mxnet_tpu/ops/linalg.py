"""Linear-algebra operator family.

Reference parity: the LAPACK-backed ``_linalg_*`` ops
(/root/reference/src/operator/tensor/la_op.cc — gemm, gemm2, potrf, potri,
trmm, trsm, syrk, gelqf, syevd, sumlogdiag, extract/make diag+trian,
inverse, det, slogdet) and the numpy linalg front-end
(/root/reference/src/operator/numpy/linalg/ — svd/eig/eigh/qr/solve/
lstsq/pinv/...).

TPU-native: everything XLA lowers natively (cholesky, qr, svd, eigh,
triangular solves, det) is a pure jnp/lax expression — batched, fused,
and differentiable through the standard vjp record path.  The
nonsymmetric eigendecomposition has no TPU lowering (same as the
reference, where it is LAPACK-on-CPU); it uses the documented host
fallback (``jax.pure_callback`` to numpy) — the SURVEY §7
storage-fallback pattern.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


def _t(x):
    """Batched matrix transpose (leading dims are batch)."""
    return jnp.swapaxes(x, -1, -2)


# ---- la_op.cc family ------------------------------------------------------

@register("linalg_gemm")
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    """C' = alpha * op(A) @ op(B) + beta * C  (la_op.cc LaMatrixMacOp).
    ``axis`` names the matrix-row axis (reference semantics): for
    axis != -2 the row axis is moved into place, multiplied, and moved
    back."""
    if axis != -2:
        A = jnp.moveaxis(A, axis, -2)
        B = jnp.moveaxis(B, axis, -2)
        C = jnp.moveaxis(C, axis, -2)
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    out = alpha * jnp.matmul(a, b) + beta * C
    return jnp.moveaxis(out, -2, axis) if axis != -2 else out


@register("linalg_gemm2")
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def linalg_potrf(A):
    """Cholesky factor L with A = L L^T (la_op.cc potrf)."""
    return jnp.linalg.cholesky(A)


@register("linalg_potri")
def linalg_potri(L):
    """Inverse of A from its Cholesky factor: A^-1 = (L L^T)^-1."""
    eye = jnp.broadcast_to(jnp.eye(L.shape[-1], dtype=L.dtype), L.shape)
    linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
    return jnp.matmul(_t(linv), linv)


@register("linalg_trmm")
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matrix multiply: out = alpha * op(A) @ B (or B @ op(A))."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    tri = _t(tri) if transpose else tri
    out = jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B)
    return alpha * out


@register("linalg_trsm")
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Solve op(A) X = alpha B (or X op(A) = alpha B), A triangular."""
    return lax.linalg.triangular_solve(
        A, alpha * B, left_side=not rightside, lower=lower,
        transpose_a=transpose)


@register("linalg_syrk")
def linalg_syrk(A, transpose=False, alpha=1.0):
    """Symmetric rank-k: alpha * A A^T (or A^T A when transpose)."""
    return alpha * (jnp.matmul(_t(A), A) if transpose
                    else jnp.matmul(A, _t(A)))


@register("linalg_gelqf", num_outputs=2)
def linalg_gelqf(A):
    """LQ factorization A = L Q with Q orthonormal rows (la_op.cc gelqf)."""
    q, r = jnp.linalg.qr(_t(A), mode="reduced")
    return _t(r), _t(q)


@register("linalg_syevd", num_outputs=2)
def linalg_syevd(A):
    """Symmetric eigendecomposition: returns (U, lambda) with A = U^T
    diag(lambda) U (la_op.cc syevd row-vector convention)."""
    w, v = jnp.linalg.eigh(A)
    return _t(v), w


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_extractdiag")
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag")
def linalg_makediag(a, offset=0):
    base = jnp.apply_along_axis(jnp.diag, -1, a) if a.ndim > 1 else \
        jnp.diag(a)
    if offset == 0:
        return base
    n = a.shape[-1] + abs(offset)
    out_shape = a.shape[:-1] + (n, n)
    out = jnp.zeros(out_shape, a.dtype)
    idx = jnp.arange(a.shape[-1])
    rows = idx if offset >= 0 else idx - offset
    cols = idx + offset if offset >= 0 else idx
    return out.at[..., rows, cols].set(a)


@register("linalg_extracttrian")
def linalg_extracttrian(A, offset=0, lower=True):
    """Pack the (lower/upper) triangle into a vector (la_op.cc)."""
    n = A.shape[-1]
    rows, cols = _np.tril_indices(n, k=offset) if lower else \
        _np.triu_indices(n, k=offset)
    return A[..., rows, cols]


@register("linalg_maketrian")
def linalg_maketrian(a, offset=0, lower=True):
    # infer n from packed length L = n(n+1)/2 (+/- offset handling as in
    # la_op.cc: offset shrinks the triangle)
    L = a.shape[-1]
    k = abs(offset)
    n = int((_np.sqrt(8 * L + 1) - 1) / 2) + k
    rows, cols = _np.tril_indices(n, k=-k if offset <= 0 else 0) if lower \
        else _np.triu_indices(n, k=k if offset >= 0 else 0)
    rows, cols = rows[:L], cols[:L]
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return out.at[..., rows, cols].set(a)


@register("linalg_inverse")
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("linalg_det")
def linalg_det(A):
    return jnp.linalg.det(A)


@register("linalg_slogdet", num_outputs=2)
def linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


# ---- numpy/linalg front-end ----------------------------------------------

@register("linalg_cholesky")
def linalg_cholesky(A, upper=False):
    L = jnp.linalg.cholesky(A)
    return _t(L) if upper else L


@register("linalg_qr", num_outputs=2)
def linalg_qr(A, mode="reduced"):
    q, r = jnp.linalg.qr(A, mode=mode)
    return q, r


@register("linalg_svd", num_outputs=3)
def linalg_svd(A, full_matrices=False):
    u, s, vt = jnp.linalg.svd(A, full_matrices=full_matrices)
    return u, s, vt


@register("linalg_svdvals")
def linalg_svdvals(A):
    return jnp.linalg.svd(A, compute_uv=False)


@register("linalg_eigh", num_outputs=2)
def linalg_eigh(A, UPLO="L"):
    w, v = jnp.linalg.eigh(A, UPLO=UPLO)
    return w, v


@register("linalg_eigvalsh")
def linalg_eigvalsh(A, UPLO="L"):
    return jnp.linalg.eigvalsh(A, UPLO=UPLO)


def _host_eig(A):
    w, v = _np.linalg.eig(_np.asarray(A))
    return w.astype(_np.complex64), v.astype(_np.complex64)


@register("linalg_eig", num_outputs=2, differentiable=False)
def linalg_eig(A):
    """Nonsymmetric eigendecomposition.  No TPU lowering exists (XLA
    restriction; the reference is LAPACK-on-CPU too, c_lapack_api.h) —
    host fallback via pure_callback, complex64 outputs."""
    out_shapes = (jax.ShapeDtypeStruct(A.shape[:-1], jnp.complex64),
                  jax.ShapeDtypeStruct(A.shape, jnp.complex64))
    return jax.pure_callback(_host_eig, out_shapes, A, vmap_method="sequential")


@register("linalg_eigvals", differentiable=False)
def linalg_eigvals(A):
    out_shape = jax.ShapeDtypeStruct(A.shape[:-1], jnp.complex64)
    return jax.pure_callback(
        lambda a: _np.linalg.eigvals(_np.asarray(a)).astype(_np.complex64),
        out_shape, A, vmap_method="sequential")


@register("linalg_solve")
def linalg_solve(A, b):
    return jnp.linalg.solve(A, b)


@register("linalg_lstsq", num_outputs=4, differentiable=False)
def linalg_lstsq(A, b, rcond=None):
    x, resid, rank, sv = jnp.linalg.lstsq(A, b, rcond=rcond)
    return x, resid, rank, sv


@register("linalg_pinv")
def linalg_pinv(A, rcond=None):
    return jnp.linalg.pinv(A, rcond=rcond)


@register("linalg_matrix_rank", differentiable=False)
def linalg_matrix_rank(A, tol=None):
    return jnp.linalg.matrix_rank(A, tol=tol)


@register("linalg_matrix_power")
def linalg_matrix_power(A, n=1):
    return jnp.linalg.matrix_power(A, n)


@register("linalg_norm")
def linalg_norm(A, ord=None, axis=None, keepdims=False):
    return jnp.linalg.norm(A, ord=ord, axis=axis, keepdims=keepdims)


@register("linalg_cond", differentiable=False)
def linalg_cond(A, p=None):
    return jnp.linalg.cond(A, p=p)


@register("linalg_multi_dot")
def linalg_multi_dot(*arrays):
    return jnp.linalg.multi_dot(list(arrays))


@register("linalg_tensorinv")
def linalg_tensorinv(A, ind=2):
    return jnp.linalg.tensorinv(A, ind=ind)


@register("linalg_tensorsolve")
def linalg_tensorsolve(A, b, axes=None):
    return jnp.linalg.tensorsolve(A, b, axes=axes)
