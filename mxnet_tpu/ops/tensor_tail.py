"""Tensor-op long tail: indexing/layout/shape utilities.

Reference: src/operator/tensor/matrix_op.cc (reverse:827, depth_to_space:953,
space_to_depth:997, reshape_like, broadcast_like), indexing_op.cc
(batch_take:730), nn/moments.cc:34, nn/im2col.h, contrib/krprod.cc:75.
Each op is one fused jnp/lax expression; im2col rides
``conv_general_dilated_patches`` (the MXU-friendly unfold) and col2im is its
exact adjoint via ``jax.vjp`` — the reference needed a hand-written scatter
kernel (im2col.h:157) for the same thing.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register


@register("batch_take")
def batch_take(a, indices):
    """output[i] = a[i, indices[i]]  [indexing_op.cc:730; deprecated alias
    of pick]."""
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("broadcast_like")
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    """Broadcast lhs to rhs's shape [matrix_op.cc broadcast_like]; with
    axes given, only those dims are matched."""
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    shape = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        shape[la % lhs.ndim] = rhs.shape[ra % rhs.ndim]
    return jnp.broadcast_to(lhs, tuple(shape))


@register("reshape_like")
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to rhs's shape [matrix_op.cc reshape_like]; the begin/end
    window swaps only that slice of the shape."""
    if lhs_begin is None and rhs_begin is None:
        return lhs.reshape(rhs.shape)
    ls = list(lhs.shape)
    lb = 0 if lhs_begin is None else lhs_begin % (lhs.ndim + 1)
    le = lhs.ndim if lhs_end is None else lhs_end % (lhs.ndim + 1)
    rb = 0 if rhs_begin is None else rhs_begin % (rhs.ndim + 1)
    re_ = rhs.ndim if rhs_end is None else rhs_end % (rhs.ndim + 1)
    return lhs.reshape(tuple(ls[:lb]) + rhs.shape[rb:re_] + tuple(ls[le:]))


@register("reverse")
def reverse(data, axis=0):
    """Flip along axis, alias of flip [matrix_op.cc:827]."""
    axes = axis if isinstance(axis, (tuple, list)) else (axis,)
    return jnp.flip(data, axes)


@register("slice")
def slice(data, begin, end, step=None):  # noqa: A001 - reference op name
    """Basic strided slice with None-tolerant begin/end/step
    [matrix_op.cc slice]."""
    import builtins

    slices = []
    step = step or (None,) * len(begin)
    for i, (b, e) in enumerate(zip(begin, end)):
        s = step[i] if i < len(step) else None
        slices.append(builtins.slice(b, e, s))
    for _ in range(data.ndim - len(slices)):
        slices.append(builtins.slice(None))
    return data[tuple(slices)]


@register("moments", num_outputs=2)
def moments(data, axes=None, keepdims=False):
    """mean, var over axes [nn/moments.cc:34]."""
    ax = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.mean(jnp.square(data - mean), axis=ax, keepdims=keepdims)
    if not keepdims:
        mean = mean.reshape(var.shape)
    return mean, var


@register("depth_to_space")
def depth_to_space(data, block_size):
    """NCHW depth→space [matrix_op.cc:953 — reshape/transpose chain kept
    verbatim so the element order matches DCR mode]."""
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def space_to_depth(data, block_size):
    """NCHW space→depth, inverse of depth_to_space [matrix_op.cc:997]."""
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


def _im2col(data, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    n, c, _, _ = data.shape
    kh, kw = kernel
    patches = jax.lax.conv_general_dilated_patches(
        data, filter_shape=(kh, kw), window_strides=tuple(stride),
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=tuple(dilate))
    # patches: (N, C*kh*kw, OH, OW) with channel-major ordering = reference's
    # (c * kh + ki) * kw + kj layout (im2col.h:87)
    return patches.reshape(n, c * kh * kw, -1)


@register("im2col")
def im2col(data, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Unfold conv patches to columns (N, C*kh*kw, L) [nn/im2col.h:87]."""
    return _im2col(data, tuple(kernel), tuple(stride), tuple(dilate),
                   tuple(pad))


@register("col2im")
def col2im(data, input_size, kernel, stride=(1, 1), dilate=(1, 1),
           pad=(0, 0)):
    """Fold columns back, summing overlaps [nn/im2col.h:157] — computed as
    the exact vjp (adjoint) of im2col at the target geometry."""
    n = data.shape[0]
    shape = (n, input_size[0], input_size[1], input_size[2]) \
        if len(input_size) == 3 else tuple(input_size)
    f = functools.partial(_im2col, kernel=tuple(kernel),
                          stride=tuple(stride), dilate=tuple(dilate),
                          pad=tuple(pad))
    _, vjp = jax.vjp(f, jnp.zeros(shape, data.dtype))
    return vjp(data)[0]


@register("khatri_rao")
def khatri_rao(*matrices):
    """Column-wise Khatri-Rao product [contrib/krprod.cc:75]."""
    out = matrices[0]
    for m in matrices[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, m.shape[1])
    return out


@register("argmax_channel", differentiable=False)
def argmax_channel(data):
    """Argmax along the trailing axis of the 2-D flattened input
    [broadcast_reduce_op_index.cc:82]."""
    return jnp.argmax(data.reshape(data.shape[0], -1), axis=-1).astype(
        jnp.float32)
