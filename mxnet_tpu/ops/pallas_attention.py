"""Flash attention: Pallas TPU kernel + blockwise-JAX fallback.

Reference parity: the reference's fastest attention path is
``_contrib_interleaved_matmul_selfatt_qk/valatt`` (src/operator/contrib/
transformer.cc:650-826) — cuBLAS strided-batch GEMMs that still materialize
the (Tq, Tk) score matrix in HBM.  The TPU-native design never materializes
it: the Pallas kernel streams K/V blocks through VMEM with an online-softmax
running (m, l, acc) state, so memory is O(T·D) and the MXU sees back-to-back
(block_q × D) @ (D × block_k) matmuls.

Three tiers:
- ``flash_attention``     — Pallas kernels fwd AND bwd (TPU;
                            ``interpret=True`` elsewhere so the same
                            kernels are testable on CPU): the backward
                            recomputes per-block probabilities from the
                            saved logsumexp in dedicated dq and dk/dv
                            kernels, with in-kernel probability dropout.
- ``blockwise_attention`` — pure-JAX lax.scan online softmax;
                            differentiable end-to-end; the fallback path.
- dense                   — plain einsum chain (ops/nn.py), best for short T.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30

# registered hand-set defaults — the mx.autotune sites' reference
# configs.  MXNET_AUTOTUNE=0 resolves to exactly these literals, so
# the untuned stack is bit-and-perf identical to the pre-autotune one.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
DEFAULT_BLOCKWISE_K = 256


def _tuned_flash_blocks(q, k, causal, block_q, block_k, dropout_p=0.0):
    """Resolve (block_q, block_k): explicit caller values win, else
    the mx.autotune ``flash_attention`` winner for this workload key,
    else the hand-set defaults.  A malformed stored config degrades to
    the defaults with a counted fallback — never an error.

    Dropout pins the defaults: the in-kernel keep mask is seeded per
    (q-block, k-block) TILE, so different block sizes draw different
    masks — a tuned winner measured bit-identical on the dropout-free
    path would still change dropout numerics.  Only explicit block
    arguments override blocks under dropout."""
    if block_q is not None and block_k is not None:
        return int(block_q), int(block_k)
    from .. import autotune as _at

    bq, bk = DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
    if dropout_p > 0.0:
        return (int(block_q) if block_q is not None else bq,
                int(block_k) if block_k is not None else bk)
    if _at.is_enabled():
        B, H, Tq, D = q.shape
        cfg = _at.lookup(
            "flash_attention",
            (B, H, Tq, k.shape[2], D, str(q.dtype), bool(causal)),
            (bq, bk))
        try:
            bq, bk = int(cfg[0]), int(cfg[1])
        except (TypeError, ValueError, IndexError):
            _at.fallback("invalid_config")
            bq, bk = DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
    return (int(block_q) if block_q is not None else bq,
            int(block_k) if block_k is not None else bk)


def _tuned_blockwise_k(q, k, causal, block_k, dropout_p=0.0):
    """``block_k`` for ``blockwise_attention``: explicit value, tuned
    winner, or today's literal 256.  Dropout pins the default — the
    per-block threefry mask is folded by k-block index, so a different
    block_k draws different masks (same contract as the flash
    kernel)."""
    if block_k is not None:
        return int(block_k)
    from .. import autotune as _at

    bk = DEFAULT_BLOCKWISE_K
    if dropout_p > 0.0:
        return bk
    if _at.is_enabled():
        B, H, Tq, D = q.shape
        cfg = _at.lookup(
            "blockwise_attention",
            (B, H, Tq, k.shape[2], D, str(q.dtype), bool(causal)), bk)
        try:
            bk = int(cfg)
        except (TypeError, ValueError):
            _at.fallback("invalid_config")
            bk = DEFAULT_BLOCKWISE_K
    return bk


# ---------------------------------------------------------------------------
# blockwise (pure JAX) — the reference semantics + the backward path
# ---------------------------------------------------------------------------
def blockwise_attention(q, k, v, causal=False, sm_scale=None, block_k=None,
                        dropout_p=0.0, dropout_key=None):
    """Memory-efficient attention via lax.scan over K/V blocks.

    q, k, v: (B, H, T, D).  Differentiable; O(T·D + T·block_k) live memory.

    ``dropout_p`` drops attention PROBABILITIES (the BERT recipe) without
    ever materializing the (T, T) matrix: the softmax denominator
    accumulates the undropped mass while the numerator applies a
    per-block threefry mask — exactly dropout(softmax(s)) @ v, computed
    online.  Deterministic per ``dropout_key``, so the vjp recomputation
    sees the same mask.

    ``block_k=None`` (default) resolves through the mx.autotune
    ``blockwise_attention`` site: the hand-set literal 256 when
    autotune is off or cold (and always under dropout — the per-block
    mask partition must not move with a tuned block size)."""
    block_k = _tuned_blockwise_k(q, k, causal, block_k,
                                 dropout_p=float(dropout_p))
    if dropout_p > 0.0 and dropout_key is None:
        raise ValueError(
            "blockwise_attention: dropout_p > 0 requires dropout_key "
            "(e.g. jax.random.PRNGKey / mxnet_tpu.random.take_key())")
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    block_k = min(block_k, Tk)
    nk = -(-Tk // block_k)
    pad = nk * block_k - Tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, nk, block_k, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nk, block_k, D).transpose(2, 0, 1, 3, 4)
    qs = q.astype(jnp.float32) * scale
    q_idx = jnp.arange(Tq)

    def body(carry, inp):
        m, l, acc = carry
        kblk, vblk, j = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, kblk.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        k_idx = j * block_k + jnp.arange(block_k)
        valid = k_idx < Tk
        if causal:
            valid = valid[None, :] & (k_idx[None, :] <= q_idx[:, None])
            s = jnp.where(valid[None, None], s, _NEG_INF)
        else:
            s = jnp.where(valid[None, None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(-1)
        if dropout_p > 0.0:
            keep = 1.0 - dropout_p
            mask_bits = jax.random.bernoulli(
                jax.random.fold_in(dropout_key, j), keep, p.shape)
            p_num = p * mask_bits.astype(p.dtype) / keep
        else:
            p_num = p
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p_num, vblk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return (m_new, l, acc), None

    m0 = jnp.full((B, H, Tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    a0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, a0), (kb, vb, jnp.arange(nk)))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels (forward + flash backward; reference fwd-only equivalent:
# src/operator/contrib/transformer.cc:650-826)
# ---------------------------------------------------------------------------
def _tile_keep_mask(seed, bh, qi, j, shape, dropout_p, interpret):
    """Deterministic per-tile keep mask.

    Seeding by (seed, bh, qi, j) makes the SAME mask reproducible from the
    forward kernel, the dq kernel (fixed qi, looping j) and the dkv kernel
    (fixed j, looping qi) without storing any bits.  On TPU hardware the
    bits come from the core PRNG (pltpu.prng_*); interpret mode has no
    lowering for those, so it derives a threefry mask instead — each
    backend is self-consistent across its fwd/bwd passes, which is the
    only requirement (masks need not match across backends)."""
    if interpret:
        key = jax.random.fold_in(jax.random.fold_in(jax.random.fold_in(
            jax.random.PRNGKey(seed), bh), qi), j)
        return jax.random.bernoulli(key, 1.0 - dropout_p, shape)
    from jax.experimental.pallas import tpu as pltpu

    pltpu.prng_seed(seed, bh, qi, j)
    bits = pltpu.prng_random_bits(shape)
    thresh = jnp.uint32(int((1.0 - dropout_p) * float(2 ** 32 - 1)))
    return bits.astype(jnp.uint32) < thresh


def _flash_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                  causal, block_q, block_k, seq_k, dropout_p, interpret):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, D)
    D = q.shape[-1]
    nk = pl.cdiv(seq_k, block_k)
    if causal:
        # skip fully-masked K blocks right of the diagonal
        nk = jnp.minimum(nk, pl.cdiv((qi + 1) * block_q, block_k))

    def body(j, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (block_q, block_k)
        k_idx = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_idx < seq_k
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (k_idx <= q_idx)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        # denominator accumulates UNdropped mass (the BERT recipe:
        # dropout(softmax(s)) @ v — normalization sees the full softmax)
        l_new = l * corr + p.sum(-1)
        if dropout_p > 0.0:
            keep = _tile_keep_mask(seed_ref[0], bh, qi, j, p.shape,
                                   dropout_p, interpret)
            p = p * keep.astype(p.dtype) / (1.0 - dropout_p)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    a0 = jnp.zeros((block_q, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, a0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    # lse rides a (8, block_q) tile — Mosaic requires the last two block
    # dims be (8k, 128k)-aligned, so a flat (1, block_q) row is illegal on
    # real TPU; sublane-broadcast and let the caller slice row 0
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    lse_ref[0, 0] = jax.lax.broadcast_in_dim(lse, (8, block_q), (1,))


def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, *, scale, causal, block_q, block_k,
                   seq_k, dropout_p, interpret):
    """dq for one (bh, q-block): ds = p∘(msc∘(dO·Vᵀ) − Δ); dq = scale·ds·K."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    qs = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0, 0, :]           # row 0 of the (8, block_q) tile
    delta = delta_ref[0, 0, 0, :]
    D = qs.shape[-1]
    nk = pl.cdiv(seq_k, block_k)
    if causal:
        nk = jnp.minimum(nk, pl.cdiv((qi + 1) * block_q, block_k))

    def body(j, dq):
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(qs, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_idx = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_idx < seq_k
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (k_idx <= q_idx)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                  # rows sum to 1
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _tile_keep_mask(seed_ref[0], bh, qi, j, p.shape,
                                   dropout_p, interpret)
            dp = dp * keep.astype(dp.dtype) / (1.0 - dropout_p)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros((block_q, D),
                                                  jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, *, scale, causal, block_q,
                    block_k, seq_q, seq_k, dropout_p, interpret):
    """dk/dv for one (bh, k-block), looping q blocks.

    dv = (p∘msc)ᵀ·dO;  dk = scale·dsᵀ·Q  with the SAME per-tile dropout
    mask as the forward (regenerated, not stored)."""
    bh = pl.program_id(0)
    j = pl.program_id(1)
    kblk = k_ref[0].astype(jnp.float32)               # (block_k, D)
    vblk = v_ref[0].astype(jnp.float32)
    D = kblk.shape[-1]
    nq = pl.cdiv(seq_q, block_q)

    def body(qi, carry):
        dk, dv = carry
        qs = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(
            jnp.float32) * scale
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, qi, 0, :]      # (nq, 8, block_q) layout, row 0
        delta = delta_ref[0, qi, 0, :]
        s = jax.lax.dot_general(qs, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_idx = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        q_idx = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        valid = (k_idx < seq_k) & (q_idx < seq_q)
        if causal:
            valid = valid & (k_idx <= q_idx)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(valid, p, 0.0)                  # padded q rows -> 0
        if dropout_p > 0.0:
            keep = _tile_keep_mask(seed_ref[0], bh, qi, j, p.shape,
                                   dropout_p, interpret).astype(p.dtype) \
                / (1.0 - dropout_p)
        else:
            keep = None
        pm = p * keep if keep is not None else p
        dv = dv + jax.lax.dot_general(
            pm, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if keep is not None:
            dp = dp * keep
        ds = p * (dp - delta[:, None])
        dk = dk + jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, D), jnp.float32)
    dv0 = jnp.zeros((block_k, D), jnp.float32)
    # causal: q blocks strictly left of this k block see only masked score
    lo = (j * block_k) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(lo, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)               # already scale·dsᵀ·Qs
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _smem_spec():
    """BlockSpec for the scalar dropout seed (SMEM on TPU)."""
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _pad_pack(q, k, v, block_q, block_k):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    nq = -(-Tq // block_q)
    nk = -(-Tk // block_k)
    pad_q = nq * block_q - Tq
    pad_k = nk * block_k - Tk
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        # k/v must be padded to a block multiple: pl.ds clamps its start at
        # the array edge, which would misalign rows against the k_idx mask
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    qf = q.reshape(B * H, nq * block_q, D)
    kf = k.reshape(B * H, Tk + pad_k, D)
    vf = v.reshape(B * H, Tk + pad_k, D)
    return qf, kf, vf, nq, nk, pad_q, pad_k


def _flash_forward(q, k, v, seed, causal, sm_scale, block_q, block_k,
                   interpret, dropout_p, want_lse=False):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    qf, kf, vf, nq, nk, pad_q, _pad_k = _pad_pack(q, k, v, block_q, block_k)
    Tk_pad = kf.shape[1]

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_k=Tk, dropout_p=dropout_p,
        interpret=interpret)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, nq),
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk_pad, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk_pad, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, i: (b, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, nq * block_q, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, nq, 8, block_q), jnp.float32),
        ],
        interpret=interpret,
    )(seed, qf, kf, vf)
    lse = lse[:, :, 0, :].reshape(B * H, nq * block_q)
    outr = out.reshape(B, H, nq * block_q, D)
    if pad_q:
        outr = outr[:, :, :Tq]
    if want_lse:
        return outr, lse
    return outr


def _flash_backward(q, k, v, seed, out, lse, do, causal, scale, block_q,
                    block_k, interpret, dropout_p, dlse=None):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    qf, kf, vf, nq, nk, pad_q, pad_k = _pad_pack(q, k, v, block_q, block_k)
    Tq_pad, Tk_pad = qf.shape[1], kf.shape[1]
    dof = jnp.pad(do, ((0, 0), (0, 0), (0, pad_q), (0, 0))) if pad_q else do
    dof = dof.reshape(B * H, Tq_pad, D)
    # Δ = rowsum(dO ∘ O) — one cheap fused XLA reduction, fed to both
    # kernels (padded rows contribute zeros via the padded dO)
    outf = (jnp.pad(out, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
            if pad_q else out).reshape(B * H, Tq_pad, D)
    delta = jnp.sum(dof.astype(jnp.float32) * outf.astype(jnp.float32),
                    axis=-1)                           # (B*H, Tq_pad)
    if dlse is not None:
        # lse cotangent folds into the delta term: the softmax backward is
        # ds = p·(dp − Δ) and ∂lse/∂s = p, so ds = p·(dp − (Δ − dlse)) —
        # the kernels need no change to support flash_attention_lse
        dlf = jnp.pad(dlse.reshape(B * H, Tq),
                      ((0, 0), (0, pad_q))) if pad_q \
            else dlse.reshape(B * H, Tq)
        delta = delta - dlf.astype(jnp.float32)

    # widen lse/delta rows to the (nq, 8, block_q) tile layout the kernels
    # read (see _flash_kernel's lse note)
    def _widen(x):
        x = x.reshape(B * H, nq, 1, block_q)
        return jnp.broadcast_to(x, (B * H, nq, 8, block_q))

    lse4 = _widen(lse)
    delta4 = _widen(delta)

    smem_spec = _smem_spec()
    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_k=Tk, dropout_p=dropout_p,
        interpret=interpret)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, nq),
        in_specs=[
            smem_spec,
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk_pad, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk_pad, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, i: (b, i, 0, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, i: (b, i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq_pad, D), q.dtype),
        interpret=interpret,
    )(seed, qf, kf, vf, dof, lse4, delta4)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_q=Tq, seq_k=Tk, dropout_p=dropout_p,
        interpret=interpret)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, nk),
        in_specs=[
            smem_spec,
            pl.BlockSpec((1, Tq_pad, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, Tq_pad, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, nq, 8, block_q), lambda b, j: (b, 0, 0, 0)),
            pl.BlockSpec((1, nq, 8, block_q), lambda b, j: (b, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk_pad, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk_pad, D), v.dtype),
        ],
        interpret=interpret,
    )(seed, qf, kf, vf, dof, lse4, delta4)

    dq = dq.reshape(B, H, Tq_pad, D)[:, :, :Tq]
    dk = dk.reshape(B, H, Tk_pad, D)[:, :, :Tk]
    dv = dv.reshape(B, H, Tk_pad, D)[:, :, :Tk]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_core(q, k, v, seed, causal, sm_scale, block_q, block_k,
                interpret, dropout_p):
    return _flash_forward(q, k, v, seed, causal, sm_scale, block_q,
                          block_k, interpret, dropout_p)


def _flash_core_fwd(q, k, v, seed, causal, sm_scale, block_q, block_k,
                    interpret, dropout_p):
    out, lse = _flash_forward(q, k, v, seed, causal, sm_scale, block_q,
                              block_k, interpret, dropout_p, want_lse=True)
    return out, (q, k, v, seed, out, lse)


def _flash_core_bwd(causal, sm_scale, block_q, block_k, interpret,
                    dropout_p, res, do):
    import numpy as _onp

    q, k, v, seed, out, lse = res
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    dq, dk, dv = _flash_backward(q, k, v, seed, out, lse, do, causal,
                                 scale, block_q, block_k, interpret,
                                 dropout_p)
    dseed = _onp.zeros((1,), jax.dtypes.float0)   # int seed: zero cotangent
    return dq, dk, dv, dseed


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _flash_lse_impl(q, k, v, causal, sm_scale, block_q, block_k,
                    interpret):
    interpret = _default_interpret() if interpret is None else interpret
    seed = jnp.zeros((1,), jnp.int32)
    out, lse = _flash_forward(q, k, v, seed, causal, sm_scale, block_q,
                              block_k, interpret, 0.0, want_lse=True)
    B, H, Tq, _D = q.shape
    return out, lse.reshape(B, H, -1)[:, :, :Tq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_lse(q, k, v, causal=False, sm_scale=None, block_q=512,
                        block_k=512, interpret=None):
    """Flash attention returning (out, logsumexp) — the building block for
    ring/context-parallel composition (parallel/ring.py): partial results
    from different K/V shards merge exactly via their lse.  The lse
    cotangent is honored (it folds into the backward's delta term)."""
    return _flash_lse_impl(q, k, v, causal, sm_scale, block_q, block_k,
                           interpret)


def _flash_lse_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    outs = _flash_lse_impl(q, k, v, causal, sm_scale, block_q, block_k,
                           interpret)
    return outs, (q, k, v) + outs


def _flash_lse_bwd(causal, sm_scale, block_q, block_k, interpret, res,
                   cts):
    q, k, v, out, lse = res
    do, dlse = cts
    interpret = _default_interpret() if interpret is None else interpret
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    B, H, Tq, _ = q.shape
    bq = min(block_q, Tq)
    nq = -(-Tq // bq)
    lse_flat = jnp.pad(lse, ((0, 0), (0, 0), (0, nq * bq - Tq))) \
        .reshape(B * H, nq * bq) if nq * bq != Tq \
        else lse.reshape(B * H, Tq)
    seed = jnp.zeros((1,), jnp.int32)
    dq, dk, dv = _flash_backward(q, k, v, seed, out, lse_flat, do, causal,
                                 scale, block_q, block_k, interpret, 0.0,
                                 dlse=dlse)
    return dq, dk, dv


flash_attention_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=None,
                    block_k=None, interpret=None, dropout_p=0.0,
                    dropout_key=None):
    """Flash attention, (B, H, T, D) layout.

    ``block_q``/``block_k`` default to the mx.autotune
    ``flash_attention`` winner for this workload (the hand-set 512/512
    literals when autotune is off or cold); explicit values always win.

    Forward AND backward run Pallas kernels (interpret mode off-TPU): the
    backward recomputes per-block probabilities from the saved logsumexp —
    residual memory stays O(T·D), and dq/dk/dv are back-to-back MXU
    matmuls (the fused equivalent the reference lacks; its
    interleaved_matmul kernels are fwd-only, transformer.cc:650-826).
    Attention-probability dropout runs IN-kernel from the TPU PRNG: the
    per-tile mask is regenerated — never stored — in fwd, dq and dkv
    passes, seeded by (key, bh, q-block, k-block)."""
    block_q, block_k = _tuned_flash_blocks(q, k, causal, block_q, block_k,
                                           dropout_p=float(dropout_p))
    interpret = _default_interpret() if interpret is None else interpret
    if dropout_p > 0.0:
        if dropout_key is None:
            raise ValueError("flash_attention: dropout_p > 0 requires "
                             "dropout_key")
        # fold ALL key words into the seed: threefry key_data for
        # PRNGKey(s), s < 2^32 is [0, s] — taking only word 0 would give
        # every such key the same mask
        kd = jax.random.key_data(dropout_key).reshape(-1)
        folded = jnp.bitwise_xor(kd[0] * jnp.uint32(2654435761),
                                 kd[-1]) if kd.shape[0] > 1 else kd[0]
        seed = folded.astype(jnp.int32).reshape(1)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    return _flash_core(q, k, v, seed, causal, sm_scale, block_q, block_k,
                       interpret, float(dropout_p))


def _default_interpret():
    return jax.default_backend() != "tpu"


def use_flash(seq_q, seq_k, head_dim, has_mask):
    """Dispatch heuristic for impl='auto': flash pays off once the score
    matrix no longer fits the fusion footprint; dense einsum wins short-T."""
    if has_mask:
        return False
    return seq_q * seq_k >= 256 * 256 and head_dim <= 256
