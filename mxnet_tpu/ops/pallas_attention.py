"""Flash attention: Pallas TPU kernel + blockwise-JAX fallback.

Reference parity: the reference's fastest attention path is
``_contrib_interleaved_matmul_selfatt_qk/valatt`` (src/operator/contrib/
transformer.cc:650-826) — cuBLAS strided-batch GEMMs that still materialize
the (Tq, Tk) score matrix in HBM.  The TPU-native design never materializes
it: the Pallas kernel streams K/V blocks through VMEM with an online-softmax
running (m, l, acc) state, so memory is O(T·D) and the MXU sees back-to-back
(block_q × D) @ (D × block_k) matmuls.

Three tiers:
- ``flash_attention``     — Pallas kernel (TPU; ``interpret=True`` elsewhere
                            so the same kernel is testable on CPU).
- ``blockwise_attention`` — pure-JAX lax.scan online softmax; differentiable;
                            the custom-vjp backward recomputes through this.
- dense                   — plain einsum chain (ops/nn.py), best for short T.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# blockwise (pure JAX) — the reference semantics + the backward path
# ---------------------------------------------------------------------------
def blockwise_attention(q, k, v, causal=False, sm_scale=None, block_k=256,
                        dropout_p=0.0, dropout_key=None):
    """Memory-efficient attention via lax.scan over K/V blocks.

    q, k, v: (B, H, T, D).  Differentiable; O(T·D + T·block_k) live memory.

    ``dropout_p`` drops attention PROBABILITIES (the BERT recipe) without
    ever materializing the (T, T) matrix: the softmax denominator
    accumulates the undropped mass while the numerator applies a
    per-block threefry mask — exactly dropout(softmax(s)) @ v, computed
    online.  Deterministic per ``dropout_key``, so the vjp recomputation
    sees the same mask."""
    if dropout_p > 0.0 and dropout_key is None:
        raise ValueError(
            "blockwise_attention: dropout_p > 0 requires dropout_key "
            "(e.g. jax.random.PRNGKey / mxnet_tpu.random.take_key())")
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    block_k = min(block_k, Tk)
    nk = -(-Tk // block_k)
    pad = nk * block_k - Tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, nk, block_k, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nk, block_k, D).transpose(2, 0, 1, 3, 4)
    qs = q.astype(jnp.float32) * scale
    q_idx = jnp.arange(Tq)

    def body(carry, inp):
        m, l, acc = carry
        kblk, vblk, j = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, kblk.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        k_idx = j * block_k + jnp.arange(block_k)
        valid = k_idx < Tk
        if causal:
            valid = valid[None, :] & (k_idx[None, :] <= q_idx[:, None])
            s = jnp.where(valid[None, None], s, _NEG_INF)
        else:
            s = jnp.where(valid[None, None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(-1)
        if dropout_p > 0.0:
            keep = 1.0 - dropout_p
            mask_bits = jax.random.bernoulli(
                jax.random.fold_in(dropout_key, j), keep, p.shape)
            p_num = p * mask_bits.astype(p.dtype) / keep
        else:
            p_num = p
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p_num, vblk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return (m_new, l, acc), None

    m0 = jnp.full((B, H, Tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    a0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, a0), (kb, vb, jnp.arange(nk)))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------
def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q,
                  block_k, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, D)
    D = q.shape[-1]
    nk = pl.cdiv(seq_k, block_k)
    if causal:
        # skip fully-masked K blocks right of the diagonal
        nk = jnp.minimum(nk, pl.cdiv((qi + 1) * block_q, block_k))

    def body(j, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (block_q, block_k)
        k_idx = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_idx < seq_k
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (k_idx <= q_idx)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    a0 = jnp.zeros((block_q, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, a0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    nq = -(-Tq // block_q)
    nk = -(-Tk // block_k)
    pad_q = nq * block_q - Tq
    pad_k = nk * block_k - Tk
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        # k/v must be padded to a block multiple: pl.ds clamps its start at
        # the array edge, which would misalign rows against the k_idx mask
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    Tk_pad = Tk + pad_k
    qf = q.reshape(B * H, nq * block_q, D)
    kf = k.reshape(B * H, Tk_pad, D)
    vf = v.reshape(B * H, Tk_pad, D)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_k=Tk)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk_pad, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk_pad, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, nq * block_q, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(B, H, nq * block_q, D)
    return out[:, :, :Tq] if pad_q else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=512,
                    block_k=512, interpret=None):
    """Flash attention, (B, H, T, D) layout.

    Forward runs the Pallas kernel (interpret mode off-TPU); backward
    recomputes through ``blockwise_attention`` so residual memory stays
    O(T·D) — the flash-attention trade (extra FLOPs for HBM locality) that
    the MXU absorbs.
    """
    interpret = _default_interpret() if interpret is None else interpret
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    interpret = _default_interpret() if interpret is None else interpret
    out = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                         interpret)
    return out, (q, k, v)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, causal=causal, sm_scale=sm_scale, block_k=block_k),
        q, k, v)
    return vjp(do)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _default_interpret():
    return jax.default_backend() != "tpu"


def use_flash(seq_q, seq_k, head_dim, has_mask):
    """Dispatch heuristic for impl='auto': flash pays off once the score
    matrix no longer fits the fusion footprint; dense einsum wins short-T."""
    if has_mask:
        return False
    return seq_q * seq_k >= 256 * 256 and head_dim <= 256
