"""Legacy CamelCase op names — MXNet 1.x's original operator surface.

Reference: the CamelCase registrations scattered through src/operator/
(Activation: nn/activation.cc:158, LeakyReLU: leaky_relu.cc:135, Dropout:
nn/dropout.cc:151, Pooling: nn/pooling.cc:372, ROIPooling: roi_pooling.cc:
224, SwapAxis: swapaxis.cc:76, UpSampling: nn/upsampling.cc:142, ...).
MXNet 2.0 kept them alive for 1.x model compatibility; a user switching
frameworks expects ``mx.nd.Convolution(...)`` to work verbatim, so the names
are first-class registry entries here:

- where the snake_case op already uses the reference attr names, the
  CamelCase name is a registry alias (same Operator object);
- where the 1.x signature differs (act_type dispatchers, Dropout's implicit
  train-mode RNG), a thin adapter fn maps 1.x attrs onto the TPU-native op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError, thread_state
from . import core, nn
from .registry import alias, register

# ---- direct aliases: snake_case op already speaks the 1.x attr names ------
for _camel, _snake in [
        ("Cast", "cast"), ("Concat", "concat"), ("Flatten", "flatten"),
        ("Reshape", "reshape"), ("Pad", "pad"), ("SwapAxis", "swapaxes"),
        ("SliceChannel", "split"), ("UpSampling", "upsampling"),
        ("BatchNorm", "batch_norm"), ("LayerNorm", "layer_norm"),
        ("GroupNorm", "group_norm"), ("InstanceNorm", "instance_norm"),
        ("LRN", "lrn"), ("CTCLoss", "ctc_loss"),
        ("SequenceMask", "sequence_mask"), ("SequenceLast", "sequence_last"),
        ("SequenceReverse", "sequence_reverse"),
        ("FullyConnected", "fully_connected"),
        ("Convolution", "convolution"), ("Deconvolution", "deconvolution"),
        ("Pooling", "pooling"), ("slice_channel", "split"),
        # elemwise_* kept as registry names (tensor/elemwise_binary_op
        # registrations) — same fused kernels as the broadcast forms here
        ("elemwise_add", "add"), ("elemwise_sub", "subtract"),
        ("elemwise_mul", "multiply"), ("elemwise_div", "divide"),
        ("broadcast_add", "add"), ("broadcast_sub", "subtract"),
        ("broadcast_mul", "multiply"), ("broadcast_div", "divide")]:
    alias(_camel, _snake)


_ACTIVATIONS = {
    "relu": nn.relu, "sigmoid": nn.sigmoid, "tanh": core.tanh,
    "softrelu": nn.softrelu, "softsign": nn.softsign,
    "log_sigmoid": nn.log_sigmoid, "mish": nn.mish,
    "gelu": nn.gelu, "silu": nn.silu,
}


@register("Activation")
def Activation(data, act_type="relu"):
    """act_type dispatcher [nn/activation.cc:158]."""
    try:
        return _ACTIVATIONS[act_type].fn(data)
    except KeyError:
        raise MXNetError("Activation: unknown act_type %r" % (act_type,))


@register("LeakyReLU")
def LeakyReLU(data, gamma=None, act_type="leaky", slope=0.25,
              lower_bound=0.125, upper_bound=0.334):
    """act_type dispatcher [leaky_relu.cc:135].  rrelu samples a per-element
    slope in training (the reference drew from the resource-pool RNG) and
    uses the midpoint slope at inference."""
    if act_type == "leaky":
        return nn.leaky_relu.fn(data, slope=slope)
    if act_type == "prelu":
        return nn.prelu.fn(data, gamma)
    if act_type == "elu":
        return nn.elu.fn(data, alpha=slope)
    if act_type == "selu":
        return nn.selu.fn(data)
    if act_type == "gelu":
        return nn.gelu.fn(data)
    if act_type == "rrelu":
        if thread_state.is_training:
            from .. import random as _random

            u = jax.random.uniform(
                _random.take_key(), data.shape, jnp.float32,
                lower_bound, upper_bound).astype(data.dtype)
            return jnp.where(data >= 0, data, data * u)
        return nn.leaky_relu.fn(
            data, slope=(lower_bound + upper_bound) / 2.0)
    raise MXNetError("LeakyReLU: unknown act_type %r" % (act_type,))


@register("Dropout")
def Dropout(data, p=0.5, mode="training", axes=None, cudnn_off=False):
    """1.x Dropout [nn/dropout.cc:151]: RNG is implicit (the reference
    pulled from the per-device resource pool; here the framework RNG stream,
    mxnet_tpu/random.py) and train-mode gating follows autograd state."""
    active = mode == "always" or (mode == "training"
                                  and thread_state.is_training)
    if not active or p <= 0.0:
        return data
    from .. import random as _random

    return nn.dropout.fn(data, _random.take_key(), p=p, axes=axes)


@register("Embedding")
def Embedding(data, weight, input_dim=None, output_dim=None,
              dtype="float32", sparse_grad=False):
    """1.x Embedding [indexing_op.cc Embedding]: input_dim/output_dim are
    declarative (shape inference in the reference); the lookup is the same
    gather."""
    return core.embedding.fn(data, weight)


def _rnn_num_outputs(attrs):
    if not attrs.get("state_outputs", False):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


def _rnn_fn(data, parameters, state, state_cell=None, state_size=None,
            num_layers=1, mode="lstm", bidirectional=False, p=0.0,
            state_outputs=False, lstm_state_clip_min=None,
            lstm_state_clip_max=None, **_ignored):
    """1.x fused RNN op [rnn.cc:295 RNN]: all layers' weights+biases ride in
    ONE flat parameter vector (weights for every (layer, direction) first,
    then all biases — rnn-inl.h GetRnnParamSize layout), data is TNC.

    The recurrence itself is the gluon fused path (gluon/rnn/rnn_layer.py
    _rnn_forward — lax.scan with the input GEMM batched over time); this
    wrapper only unpacks the packed vector.  Gate order matches _cell_step
    (lstm: i,f,g,o).
    """
    from ..gluon.rnn.rnn_layer import _GATES, _rnn_forward

    T, B, I = data.shape
    H = int(state_size)
    G = _GATES[mode]
    ndir = 2 if bidirectional else 1
    dt = data.dtype

    shapes = []  # (layer, dir) -> (wi_shape, wh_shape)
    for layer in range(int(num_layers)):
        in_sz = I if layer == 0 else H * ndir
        for _d in range(ndir):
            shapes.append(((G * H, in_sz), (G * H, H)))
    flat = parameters.reshape(-1)
    off = 0
    wis, whs = [], []
    for wi_s, wh_s in shapes:
        n = wi_s[0] * wi_s[1]
        wis.append(flat[off:off + n].reshape(wi_s)); off += n
        n = wh_s[0] * wh_s[1]
        whs.append(flat[off:off + n].reshape(wh_s)); off += n
    bis, bhs = [], []
    for _ in shapes:
        bis.append(flat[off:off + G * H]); off += G * H
        bhs.append(flat[off:off + G * H]); off += G * H

    weights = []
    for wi, wh, bi, bh in zip(wis, whs, bis, bhs):
        weights.extend([wi.astype(dt), wh.astype(dt), bi.astype(dt),
                        bh.astype(dt)])
    c0 = state_cell if state_cell is not None else jnp.zeros_like(state)
    key = None
    if p and float(p) > 0 and thread_state.is_training:
        from .. import random as _random

        key = _random.take_key()  # inter-layer dropout, training only
    out, hT, cT = _rnn_forward(data, state, c0, mode, int(num_layers),
                               bool(bidirectional), float(p), key,
                               *weights)
    if mode == "lstm" and lstm_state_clip_min is not None:
        cT = jnp.clip(cT, lstm_state_clip_min, lstm_state_clip_max)
    if not state_outputs:
        return out
    if mode == "lstm":
        return out, hT, cT
    return out, hT


_rnn_fn.__name__ = "RNN"
register("RNN", num_outputs=_rnn_num_outputs)(_rnn_fn)


@register("ROIPooling")
def ROIPooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max-pool ROI quantized to the feature grid [roi_pooling.cc:224].
    rois: (R, 5) of [batch_idx, x1, y1, x2, y2] in image coords.

    Vectorized as two masked max-reductions (H then W): each output bin
    row/col builds a membership mask against the rounded roi bin edges —
    no data-dependent shapes, so it jits on TPU.
    """
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))
    N, C, H, W = data.shape
    R = rois.shape[0]
    bidx = rois[:, 0].astype(jnp.int32)
    # reference: round(coord * scale); end-inclusive grid, min size 1
    x1 = jnp.round(rois[:, 1] * spatial_scale)
    y1 = jnp.round(rois[:, 2] * spatial_scale)
    x2 = jnp.round(rois[:, 3] * spatial_scale)
    y2 = jnp.round(rois[:, 4] * spatial_scale)
    roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
    roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
    bin_h = roi_h / ph
    bin_w = roi_w / pw

    hh = jnp.arange(H, dtype=jnp.float32)
    ww = jnp.arange(W, dtype=jnp.float32)
    pi = jnp.arange(ph, dtype=jnp.float32)
    pj = jnp.arange(pw, dtype=jnp.float32)
    # (R, ph, H): h in [floor(y1 + i*bin_h), ceil(y1 + (i+1)*bin_h))
    hstart = jnp.floor(y1[:, None] + pi[None, :] * bin_h[:, None])
    hend = jnp.ceil(y1[:, None] + (pi[None, :] + 1.0) * bin_h[:, None])
    hmask = (hh[None, None, :] >= hstart[..., None]) & \
            (hh[None, None, :] < hend[..., None])
    wstart = jnp.floor(x1[:, None] + pj[None, :] * bin_w[:, None])
    wend = jnp.ceil(x1[:, None] + (pj[None, :] + 1.0) * bin_w[:, None])
    wmask = (ww[None, None, :] >= wstart[..., None]) & \
            (ww[None, None, :] < wend[..., None])

    neg = jnp.asarray(-jnp.inf, data.dtype)
    x = data[bidx]                                   # (R, C, H, W)
    # reduce H: (R, C, ph, W)
    xh = jnp.max(jnp.where(hmask[:, None, :, :, None], x[:, :, None], neg),
                 axis=3)
    # reduce W: (R, C, ph, pw)
    out = jnp.max(jnp.where(wmask[:, None, None, :, :],
                            xh[:, :, :, None, :], neg), axis=4)
    return jnp.where(jnp.isfinite(out), out, 0.0)
