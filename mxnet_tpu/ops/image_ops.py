"""Image op family — the reference's ``_image_*`` operators, exposed as
``mx.nd.image.*`` / ``mx.npx.image.*``.

Reference: src/operator/image/image_random.cc (_image_normalize:106,
_image_random_resized_crop:121, jitter family), image_resize.cc
(_image_resize:36), crop.cc (_image_crop:39, _image_random_crop:86),
totensor.cc (_image_to_tensor:42).

Conventions (kept from the reference):
- layout is HWC (or NHWC batched) EXCEPT normalize, which runs on the
  CHW/NCHW output of to_tensor;
- to_tensor scales uint8 [0,255] -> float32 [0,1] and moves channels
  first;
- random_* ops draw from the framework RNG stream (reference: per-device
  resource pool) and are registered non-differentiable like their
  MakeZeroGradNodes originals; deterministic ops (to_tensor, normalize,
  crop, resize) keep autograd.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _onp

from .registry import alias, register


def _key():
    from .. import random as _random

    return _random.take_key()


def _batched(x):
    return x.ndim == 4


@register("image_to_tensor")
def image_to_tensor(data):
    """HWC uint8 [0,255] -> CHW float32 [0,1] [totensor.cc:42]."""
    x = data.astype(jnp.float32) / 255.0
    if _batched(data):
        return jnp.transpose(x, (0, 3, 1, 2))
    return jnp.transpose(x, (2, 0, 1))


@register("image_normalize")
def image_normalize(data, mean=0.0, std=1.0):
    """(x - mean) / std on CHW/NCHW float [image_random.cc:106]."""
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    shape = (-1, 1, 1) if not _batched(data) else (1, -1, 1, 1)
    if mean.ndim == 0:
        mean = mean[None]
    if std.ndim == 0:
        std = std[None]
    return (data - mean.reshape(shape)) / std.reshape(shape)


@register("image_resize")
def image_resize(data, size=None, keep_ratio=False, interp=1):
    """Resize HWC/NHWC to size=(w, h) [image_resize.cc:36]."""
    w, h = (size, size) if isinstance(size, int) else tuple(size)
    method = "nearest" if interp == 0 else "bilinear"
    if _batched(data):
        out_shape = (data.shape[0], h, w, data.shape[3])
    else:
        out_shape = (h, w, data.shape[2])
    out = jax.image.resize(data.astype(jnp.float32), out_shape, method)
    return out.astype(data.dtype) if data.dtype == jnp.uint8 else out


@register("image_crop")
def image_crop(data, x=0, y=0, width=1, height=1):
    """Fixed crop at (x, y) size (width, height) [crop.cc:39]."""
    if _batched(data):
        return data[:, y:y + height, x:x + width, :]
    return data[y:y + height, x:x + width, :]


@register("image_random_crop", differentiable=False)
def image_random_crop(data, xrange=(0.0, 1.0), yrange=(0.0, 1.0), width=1,
                      height=1, interp=1):
    """Random-position crop; xrange/yrange bound the start position as
    fractions of the free space [crop.cc:86]."""
    H, W = (data.shape[1], data.shape[2]) if _batched(data) \
        else (data.shape[0], data.shape[1])
    k1, k2 = jax.random.split(_key())
    free_x, free_y = max(0, W - width), max(0, H - height)
    fx = jax.random.uniform(k1, (), minval=xrange[0], maxval=xrange[1])
    fy = jax.random.uniform(k2, (), minval=yrange[0], maxval=yrange[1])
    x0 = jnp.round(fx * free_x).astype(jnp.int32)
    y0 = jnp.round(fy * free_y).astype(jnp.int32)
    if _batched(data):
        return jax.lax.dynamic_slice(
            data, (0, y0, x0, 0),
            (data.shape[0], height, width, data.shape[3]))
    return jax.lax.dynamic_slice(data, (y0, x0, 0),
                                 (height, width, data.shape[2]))


@register("image_random_resized_crop", differentiable=False)
def image_random_resized_crop(data, size=None, scale=(0.08, 1.0),
                              ratio=(3 / 4, 4 / 3), interp=1,
                              max_trial=10):
    """Inception-style area/aspect crop then resize
    [image_random.cc:121].  Geometry is drawn host-side (static shapes
    for XLA) from the FRAMEWORK RNG stream, so mx.random.seed makes the
    pipeline reproducible; pixels flow through slice + resize."""
    import math

    import numpy as _np

    H, W = (data.shape[1], data.shape[2]) if _batched(data) \
        else (data.shape[0], data.shape[1])
    # one key -> all host-side draws this call (seeded, thread-safe)
    draws = _np.asarray(jax.random.uniform(_key(), (max_trial, 4)))
    for t in range(max_trial):
        u_area, u_ratio, u_x, u_y = draws[t]
        area = (scale[0] + u_area * (scale[1] - scale[0])) * H * W
        ar = math.exp(math.log(ratio[0]) + u_ratio *
                      (math.log(ratio[1]) - math.log(ratio[0])))
        cw = int(round(math.sqrt(area * ar)))
        ch = int(round(math.sqrt(area / ar)))
        if cw <= W and ch <= H:
            x0 = int(u_x * (W - cw + 1))
            y0 = int(u_y * (H - ch + 1))
            cropped = image_crop.fn(data, x0, y0, cw, ch)
            return image_resize.fn(cropped, size=size, interp=interp)
    # fallback: center crop of the short side
    s = min(H, W)
    cropped = image_crop.fn(data, (W - s) // 2, (H - s) // 2, s, s)
    return image_resize.fn(cropped, size=size, interp=interp)


@register("image_flip_left_right")
def image_flip_left_right(data):
    return jnp.flip(data, axis=2 if _batched(data) else 1)


@register("image_flip_top_bottom")
def image_flip_top_bottom(data):
    return jnp.flip(data, axis=1 if _batched(data) else 0)


def _maybe(data, fn, p=0.5):
    return jnp.where(jax.random.uniform(_key(), ()) < p, fn(data), data)


@register("image_random_flip_left_right", differentiable=False)
def image_random_flip_left_right(data, p=0.5):
    return _maybe(data, image_flip_left_right.fn, p)


@register("image_random_flip_top_bottom", differentiable=False)
def image_random_flip_top_bottom(data, p=0.5):
    return _maybe(data, image_flip_top_bottom.fn, p)


def _blend(a, b, alpha):
    return a * alpha + b * (1.0 - alpha)


# Plain numpy on purpose: a module-level jnp constant would trigger PJRT
# backend initialization during `import mxnet_tpu` (fail-slow when the TPU
# tunnel is unreachable).  jnp ops accept numpy operands and the constant is
# folded into the compiled program either way.
_GRAY = _onp.asarray([0.299, 0.587, 0.114], dtype=_onp.float32)


@register("image_random_brightness", differentiable=False)
def image_random_brightness(data, min_factor=1.0, max_factor=1.0):
    """x *= f, f ~ U(min_factor, max_factor) [image_random.cc
    RandomBrightness — factors are multiplicative, 1.0 = identity]."""
    f = jax.random.uniform(_key(), (), minval=min_factor,
                           maxval=max_factor)
    return data.astype(jnp.float32) * f


@register("image_random_contrast", differentiable=False)
def image_random_contrast(data, min_factor=1.0, max_factor=1.0):
    f = jax.random.uniform(_key(), (), minval=min_factor,
                           maxval=max_factor)
    x = data.astype(jnp.float32)
    lum = jnp.tensordot(x, _GRAY, axes=([-1], [0]))
    if _batched(data):  # per-image anchor, not batch-global
        gray = jnp.mean(lum, axis=(1, 2), keepdims=True)[..., None]
    else:
        gray = jnp.mean(lum)
    return _blend(x, gray, f)


@register("image_random_saturation", differentiable=False)
def image_random_saturation(data, min_factor=1.0, max_factor=1.0):
    f = jax.random.uniform(_key(), (), minval=min_factor,
                           maxval=max_factor)
    x = data.astype(jnp.float32)
    gray = jnp.tensordot(x, _GRAY, axes=([-1], [0]))[..., None]
    return _blend(x, gray, f)


@register("image_random_hue", differentiable=False)
def image_random_hue(data, min_factor=0.0, max_factor=0.0):
    """YIQ rotation (the reference's tyiq/ityiq path,
    image_random-inl.h RandomHue)."""
    import numpy as _np

    f = jax.random.uniform(_key(), (), minval=min_factor, maxval=max_factor)
    u = jnp.cos(f * _np.pi)
    w = jnp.sin(f * _np.pi)
    tyiq = jnp.asarray([[0.299, 0.587, 0.114],
                        [0.596, -0.274, -0.321],
                        [0.211, -0.523, 0.311]])
    ityiq = jnp.asarray([[1.0, 0.956, 0.621],
                         [1.0, -0.272, -0.647],
                         [1.0, -1.107, 1.705]])
    bt = jnp.stack([jnp.stack([jnp.float32(1), jnp.float32(0),
                               jnp.float32(0)]),
                    jnp.stack([jnp.float32(0), u, -w]),
                    jnp.stack([jnp.float32(0), w, u])])
    t = (ityiq @ bt @ tyiq).T
    return jnp.tensordot(data.astype(jnp.float32), t, axes=([-1], [0]))


@register("image_random_color_jitter", differentiable=False)
def image_random_color_jitter(data, brightness=0.0, contrast=0.0,
                              saturation=0.0, hue=0.0):
    x = data.astype(jnp.float32)
    if brightness > 0:
        x = image_random_brightness.fn(x, max(0.0, 1 - brightness),
                                       1 + brightness)
    if contrast > 0:
        x = image_random_contrast.fn(x, max(0.0, 1 - contrast),
                                     1 + contrast)
    if saturation > 0:
        x = image_random_saturation.fn(x, max(0.0, 1 - saturation),
                                       1 + saturation)
    if hue > 0:
        x = image_random_hue.fn(x, -hue, hue)
    return x


@register("image_adjust_lighting")
def image_adjust_lighting(data, alpha=None):
    """AlexNet PCA lighting with fixed alpha [image_random.cc
    AdjustLighting]."""
    eigval = jnp.asarray([55.46, 4.794, 1.148])
    eigvec = jnp.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.8140],
                          [-0.5836, -0.6948, 0.4203]])
    alpha = jnp.asarray(alpha, jnp.float32)
    rgb = (eigvec * alpha[None, :]) @ eigval
    return data.astype(jnp.float32) + rgb


@register("image_random_lighting", differentiable=False)
def image_random_lighting(data, alpha_std=0.05):
    alpha = jax.random.normal(_key(), (3,)) * alpha_std
    return image_adjust_lighting.fn(data, alpha=alpha)


for _ref, _ours in [
        ("_image_to_tensor", "image_to_tensor"),
        ("_image_normalize", "image_normalize"),
        ("_image_resize", "image_resize"),
        ("_image_crop", "image_crop"),
        ("_image_random_crop", "image_random_crop"),
        ("_image_random_resized_crop", "image_random_resized_crop"),
        ("_image_flip_left_right", "image_flip_left_right"),
        ("_image_flip_top_bottom", "image_flip_top_bottom"),
        ("_image_random_flip_left_right", "image_random_flip_left_right"),
        ("_image_random_flip_top_bottom", "image_random_flip_top_bottom"),
        ("_image_random_brightness", "image_random_brightness"),
        ("_image_random_contrast", "image_random_contrast"),
        ("_image_random_saturation", "image_random_saturation"),
        ("_image_random_hue", "image_random_hue"),
        ("_image_random_color_jitter", "image_random_color_jitter"),
        ("_image_adjust_lighting", "image_adjust_lighting"),
        ("_image_random_lighting", "image_random_lighting")]:
    alias(_ref, _ours)


# ---------------------------------------------------------------------------
# spatial sampling family — BilinearSampler (bilinear_sampler.cc:150),
# GridGenerator (grid_generator.cc:237), SpatialTransformer
# (spatial_transformer.cc:217).  One differentiable jnp bilinear-sample
# core serves all three (plus image.imrotate); XLA fuses the gathers.
# ---------------------------------------------------------------------------

def _bilinear_sample_core(data, grid):
    """data (N,C,H,W), grid (N,2,Ho,Wo) with grid[:,0]=x, grid[:,1]=y in
    [-1,1]; out-of-range samples read 0 (the reference's zero padding)."""
    N, C, H, W = data.shape
    x = (grid[:, 0] + 1.0) * (W - 1) / 2.0       # (N,Ho,Wo)
    y = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def take(yi, xi):
        inb = ((xi >= 0) & (xi <= W - 1) & (yi >= 0)
               & (yi <= H - 1))
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        flat = data.reshape(N, C, H * W)
        idx = (yc * W + xc).reshape(N, 1, -1)
        vals = jnp.take_along_axis(
            flat, jnp.broadcast_to(idx, (N, C, idx.shape[-1])), axis=2)
        vals = vals.reshape(N, C, *xi.shape[1:])
        return vals * inb[:, None].astype(data.dtype)

    out = (take(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
           + take(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
           + take(y0 + 1, x0) * (wy * (1 - wx))[:, None]
           + take(y0 + 1, x0 + 1) * (wy * wx)[:, None])
    return out.astype(data.dtype)


@register("BilinearSampler")
def bilinear_sampler(data, grid, cudnn_off=None):
    """Reference bilinear_sampler.cc:150: sample ``data`` at ``grid``
    (normalized [-1,1] x,y), zero outside."""
    return _bilinear_sample_core(data, grid)


@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Reference grid_generator.cc:237.

    affine: ``data`` (N,6) row-major 2x3 theta -> grid (N,2,Ho,Wo)
    warp: ``data`` (N,2,H,W) pixel offsets -> normalized grid
    """
    if transform_type == "affine":
        N = data.shape[0]
        Ho, Wo = int(target_shape[0]), int(target_shape[1])
        theta = data.reshape(N, 2, 3)
        ys, xs = jnp.meshgrid(
            jnp.linspace(-1.0, 1.0, Ho), jnp.linspace(-1.0, 1.0, Wo),
            indexing="ij")
        ones = jnp.ones_like(xs)
        base = jnp.stack([xs, ys, ones], axis=0).reshape(3, -1)  # (3,HoWo)
        out = jnp.einsum("nij,jk->nik", theta, base)             # (N,2,HoWo)
        return out.reshape(N, 2, Ho, Wo)
    if transform_type == "warp":
        N, _two, H, W = data.shape
        ys, xs = jnp.meshgrid(jnp.arange(H, dtype=data.dtype),
                              jnp.arange(W, dtype=data.dtype),
                              indexing="ij")
        x_new = (data[:, 0] + xs) * (2.0 / max(W - 1, 1)) - 1.0
        y_new = (data[:, 1] + ys) * (2.0 / max(H - 1, 1)) - 1.0
        return jnp.stack([x_new, y_new], axis=1)
    raise ValueError("GridGenerator transform_type %r" % (transform_type,))


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=None):
    """Reference spatial_transformer.cc:217 (STN): affine theta from the
    localization net + bilinear sampling in one op."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise ValueError("SpatialTransformer supports affine/bilinear")
    grid = grid_generator.fn(loc, "affine", target_shape)
    return _bilinear_sample_core(data, grid)
