"""Optimizer update ops — the reference's fused-updater op family.

Reference: src/operator/optimizer_op.cc (sgd/adam/nag/ftml/rmsprop/ftrl/
signsgd/signum/lamb registrations, lines 314-1010), contrib/multi_sum_sq.cc,
contrib/multi_lars.cc, contrib/all_finite.cc, operator/tensor/amp_cast.cc.
The reference exposes every optimizer's update rule as an NNVM op so graph
executors and the Python `Optimizer` classes share one kernel; users also
call them directly (``mx.nd.sgd_update(w, g, lr=.1, out=w)``).

TPU-native rendering: each op is a pure jnp expression over the flattened
arrays — XLA fuses the whole update into one elementwise kernel over HBM
(the reference needed hand-fused mshadow kernels for this; optimizer_op-inl.h
:226 MultiSGDKernel).  State "mutation" (FMutateInputs) is declared through
the registry's ``mutates`` metadata: the fn returns the new state values and
invoke() rebinds the caller's NDArray handles — semantics identical, data
flow functional.

The multi_-prefixed variants take interleaved flat lists exactly like the
reference (set_num_inputs lambda, optimizer_op.cc:322-330); on TPU they
matter less (XLA already fuses across ops) but the API surface is kept so
generated reference code ports verbatim.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

__all__ = []


def _rescale_clip(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


# ---------------------------------------------------------------------------
# single-tensor updaters (optimizer_op.cc:314-1010)
# ---------------------------------------------------------------------------
@register("sgd_update", differentiable=False)
def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    """weight -= lr * (clip(rescale*grad) + wd*weight)   [optimizer_op.cc:501]"""
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    return weight - lr * g


@register("sgd_mom_update", differentiable=False, mutates=(2,))
def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """mom = momentum*mom - lr*(clip(rescale*grad)+wd*w); w += mom
    [optimizer_op.cc:530]"""
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("mp_sgd_update", differentiable=False, mutates=(2,))
def mp_sgd_update(weight, grad, weight32, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Multi-precision SGD: update runs on the f32 master copy; the low-
    precision weight output is a cast of it [optimizer_op.cc:583]."""
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_w32 = weight32 - lr * (g + wd * weight32)
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", differentiable=False, mutates=(2, 3))
def mp_sgd_mom_update(weight, grad, mom, weight32, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("nag_mom_update", differentiable=False, mutates=(2,))
def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """Nesterov momentum [optimizer_op-inl.h:1029 NAGMomKernel]:
    g' = clip(rescale*g) + wd*w; mom = momentum*mom - lr*g';
    w += momentum*mom - lr*g'"""
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom - lr * g
    return weight + momentum * new_mom - lr * g, new_mom


@register("mp_nag_mom_update", differentiable=False, mutates=(2, 3))
def mp_nag_mom_update(weight, grad, mom, weight32, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad,
                      clip_gradient) + wd * weight32
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + momentum * new_mom - lr * g
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", differentiable=False, mutates=(2, 3))
def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    """[optimizer_op.cc:651] m=b1*m+(1-b1)g; v=b2*v+(1-b2)g^2;
    w -= lr*m/(sqrt(v)+eps).  wd folds into g (AdamUpdate kernel)."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("ftml_update", differentiable=False, mutates=(2, 3, 4))
def ftml_update(weight, grad, d, v, z, lr, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0):
    """FTML (Zheng & Kwok 2017) [optimizer_op.cc:618]."""
    g = _rescale_clip(grad, rescale_grad, clip_grad) + wd * weight
    new_v = beta2 * v + (1.0 - beta2) * g * g
    b1t = beta1 ** t
    b2t = beta2 ** t
    new_d = (1.0 - b1t) / lr * (jnp.sqrt(new_v / (1.0 - b2t)) + epsilon)
    sigma = new_d - beta1 * d
    new_z = beta1 * z + (1.0 - b1t) * g - sigma * weight
    return -new_z / new_d, new_d, new_v, new_z


@register("rmsprop_update", differentiable=False, mutates=(2,))
def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    """Hinton's RMSProp [optimizer_op.cc:755]."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1.0 - gamma1) * g * g
    # eps OUTSIDE the sqrt: RMSPropUpdateKernel divides by sqrt(n)+eps
    # (optimizer_op-inl.h:2025); only the centered variant keeps it inside
    new_w = weight - lr * g / (jnp.sqrt(new_n) + epsilon)
    if clip_weights is not None and clip_weights >= 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", differentiable=False, mutates=(2, 3, 4))
def rmspropalex_update(weight, grad, n, g, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """Graves' non-centered RMSProp [optimizer_op.cc:805]."""
    gr = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1.0 - gamma1) * gr * gr
    new_g = gamma1 * g + (1.0 - gamma1) * gr
    new_delta = gamma2 * delta - lr * gr / jnp.sqrt(
        new_n - new_g * new_g + epsilon)
    new_w = weight + new_delta
    if clip_weights is not None and clip_weights >= 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("ftrl_update", differentiable=False, mutates=(2, 3))
def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    """FTRL (McMahan et al. 2013) [optimizer_op.cc:847]."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_z = z + g - (jnp.sqrt(n + g * g) - jnp.sqrt(n)) * weight / lr
    new_n = n + g * g
    new_w = ((jnp.sign(new_z) * lamda1 - new_z)
             / ((beta + jnp.sqrt(new_n)) / lr + wd)
             * (jnp.abs(new_z) > lamda1))
    return new_w, new_z, new_n


@register("signsgd_update", differentiable=False)
def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    """w -= lr * sign(g)  [optimizer_op.cc:50]"""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    return weight * (1.0 - lr * wd) - lr * jnp.sign(g)


@register("signum_update", differentiable=False, mutates=(2,))
def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    """Signum [optimizer_op.cc:76]: m = b*m - (1-b)*g; w = (1-lr*wd_lh)*w +
    lr*sign(m) with m's sign convention from the kernel (mom carries -g)."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom - (1.0 - momentum) * g
    new_w = (1.0 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom


@register("lamb_update_phase1", differentiable=False, mutates=(2, 3))
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """[optimizer_op-inl.h:1573 LambUpdatePhaseOneKernel] returns the lamb
    direction g; caller computes r1/r2 norms and calls phase2."""
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    if bias_correction:
        mean_hat = new_mean / (1.0 - beta1 ** t)
        var_hat = new_var / (1.0 - beta2 ** t)
        out = mean_hat / (jnp.sqrt(var_hat) + epsilon) + wd * weight
    else:
        out = new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight
    return out, new_mean, new_var


@register("lamb_update_phase2", differentiable=False)
def lamb_update_phase2(weight, g, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0):
    """[optimizer_op-inl.h:1657 LambUpdatePhaseTwoKernel]"""
    new_r1 = r1.reshape(())
    if lower_bound >= 0:
        new_r1 = jnp.maximum(new_r1, lower_bound)
    if upper_bound >= 0:
        new_r1 = jnp.minimum(new_r1, upper_bound)
    r2v = r2.reshape(())
    ratio = jnp.where((new_r1 == 0.0) | (r2v == 0.0), 1.0, new_r1 / r2v)
    return weight - lr * ratio * g


@register("mp_lamb_update_phase1", differentiable=False, mutates=(2, 3))
def mp_lamb_update_phase1(weight, grad, mean, var, weight32, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1,
                          bias_correction=True, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0):
    """fp16 weights with f32 master copy [optimizer_op.cc mp_lamb_phase1]."""
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    if bias_correction:
        mean_hat = new_mean / (1.0 - beta1 ** t)
        var_hat = new_var / (1.0 - beta2 ** t)
        out = mean_hat / (jnp.sqrt(var_hat) + epsilon) + wd * weight32
    else:
        out = new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight32
    return out, new_mean, new_var


@register("mp_lamb_update_phase2", differentiable=False, mutates=(4,))
def mp_lamb_update_phase2(weight, g, r1, r2, weight32, lr, lower_bound=-1.0,
                          upper_bound=-1.0):
    new_r1 = r1.reshape(())
    if lower_bound >= 0:
        new_r1 = jnp.maximum(new_r1, lower_bound)
    if upper_bound >= 0:
        new_r1 = jnp.minimum(new_r1, upper_bound)
    r2v = r2.reshape(())
    ratio = jnp.where((new_r1 == 0.0) | (r2v == 0.0), 1.0, new_r1 / r2v)
    new_w32 = weight32 - lr * ratio * g
    return new_w32.astype(weight.dtype), new_w32


# ---------------------------------------------------------------------------
# multi-tensor updaters (optimizer_op.cc:314-470; interleaved input lists)
# ---------------------------------------------------------------------------
def _norm_list(v, n):
    if isinstance(v, (int, float)):
        return [v] * n
    return list(v)


def _multi_sgd(arrays, stride, lrs, wds, momentum, rescale_grad,
               clip_gradient, has_mom, has_mp):
    """MultiSGDKernel (optimizer_op-inl.h:226) over per-tensor groups."""
    n = len(arrays) // stride
    lrs = _norm_list(lrs, n)
    wds = _norm_list(wds, n)
    new_ws, new_moms, new_w32s = [], [], []
    for i in range(n):
        grp = arrays[i * stride:(i + 1) * stride]
        w, g = grp[0], grp[1]
        mom = grp[2] if has_mom else None
        w32 = grp[-1] if has_mp else None
        master = w32 if has_mp else w
        gr = _rescale_clip(g.astype(master.dtype), rescale_grad,
                           clip_gradient) + wds[i] * master
        if has_mom:
            new_mom = momentum * mom - lrs[i] * gr
            new_master = master + new_mom
            new_moms.append(new_mom)
        else:
            new_master = master - lrs[i] * gr
        new_ws.append(new_master.astype(w.dtype))
        if has_mp:
            new_w32s.append(new_master)
    return new_ws, new_moms, new_w32s


def _interleaved(stride, has_mom, has_mp, preloaded=False):
    """Build fn + num_outputs/mutates resolvers for one multi_sgd variant."""

    def fn(*arrays, lrs=None, wds=None, momentum=0.0, rescale_grad=1.0,
           clip_gradient=-1.0, num_weights=None):
        if preloaded:
            arrays, lr_arr, wd_arr = arrays[:-2], arrays[-2], arrays[-1]
            lrs = [lr_arr[i] for i in range(len(arrays) // stride)]
            wds = [wd_arr[i] for i in range(len(arrays) // stride)]
        new_ws, new_moms, new_w32s = _multi_sgd(
            list(arrays), stride, lrs, wds, momentum, rescale_grad,
            clip_gradient, has_mom, has_mp)
        n = len(new_ws)
        state = []
        for i in range(n):  # mutated inputs in position order per group
            if has_mom:
                state.append(new_moms[i])
            if has_mp:
                state.append(new_w32s[i])
        return tuple(new_ws) + tuple(state)

    def num_outputs(attrs):
        nw = attrs.get("num_weights")
        if nw is None:
            raise ValueError("multi_sgd family requires num_weights=")
        return int(nw)

    def mutates(attrs):
        nw = int(attrs.get("num_weights"))
        pos = []
        for i in range(nw):
            base = i * stride
            if has_mom:
                pos.append(base + 2)
            if has_mp:
                pos.append(base + stride - 1)
        return pos

    return fn, num_outputs, mutates


for _name, _stride, _mom, _mp, _pre in [
        ("multi_sgd_update", 2, False, False, False),
        ("multi_sgd_mom_update", 3, True, False, False),
        ("multi_mp_sgd_update", 3, False, True, False),
        ("multi_mp_sgd_mom_update", 4, True, True, False),
        ("preloaded_multi_sgd_update", 2, False, False, True),
        ("preloaded_multi_sgd_mom_update", 3, True, False, True),
        ("preloaded_multi_mp_sgd_update", 3, False, True, True),
        ("preloaded_multi_mp_sgd_mom_update", 4, True, True, True)]:
    _fn, _nout, _mut = _interleaved(_stride, _mom, _mp, _pre)
    _fn.__name__ = _name
    _fn.__doc__ = ("Fused multi-tensor %s (reference optimizer_op.cc:314-470"
                   "%s); interleaved inputs, stride %d."
                   % (_name, ", lrs/wds as device arrays" if _pre else "",
                      _stride))
    register(_name, num_outputs=_nout, differentiable=False,
             mutates=_mut)(_fn)


# ---------------------------------------------------------------------------
# LARS helpers (contrib/multi_sum_sq.cc, contrib/multi_lars.cc)
# ---------------------------------------------------------------------------
@register("multi_sum_sq", differentiable=False)
def multi_sum_sq(*arrays, num_arrays=None):
    """Per-array sum of squares, one (N,) f32 output
    [contrib/multi_sum_sq.cc:36]."""
    return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32)))
                      for a in arrays])


@register("multi_lars", differentiable=False)
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0):
    """LARS trust-ratio LR scaling [contrib/multi_lars.cc:35]:
    lr_i *= eta*||w||/(||g||*rescale + wd*||w|| + eps) when both norms > 0."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    ratio = eta * w_norm / (g_norm + wds * w_norm + eps)
    return lrs * jnp.where((w_norm > 0) & (g_norm > 0), ratio, 1.0)


# ---------------------------------------------------------------------------
# AMP helper ops (contrib/all_finite.cc, tensor/amp_cast.cc)
# ---------------------------------------------------------------------------
@register("all_finite", differentiable=False)
def all_finite(data, init_output=True):
    """Scalar 1/0: every element finite [contrib/all_finite.cc:99]."""
    return jnp.all(jnp.isfinite(data.astype(jnp.float32))).astype(
        jnp.float32).reshape(1)


@register("multi_all_finite", differentiable=False)
def multi_all_finite(*arrays, num_arrays=None, init_output=True):
    """AND of all_finite over N arrays [contrib/all_finite.cc:127]."""
    ok = jnp.array(True)
    for a in arrays:
        ok = ok & jnp.all(jnp.isfinite(a.astype(jnp.float32)))
    return ok.astype(jnp.float32).reshape(1)


@register("amp_cast")
def amp_cast(data, dtype="float16"):
    """Cast inserted by the AMP pass [tensor/amp_cast.cc:31]; identity-like
    and differentiable (grad casts back automatically via vjp)."""
    return data.astype(jnp.dtype(dtype))


def _amp_multicast_fn(*arrays, num_outputs=None, cast_narrow=False):
    """Cast N arrays to a common dtype [tensor/amp_cast.cc:55]: the widest
    input type (or narrowest with cast_narrow=True)."""
    dt = arrays[0].dtype
    for a in arrays[1:]:
        dt = (jnp.promote_types(dt, a.dtype) if not cast_narrow
              else (a.dtype if jnp.dtype(a.dtype).itemsize <
                    jnp.dtype(dt).itemsize else dt))
    return tuple(a.astype(dt) for a in arrays)


register("amp_multicast",
         num_outputs=lambda attrs: int(attrs.get("num_outputs", 1)))(
             _amp_multicast_fn)


def _reset_arrays_fn(*arrays, num_arrays=None):
    return tuple(jnp.zeros_like(a) for a in arrays)


_reset_arrays_fn.__doc__ = ("Zero every input in place "
                            "[contrib/reset_arrays.cc:35].")
register("reset_arrays", num_outputs=0, differentiable=False,
         mutates=lambda attrs: list(range(int(attrs["num_arrays"]))))(
             _reset_arrays_fn)
