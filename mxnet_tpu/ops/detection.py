"""Detection / bounding-box operator family.

Reference parity: /root/reference/src/operator/contrib/bounding_box.cc
(box_iou, box_nms, box_encode, box_decode, bipartite_matching),
roi_align.cc, and the multibox family (multibox_prior.cc,
multibox_detection.cc).

TPU-native notes: everything is expressed with static shapes so XLA can
compile it — NMS keeps the box count fixed and marks suppressed entries
with -1 scores (exactly the reference's in-place -1 convention,
bounding_box.cc BoxNMSForward), selection loops are lax.fori_loop /
top_k, and ROI Align is a gather + bilinear-weights einsum that lands on
the MXU instead of the reference's per-pixel CUDA kernel.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


def _corner(boxes, fmt):
    """-> (xmin, ymin, xmax, ymax) from 'corner' or 'center' format."""
    if fmt == "corner":
        return boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3]
    cx, cy, w, h = (boxes[..., 0], boxes[..., 1], boxes[..., 2],
                    boxes[..., 3])
    return cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2


@register("box_iou")
def box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU: lhs (..., N, 4) x rhs (..., M, 4) -> (..., N, M)
    (bounding_box.cc box_iou)."""
    lx1, ly1, lx2, ly2 = _corner(lhs[..., :, None, :], format)
    rx1, ry1, rx2, ry2 = _corner(rhs[..., None, :, :], format)
    ix = jnp.maximum(jnp.minimum(lx2, rx2) - jnp.maximum(lx1, rx1), 0)
    iy = jnp.maximum(jnp.minimum(ly2, ry2) - jnp.maximum(ly1, ry1), 0)
    inter = ix * iy
    area_l = jnp.maximum(lx2 - lx1, 0) * jnp.maximum(ly2 - ly1, 0)
    area_r = jnp.maximum(rx2 - rx1, 0) * jnp.maximum(ry2 - ry1, 0)
    union = area_l + area_r - inter
    # guard the denominator BEFORE dividing: a where() around an unguarded
    # division still produces NaN cotangents for union==0 rows (zero-padded
    # box lists) through the vjp
    safe_union = jnp.where(union > 0, union, 1.0)
    return jnp.where(union > 0, inter / safe_union, 0.0)


@register("box_nms", differentiable=False)
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """Non-maximum suppression (bounding_box.cc BoxNMSForward).

    data: (..., N, K) rows [id?, score, x1, y1, x2, y2, ...]; suppressed
    rows get score -1 (shape-stable, reference convention)."""
    batch_shape = data.shape[:-2]
    N, K = data.shape[-2], data.shape[-1]
    flat = data.reshape((-1, N, K))

    def one(batch):
        scores = batch[:, score_index]
        boxes = batch[:, coord_start:coord_start + 4]
        valid = scores > valid_thresh
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        if topk > 0:
            keep_rank = jnp.arange(N) < topk
        else:
            keep_rank = jnp.ones((N,), bool)
        iou = box_iou.fn(boxes, boxes, format=in_format)
        same_class = jnp.ones((N, N), bool)
        if not force_suppress and id_index >= 0:
            ids = batch[:, id_index]
            same_class = ids[:, None] == ids[None, :]

        def body(i, keep):
            # suppress everything the i-th ranked (kept, valid) box
            # overlaps; fori_loop keeps the program static-shape
            bi = order[i]
            active = keep[bi] & valid[bi] & keep_rank[i]
            overl = (iou[bi] > overlap_thresh) & same_class[bi]
            overl = overl.at[bi].set(False)
            return jnp.where(active, keep & ~overl, keep)

        keep = lax.fori_loop(0, N, body, valid & keep_rank[
            jnp.argsort(order)])
        new_scores = jnp.where(keep, scores, -1.0)
        batch = batch.at[:, score_index].set(new_scores)
        if in_format != out_format:
            if out_format == "corner":
                x1, y1, x2, y2 = _corner(boxes, in_format)
                conv = jnp.stack([x1, y1, x2, y2], axis=-1)
            else:  # corner -> center
                w = boxes[:, 2] - boxes[:, 0]
                h = boxes[:, 3] - boxes[:, 1]
                conv = jnp.stack([boxes[:, 0] + w / 2, boxes[:, 1] + h / 2,
                                  w, h], axis=-1)
            batch = batch.at[:, coord_start:coord_start + 4].set(conv)
        return batch

    out = jax.vmap(one)(flat)
    return out.reshape(batch_shape + (N, K))


@register("box_encode")
def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """Encode matched gt boxes as anchor offsets (bounding_box.cc
    box_encode; SSD target convention)."""
    ax1, ay1, ax2, ay2 = (anchors[..., 0], anchors[..., 1], anchors[..., 2],
                          anchors[..., 3])
    aw, ah = ax2 - ax1, ay2 - ay1
    acx, acy = ax1 + aw / 2, ay1 + ah / 2
    g = jnp.take_along_axis(refs, matches[..., None].astype(jnp.int32)
                            .clip(0), axis=-2)
    gx1, gy1, gx2, gy2 = g[..., 0], g[..., 1], g[..., 2], g[..., 3]
    gw, gh = gx2 - gx1, gy2 - gy1
    gcx, gcy = gx1 + gw / 2, gy1 + gh / 2
    means = jnp.asarray(means, anchors.dtype)
    stds = jnp.asarray(stds, anchors.dtype)
    t = jnp.stack([(gcx - acx) / jnp.maximum(aw, 1e-12),
                   (gcy - acy) / jnp.maximum(ah, 1e-12),
                   jnp.log(jnp.maximum(gw, 1e-12) / jnp.maximum(aw, 1e-12)),
                   jnp.log(jnp.maximum(gh, 1e-12) / jnp.maximum(ah, 1e-12))],
                  axis=-1)
    t = (t - means) / stds
    mask = jnp.broadcast_to((samples > 0.5)[..., None], t.shape)
    return jnp.where(mask, t, 0.0), mask.astype(anchors.dtype)


@register("box_decode")
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):
    """Invert box_encode (bounding_box.cc box_decode)."""
    ax1, ay1, ax2, ay2 = _corner(anchors, format)
    aw, ah = ax2 - ax1, ay2 - ay1
    acx, acy = ax1 + aw / 2, ay1 + ah / 2
    stds = jnp.asarray([std0, std1, std2, std3], data.dtype)
    d = data * stds
    pcx = d[..., 0] * aw + acx
    pcy = d[..., 1] * ah + acy
    dw, dh = d[..., 2], d[..., 3]
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    pw, ph = jnp.exp(dw) * aw, jnp.exp(dh) * ah
    return jnp.stack([pcx - pw / 2, pcy - ph / 2,
                      pcx + pw / 2, pcy + ph / 2], axis=-1)


@register("bipartite_matching", num_outputs=2, differentiable=False)
def bipartite_matching(data, threshold=0.5, is_ascend=False, topk=-1):
    """Greedy bipartite matching on a score matrix (bounding_box.cc):
    returns (row_match, col_match) index vectors, -1 for unmatched."""
    N, M = data.shape[-2], data.shape[-1]
    batch_shape = data.shape[:-2]
    flat = data.reshape((-1, N, M))
    k = N if topk <= 0 else min(topk, N)

    def one(mat):
        score = mat if not is_ascend else -mat
        row_match = jnp.full((N,), -1, jnp.int32)
        col_match = jnp.full((M,), -1, jnp.int32)

        def body(_, carry):
            rm, cm, s = carry
            idx = jnp.argmax(s)
            i, j = idx // M, idx % M
            ok = s[i, j] >= (threshold if not is_ascend else -threshold)
            rm = jnp.where(ok, rm.at[i].set(j.astype(jnp.int32)), rm)
            cm = jnp.where(ok, cm.at[j].set(i.astype(jnp.int32)), cm)
            s = jnp.where(ok, s.at[i, :].set(-jnp.inf).at[:, j]
                          .set(-jnp.inf), s)
            return rm, cm, s

        rm, cm, _ = lax.fori_loop(0, k, body,
                                  (row_match, col_match, score))
        return rm, cm

    rm, cm = jax.vmap(one)(flat)
    return (rm.reshape(batch_shape + (N,)),
            cm.reshape(batch_shape + (M,)))


@register("roi_align")
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, aligned=True):
    """ROI Align (contrib/roi_align.cc, Mask R-CNN): bilinear sampling at
    sample_ratio^2 points per output bin, averaged.

    data: (B, C, H, W); rois: (R, 5) rows [batch_idx, x1, y1, x2, y2] in
    image coords.  Differentiable (gather + weights)."""
    B, C, H, W = data.shape
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))
    if sample_ratio <= 0:
        # the reference's adaptive grid (ceil(roi_size/pooled_size) samples
        # per bin) is data-dependent — impossible in one static-shape XLA
        # program.  Fail loudly instead of silently diverging.
        raise ValueError(
            "roi_align on TPU needs an explicit sample_ratio >= 1 (the "
            "reference's adaptive sample_ratio<=0 grid is data-dependent); "
            "sample_ratio=2 matches the common detectron recipe")
    sr = int(sample_ratio)
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bin_w, bin_h = rw / pw, rh / ph
        # sample grid: (ph, sr) x (pw, sr)
        iy = (jnp.arange(ph)[:, None] + (jnp.arange(sr)[None, :] + 0.5)
              / sr).reshape(-1)                       # (ph*sr,)
        ix = (jnp.arange(pw)[:, None] + (jnp.arange(sr)[None, :] + 0.5)
              / sr).reshape(-1)                       # (pw*sr,)
        ys = y1 + iy * bin_h
        xs = x1 + ix * bin_w

        def bilinear(img, ys, xs):
            # img: (C, H, W); sample at outer grid ys x xs.
            # out-of-bounds handling mirrors roi_align.cc exactly: reject
            # samples beyond [-1, H]/[−1, W], CLAMP coords to 0 BEFORE
            # deriving the weights (else boundary bins blend a phantom
            # row/col), then bilinear-blend the 4 neighbors
            oob_y = (ys < -1.0) | (ys > H)
            oob_x = (xs < -1.0) | (xs > W)
            ys = jnp.clip(ys, 0.0, None)
            xs = jnp.clip(xs, 0.0, None)
            y0 = jnp.floor(ys)
            x0 = jnp.floor(xs)
            wy1 = ys - y0
            wx1 = xs - x0
            y0i = jnp.clip(y0.astype(jnp.int32), 0, H - 1)
            y1i = jnp.clip(y0i + 1, 0, H - 1)
            x0i = jnp.clip(x0.astype(jnp.int32), 0, W - 1)
            x1i = jnp.clip(x0i + 1, 0, W - 1)
            g = img[:, y0i][:, :, x0i] * ((1 - wy1)[:, None] *
                                          (1 - wx1)[None, :]) + \
                img[:, y1i][:, :, x0i] * (wy1[:, None] *
                                          (1 - wx1)[None, :]) + \
                img[:, y0i][:, :, x1i] * ((1 - wy1)[:, None] *
                                          wx1[None, :]) + \
                img[:, y1i][:, :, x1i] * (wy1[:, None] * wx1[None, :])
            mask = (~oob_y)[:, None] & (~oob_x)[None, :]
            return g * mask[None]

        img = data[bidx]                              # (C, H, W)
        samples = bilinear(img, ys, xs)               # (C, ph*sr, pw*sr)
        samples = samples.reshape(C, ph, sr, pw, sr)
        pooled = samples.mean(axis=(2, 4))            # (C, ph, pw)
        if position_sensitive:
            # PS-ROIAlign (R-FCN): channel group c*ph*pw + i*pw + j feeds
            # output bin (i, j) of class-channel c
            C_out = C // (ph * pw)
            cidx = (jnp.arange(C_out)[:, None, None] * (ph * pw)
                    + jnp.arange(ph)[None, :, None] * pw
                    + jnp.arange(pw)[None, None, :])
            pooled = pooled[cidx,
                            jnp.arange(ph)[None, :, None],
                            jnp.arange(pw)[None, None, :]]
        return pooled

    if position_sensitive and C % (ph * pw):
        raise ValueError("position_sensitive=True needs channels divisible "
                         "by ph*pw (got C=%d)" % C)
    return jax.vmap(one_roi)(rois)


@register("multibox_prior", differentiable=False)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """SSD anchor generation (multibox_prior.cc): (1, H*W*A, 4) corners."""
    H, W = data.shape[-2], data.shape[-1]
    sizes = _np.asarray(sizes, _np.float32)
    ratios = _np.asarray(ratios, _np.float32)
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H) + offsets[0]) * step_y
    cx = (jnp.arange(W) + offsets[1]) * step_x
    # anchors per pixel in the REFERENCE order (multibox_prior.cc: all
    # sizes with ratios[0] first, then ratios[1:] with sizes[0]); widths
    # carry the in_height/in_width aspect correction so boxes stay square
    # in image space on non-square feature maps
    aspect = float(H) / float(W)
    ws, hs = [], []
    for s in sizes:
        ws.append(s * aspect * _np.sqrt(ratios[0]))
        hs.append(s / _np.sqrt(ratios[0]))
    for r in ratios[1:]:
        ws.append(sizes[0] * aspect * _np.sqrt(r))
        hs.append(sizes[0] / _np.sqrt(r))
    ws = jnp.asarray(_np.asarray(ws) / 2)
    hs = jnp.asarray(_np.asarray(hs) / 2)
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
    cyg = cyg[..., None]
    cxg = cxg[..., None]
    boxes = jnp.stack([cxg - ws, cyg - hs, cxg + ws, cyg + hs], axis=-1)
    boxes = boxes.reshape(1, -1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


@register("multibox_detection", differentiable=False)
def multibox_detection(cls_prob, loc_pred, anchors, clip=True,
                       threshold=0.01, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD detection head (multibox_detection.cc): decode + per-class
    scores + NMS.  cls_prob (B, CLS, N) with class 0 = background,
    loc_pred (B, N*4), anchors (1, N, 4 center-format) ->
    (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], invalid rows -1."""
    B, CLS, N = cls_prob.shape
    loc = loc_pred.reshape(B, N, 4)
    # decode against center-format anchors
    acx, acy, aw, ah = (anchors[..., 0], anchors[..., 1], anchors[..., 2],
                        anchors[..., 3])
    v = variances
    pcx = loc[..., 0] * v[0] * aw + acx
    pcy = loc[..., 1] * v[1] * ah + acy
    pw = jnp.exp(loc[..., 2] * v[2]) * aw
    ph = jnp.exp(loc[..., 3] * v[3]) * ah
    boxes = jnp.stack([pcx - pw / 2, pcy - ph / 2,
                       pcx + pw / 2, pcy + ph / 2], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    scores = cls_prob[:, 1:, :]                      # drop background
    best = jnp.argmax(scores, axis=1).astype(jnp.float32)  # (B, N)
    best_score = jnp.max(scores, axis=1)
    keep = best_score > threshold
    cls_id = jnp.where(keep, best, -1.0)
    score = jnp.where(keep, best_score, -1.0)
    det = jnp.concatenate([cls_id[..., None], score[..., None], boxes],
                          axis=-1)
    return box_nms.fn(det, overlap_thresh=nms_threshold, valid_thresh=0.0,
                      topk=nms_topk, coord_start=2, score_index=1,
                      id_index=0, force_suppress=force_suppress)


@register("multibox_target", differentiable=False, num_outputs=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training-target assignment (multibox_target.cc:72
    MultiBoxTargetForward): greedy bipartite gt<->anchor matching, then
    IoU-threshold matching, optional hard-negative mining ranked by
    background confidence, and variance-scaled offset encoding.

    Host numpy kernel ON PURPOSE: the matching loop is sequential
    argmax-with-removal over (anchors x gts) — the reference runs it on
    CPU even in GPU builds (multibox_target.cu just copies); it prepares
    targets, it is not in the compiled training step.

    anchor (1, N, 4) corner format, label (B, M, 5+) rows
    [cls, x1, y1, x2, y2, ...] padded with -1, cls_pred (B, CLS, N) ->
    (loc_target (B, N*4), loc_mask (B, N*4), cls_target (B, N))."""
    import numpy as onp

    anc = onp.asarray(anchor).reshape(-1, 4)
    lab = onp.asarray(label)
    cp = onp.asarray(cls_pred)
    B, M, W = lab.shape
    N = anc.shape[0]
    loc_t = onp.zeros((B, N, 4), onp.float32)
    loc_m = onp.zeros((B, N, 4), onp.float32)
    cls_t = onp.full((B, N), float(ignore_label), onp.float32)

    aw = onp.maximum(anc[:, 2] - anc[:, 0], 1e-12)
    ah = onp.maximum(anc[:, 3] - anc[:, 1], 1e-12)
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2

    def iou(a, b):
        ix = onp.maximum(0, onp.minimum(a[:, None, 2], b[None, :, 2])
                         - onp.maximum(a[:, None, 0], b[None, :, 0]))
        iy = onp.maximum(0, onp.minimum(a[:, None, 3], b[None, :, 3])
                         - onp.maximum(a[:, None, 1], b[None, :, 1]))
        inter = ix * iy
        ua = ((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]))[:, None] \
            + ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))[None, :] - inter
        return inter / onp.maximum(ua, 1e-12)

    for nb in range(B):
        valid = 0
        while valid < M and lab[nb, valid, 0] != -1.0:
            valid += 1
        if valid == 0:
            continue
        gt = lab[nb, :valid]
        overlaps = iou(anc, gt[:, 1:5])              # (N, valid)
        anchor_flags = onp.full(N, -1, onp.int8)     # -1 ignore, 1 pos, 0 neg
        matches = onp.full(N, -1, onp.int64)
        match_iou = onp.full(N, -1.0, onp.float32)
        # 1. greedy bipartite: every gt gets its best still-free anchor
        gt_free = onp.ones(valid, bool)
        work = overlaps.copy()
        while gt_free.any():
            j, k = onp.unravel_index(onp.argmax(
                onp.where(gt_free[None, :], work, -1.0)), work.shape)
            if work[j, k] <= 1e-6:
                break
            matches[j] = k
            match_iou[j] = work[j, k]
            anchor_flags[j] = 1
            gt_free[k] = False
            work[j, :] = -1.0
        # 2. threshold matching for the rest
        if overlap_threshold > 0:
            free = anchor_flags != 1
            best_gt = onp.argmax(overlaps, axis=1)
            best_iou = overlaps[onp.arange(N), best_gt]
            take = free & (best_iou > overlap_threshold)
            matches[take] = best_gt[take]
            match_iou[free] = best_iou[free]
            anchor_flags[take] = 1
        num_pos = int((anchor_flags == 1).sum())
        # 3. negatives
        if negative_mining_ratio > 0:
            num_neg = min(int(num_pos * negative_mining_ratio),
                          N - num_pos)
            num_neg = max(num_neg, int(minimum_negative_samples))
            cand = onp.where((anchor_flags != 1)
                             & (match_iou < negative_mining_thresh))[0]
            if num_neg > 0 and len(cand):
                logits = cp[nb]                       # (CLS, N)
                mx_ = logits[:, cand].max(axis=0)
                prob_bg = onp.exp(logits[0, cand] - mx_) / onp.exp(
                    logits[:, cand] - mx_).sum(axis=0)
                # hardest negatives = lowest background confidence
                # (reference sorts SortElemDescend(-prob) — prob ascending)
                order = onp.argsort(prob_bg, kind="stable")
                anchor_flags[cand[order[:num_neg]]] = 0
        else:
            anchor_flags[anchor_flags != 1] = 0
        # 4. targets
        pos = onp.where(anchor_flags == 1)[0]
        neg = onp.where(anchor_flags == 0)[0]
        cls_t[nb, neg] = 0.0
        if len(pos):
            g = gt[matches[pos]]
            cls_t[nb, pos] = g[:, 0] + 1.0
            gw = onp.maximum(g[:, 3] - g[:, 1], 1e-12)
            gh = onp.maximum(g[:, 4] - g[:, 2], 1e-12)
            gcx = (g[:, 1] + g[:, 3]) / 2
            gcy = (g[:, 2] + g[:, 4]) / 2
            v = variances
            loc_t[nb, pos, 0] = ((gcx - acx[pos]) / aw[pos]) / v[0]
            loc_t[nb, pos, 1] = ((gcy - acy[pos]) / ah[pos]) / v[1]
            loc_t[nb, pos, 2] = onp.log(gw / aw[pos]) / v[2]
            loc_t[nb, pos, 3] = onp.log(gh / ah[pos]) / v[3]
            loc_m[nb, pos] = 1.0
    return (jnp.asarray(loc_t.reshape(B, -1)),
            jnp.asarray(loc_m.reshape(B, -1)), jnp.asarray(cls_t))


@register("rroi_align", differentiable=False)
def rroi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
               sampling_ratio=-1):
    """Rotated ROI align (contrib/rroi_align.cc — CPU-only in the
    reference too): rois rows [batch_idx, cx, cy, w, h, angle_deg];
    bilinear sampling on a rotated grid, average-pooled."""
    import numpy as onp

    x = onp.asarray(data)
    r = onp.asarray(rois)
    B, C, H, W = x.shape
    ph, pw = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else tuple(pooled_size)
    R = r.shape[0]
    out = onp.zeros((R, C, ph, pw), onp.float32)
    for i in range(R):
        b = int(r[i, 0])
        cx, cy, w, h = (r[i, 1] * spatial_scale, r[i, 2] * spatial_scale,
                        max(r[i, 3] * spatial_scale, 1.0),
                        max(r[i, 4] * spatial_scale, 1.0))
        theta = onp.deg2rad(r[i, 5])
        cosT, sinT = onp.cos(theta), onp.sin(theta)
        sr = sampling_ratio if sampling_ratio > 0 else 2
        for py in range(ph):
            for px in range(pw):
                acc = onp.zeros(C, onp.float32)
                for iy in range(sr):
                    for ix in range(sr):
                        # unit coords in the roi frame, centered
                        ux = (px + (ix + 0.5) / sr) / pw - 0.5
                        uy = (py + (iy + 0.5) / sr) / ph - 0.5
                        sx = cx + ux * w * cosT - uy * h * sinT
                        sy = cy + ux * w * sinT + uy * h * cosT
                        if sx < -1.0 or sx > W or sy < -1.0 or sy > H:
                            continue
                        # clamp BEFORE taking the fractions (reference
                        # rroi_align.cc:89-114 sets x=0 when x<=0, so a
                        # border sample reads the pure edge pixel)
                        sxc = min(max(sx, 0.0), W - 1)
                        syc = min(max(sy, 0.0), H - 1)
                        x0c = int(onp.floor(sxc))
                        y0c = int(onp.floor(syc))
                        x1c = min(x0c + 1, W - 1)
                        y1c = min(y0c + 1, H - 1)
                        fx = sxc - x0c; fy = syc - y0c
                        val = ((1 - fx) * (1 - fy) * x[b, :, y0c, x0c]
                               + fx * (1 - fy) * x[b, :, y0c, x1c]
                               + (1 - fx) * fy * x[b, :, y1c, x0c]
                               + fx * fy * x[b, :, y1c, x1c])
                        acc += val
                out[i, :, py, px] = acc / (sr * sr)
    return jnp.asarray(out)
