"""Contrib op long tail: AdamW, multi-LAMB/LANS, count_sketch, fft,
index ops, SyncBatchNorm.

Reference: src/operator/contrib/adamw.cc (_adamw_update:79,
_mp_adamw_update:34, _multi_adamw_update:143), multi_lamb.cc
(_multi_lamb_update:174), multi_lans.cc (_multi_lans_update:190),
count_sketch.cc, fft.cc, index_copy.cc, index_add.cc,
sync_batch_norm.cc (_contrib_SyncBatchNorm:105).

Notable semantics kept from the reference:
- adamw takes ``rescale_grad`` as a TENSOR input; when it is non-finite the
  entire update is skipped (adamw.cc:56 — this is the AMP grad-scaler
  contract: overflowed steps become no-ops).
- multi_lamb/multi_lans use interleaved (weight, grad, mean, var[, w32])
  groups with per-tensor learning_rates/wds and per-tensor step_count for
  bias correction.
- fft returns the reference's interleaved real/imag layout (..., 2n), not
  complex dtype (fft-inl.h output convention).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import alias, register


# ---------------------------------------------------------------------------
# AdamW (decoupled weight decay) — adamw.cc
# ---------------------------------------------------------------------------
def _adamw_math(w32, grad, mean, var, rescale, lr, eta, beta1, beta2,
                epsilon, wd, clip_gradient):
    scale = rescale.reshape(())
    ok = jnp.isfinite(scale)
    g = grad.astype(jnp.float32) * jnp.where(ok, scale, 0.0)
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    step = lr * new_mean / (jnp.sqrt(new_var) + epsilon) + wd * w32
    new_w = w32 - eta * step
    # non-finite scale: whole update is a no-op (adamw.cc:56)
    return (jnp.where(ok, new_w, w32), jnp.where(ok, new_mean, mean),
            jnp.where(ok, new_var, var))


@register("adamw_update", differentiable=False, mutates=(2, 3))
def adamw_update(weight, grad, mean, var, rescale_grad, lr, eta=1.0,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                 clip_gradient=-1.0):
    """W -= eta*(lr*m/(sqrt(v)+eps) + wd*W)  [adamw.cc:79 _adamw_update]."""
    new_w, new_mean, new_var = _adamw_math(
        weight, grad, mean, var, rescale_grad, lr, eta, beta1, beta2,
        epsilon, wd, clip_gradient)
    return new_w, new_mean, new_var


@register("mp_adamw_update", differentiable=False, mutates=(2, 3, 5))
def mp_adamw_update(weight, grad, mean, var, rescale_grad, weight32, lr,
                    eta=1.0, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                    clip_gradient=-1.0):
    """fp16 weights + f32 master [adamw.cc:34 _mp_adamw_update]."""
    new_w32, new_mean, new_var = _adamw_math(
        weight32, grad, mean, var, rescale_grad, lr, eta, beta1, beta2,
        epsilon, wd, clip_gradient)
    return new_w32.astype(weight.dtype), new_mean, new_var, new_w32


alias("_adamw_update", "adamw_update")
alias("_mp_adamw_update", "mp_adamw_update")


# ---------------------------------------------------------------------------
# multi-tensor LAMB / LANS — multi_lamb.cc / multi_lans.cc
# ---------------------------------------------------------------------------
def _norm(x):
    return jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32))))


def _trust(lr, w_norm, d_norm, lower_bound, upper_bound):
    r1 = w_norm
    if lower_bound is not None and lower_bound >= 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound >= 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where((r1 > 0) & (d_norm > 0), r1 / d_norm, 1.0)
    return lr * ratio


def _multi_lamb_fn(*arrays, learning_rates=None, wds=None, step_count=None,
                   beta1=0.9, beta2=0.999, epsilon=1e-6, rescale_grad=1.0,
                   lower_bound=-1.0, upper_bound=-1.0, clip_gradient=-1.0,
                   bias_correction=True, num_tensors=None):
    """Fused multi-tensor LAMB [multi_lamb.cc:174]: interleaved
    (weight, grad, mean, var) groups, per-tensor lr/wd/step."""
    n = len(arrays) // 4
    outs, states = [], []
    for i in range(n):
        w, g, m, v = arrays[i * 4:(i + 1) * 4]
        wf = w.astype(jnp.float32)
        gf = g.astype(jnp.float32) * rescale_grad
        if clip_gradient is not None and clip_gradient >= 0:
            gf = jnp.clip(gf, -clip_gradient, clip_gradient)
        nm = beta1 * m + (1.0 - beta1) * gf
        nv = beta2 * v + (1.0 - beta2) * gf * gf
        t = step_count[i] if step_count else 1
        if bias_correction:
            mh = nm / (1.0 - beta1 ** t)
            vh = nv / (1.0 - beta2 ** t)
        else:
            mh, vh = nm, nv
        d = mh / (jnp.sqrt(vh) + epsilon) + wds[i] * wf
        lr = _trust(learning_rates[i], _norm(wf), _norm(d), lower_bound,
                    upper_bound)
        outs.append((wf - lr * d).astype(w.dtype))
        states.extend([nm, nv])
    return tuple(outs) + tuple(states)


def _multi_lans_fn(*arrays, learning_rates=None, wds=None, step_count=None,
                   beta1=0.9, beta2=0.999, epsilon=1e-6, rescale_grad=1.0,
                   lower_bound=-1.0, upper_bound=-1.0, clip_gradient=-1.0,
                   bias_correction=True, num_tensors=None):
    """Fused multi-tensor LANS [multi_lans.cc:190; Zheng et al. 2020]:
    LAMB plus a normalized-gradient term — each tensor's grad is first
    scaled by 1/||g||, and the update blends the adam direction (weight
    beta1) with the raw normalized gradient direction (weight 1-beta1),
    each with its own trust ratio."""
    n = len(arrays) // 4
    outs, states = [], []
    for i in range(n):
        w, g, m, v = arrays[i * 4:(i + 1) * 4]
        wf = w.astype(jnp.float32)
        gf = g.astype(jnp.float32) * rescale_grad
        gf = gf / jnp.maximum(_norm(gf), 1e-12)
        if clip_gradient is not None and clip_gradient >= 0:
            gf = jnp.clip(gf, -clip_gradient, clip_gradient)
        nm = beta1 * m + (1.0 - beta1) * gf
        nv = beta2 * v + (1.0 - beta2) * gf * gf
        t = step_count[i] if step_count else 1
        if bias_correction:
            mh = nm / (1.0 - beta1 ** t)
            vh = nv / (1.0 - beta2 ** t)
        else:
            mh, vh = nm, nv
        w_norm = _norm(wf)
        denom = jnp.sqrt(vh) + epsilon
        d_adam = mh / denom + wds[i] * wf
        d_grad = gf / denom + wds[i] * wf
        lr_adam = _trust(learning_rates[i], w_norm, _norm(d_adam),
                         lower_bound, upper_bound)
        lr_grad = _trust(learning_rates[i], w_norm, _norm(d_grad),
                         lower_bound, upper_bound)
        new_w = wf - beta1 * lr_adam * d_adam \
            - (1.0 - beta1) * lr_grad * d_grad
        outs.append(new_w.astype(w.dtype))
        states.extend([nm, nv])
    return tuple(outs) + tuple(states)


def _multi4_meta(stride=4):
    def num_outputs(attrs):
        return int(attrs["num_tensors"])

    def mutates(attrs):
        n = int(attrs["num_tensors"])
        pos = []
        for i in range(n):
            pos.extend([i * stride + 2, i * stride + 3])
        return pos

    return num_outputs, mutates


_no, _mut = _multi4_meta()
_multi_lamb_fn.__name__ = "multi_lamb_update"
_multi_lans_fn.__name__ = "multi_lans_update"
register("multi_lamb_update", num_outputs=_no, differentiable=False,
         mutates=_mut)(_multi_lamb_fn)
register("multi_lans_update", num_outputs=_no, differentiable=False,
         mutates=_mut)(_multi_lans_fn)
alias("_multi_lamb_update", "multi_lamb_update")
alias("_multi_lans_update", "multi_lans_update")


# ---------------------------------------------------------------------------
# count_sketch / fft — contrib/count_sketch.cc, fft.cc
# ---------------------------------------------------------------------------
@register("count_sketch")
def count_sketch(data, h, s, out_dim=None, processing_batch_size=32):
    """Count sketch projection [count_sketch.cc:36]: out[b, h[i]] +=
    s[i] * data[b, i] — the FFT-friendly low-dim sketch from Compact
    Bilinear Pooling."""
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    B = data.shape[0]
    out = jnp.zeros((B, int(out_dim)), data.dtype)
    return out.at[:, hh].add(data * ss[None, :])


@register("fft")
def fft(data, compute_size=128):
    """FFT over the last axis, interleaved real/imag output (..., 2n)
    [fft-inl.h output layout]."""
    f = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    return jnp.stack([f.real, f.imag], axis=-1).reshape(
        data.shape[:-1] + (2 * data.shape[-1],)).astype(jnp.float32)


@register("ifft")
def ifft(data, compute_size=128):
    """Inverse of the interleaved-layout fft [fft-inl.h]; input (..., 2n)
    -> real (..., n)."""
    n = data.shape[-1] // 2
    c = data.reshape(data.shape[:-1] + (n, 2))
    z = c[..., 0] + 1j * c[..., 1]
    return jnp.fft.ifft(z, axis=-1).real.astype(jnp.float32) * n


# ---------------------------------------------------------------------------
# index ops — contrib/index_copy.cc, index_add.cc
# ---------------------------------------------------------------------------
@register("index_copy")
def index_copy(old_tensor, index_vector, new_tensor):
    """old[index[i]] = new[i]  [index_copy.cc:30]."""
    return old_tensor.at[index_vector.astype(jnp.int32)].set(new_tensor)


@register("index_add")
def index_add(data, indices, updates):
    """data[indices[i]] += updates[i] (duplicate indices accumulate)
    [index_add.cc:30]."""
    return data.at[indices.astype(jnp.int32)].add(updates)


# ---------------------------------------------------------------------------
# SyncBatchNorm — contrib/sync_batch_norm.cc
# ---------------------------------------------------------------------------
@register("sync_batch_norm", num_outputs=3)
def sync_batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-3,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    axis_name=None, ndev=1, key=None):
    """Cross-device BatchNorm [_contrib_SyncBatchNorm, sync_batch_norm.cc:
    105].  The reference synchronized per-GPU partial sums through a
    host-side shared buffer + barrier (sync_batch_norm-inl.h:87); on TPU
    the same reduction is ``lax.pmean`` over the mesh axis named
    ``axis_name`` when tracing under shard_map/pjit — XLA lowers it to an
    ICI all-reduce.  Outside an SPMD trace (axis_name=None) the global
    batch already lives in one program, so plain batch statistics ARE the
    synchronized statistics."""
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    if use_global_stats:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    else:
        axes = (0,) + tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes)
        sq = jnp.mean(jnp.square(x), axis=axes)
        if axis_name:
            mean = jax.lax.pmean(mean, axis_name)
            sq = jax.lax.pmean(sq, axis_name)
        var = sq - jnp.square(mean)
        new_mm = momentum * moving_mean + (1.0 - momentum) * mean
        new_mv = momentum * moving_var + (1.0 - momentum) * var
    shape = (1, -1) + (1,) * (x.ndim - 2)
    out = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
    return out * gamma.reshape(shape) + beta.reshape(shape), new_mm, new_mv


alias("_contrib_SyncBatchNorm", "sync_batch_norm")
alias("SyncBatchNorm", "sync_batch_norm")


# ---------------------------------------------------------------------------
# Hawkes process log-likelihood — contrib/hawkes_ll.cc
# ---------------------------------------------------------------------------
@register("hawkes_ll", num_outputs=2)
def hawkes_ll(lda, alpha, beta, state, lags, marks, valid_length, max_time):
    """Joint log likelihood of K univariate Hawkes processes
    [_contrib_hawkesll, hawkes_ll.cc:32; event recursion and remaining-
    compensator terms follow hawkesll_forward / _forward_compensator,
    hawkes_ll-inl.h:113,163].

    lda (N,K) background intensity, alpha/beta (K,), state (N,K) carried
    exp-decay memory, lags/marks (N,T) left-aligned ragged sequences,
    valid_length/max_time (N,).  Returns (ll (N,), new_state (N,K)).
    The sequence loop is one lax.scan; the whole batch vmaps over N —
    differentiable w.r.t. lda/alpha/beta/state via jax autodiff (the
    reference needed a hand-written backward kernel)."""
    N, T = lags.shape
    K = lda.shape[1]
    marks_i = marks.astype(jnp.int32)
    f32 = jnp.float32

    def row(mu_r, s0, lag_r, mark_r, vl, mt):
        def step(carry, inp):
            s, last, t, ll = carry
            lag, ci, j = inp
            t2 = t + lag
            d = t2 - last[ci]
            ed = jnp.exp(-beta[ci] * d)
            lam = mu_r[ci] + alpha[ci] * beta[ci] * s[ci] * ed
            comp = mu_r[ci] * d + alpha[ci] * s[ci] * (1.0 - ed)
            valid = j < vl
            ll2 = ll + jnp.where(valid, jnp.log(lam) - comp, 0.0)
            oh = jax.nn.one_hot(ci, K, dtype=s.dtype)
            new_s = jnp.where(valid, s * (1 - oh) + oh * (1.0 + s[ci] * ed),
                              s)
            new_last = jnp.where(valid, last * (1 - oh) + oh * t2, last)
            return (new_s, new_last, jnp.where(valid, t2, t), ll2), None

        init = (s0.astype(f32), jnp.zeros(K, f32), f32(0), f32(0))
        (s, last, _t, ll), _ = jax.lax.scan(
            step, init, (lag_r.astype(f32), mark_r, jnp.arange(T)))
        d = mt - last
        ed = jnp.exp(-beta * d)
        rem = mu_r * d + alpha * s * (1.0 - ed)
        return ll - rem.sum(), s * ed

    return jax.vmap(row)(lda.astype(f32), state, lags, marks_i,
                         valid_length, max_time.astype(f32))


alias("_contrib_hawkesll", "hawkes_ll")


# ---------------------------------------------------------------------------
# deformable convolution — contrib/deformable_convolution.cc,
# modulated_deformable_convolution.cc (DCN v1/v2)
# ---------------------------------------------------------------------------
@register("deformable_convolution")
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), pad=(1, 1), num_deformable_group=1,
                           mask=None):
    """DCN sampling conv [contrib/deformable_convolution.cc:90]: offset
    (N, dg*K*2, OH, OW) shifts each kernel tap's sampling point; bilinear
    gather + tap/channel contraction on the MXU (no im2col buffer).
    ``mask`` (N, dg*K, OH, OW), already sigmoided, enables DCNv2
    modulation [modulated_deformable_convolution.cc]."""
    N, C, H, W = data.shape
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    dg = num_deformable_group
    K = kh * kw
    OH, OW = offset.shape[2], offset.shape[3]
    offs = offset.reshape(N, dg, K, 2, OH, OW)

    oy = jnp.arange(OH) * sh - ph
    ox = jnp.arange(OW) * sw - pw
    ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
    ky, kx = ky.reshape(-1), kx.reshape(-1)
    base_y = oy[None, :, None] + ky[:, None, None]
    base_x = ox[None, None, :] + kx[:, None, None]
    sy = base_y[None, None] + offs[:, :, :, 0]
    sx = base_x[None, None] + offs[:, :, :, 1]

    y0 = jnp.floor(sy)
    x0 = jnp.floor(sx)
    fy, fx = sy - y0, sx - x0
    dpg = C // dg
    xg2 = data.reshape(N, dg, dpg, H * W)

    def gather(yi, xi):
        valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        flat = (yc * W + xc).reshape(N, dg, K, 1, OH * OW)
        took = jnp.take_along_axis(xg2[:, :, None], flat, axis=-1)
        took = took.reshape(N, dg, K, dpg, OH, OW)
        return took * valid[:, :, :, None].astype(data.dtype)

    val = (gather(y0, x0) * ((1 - fy) * (1 - fx))[:, :, :, None]
           + gather(y0, x0 + 1) * ((1 - fy) * fx)[:, :, :, None]
           + gather(y0 + 1, x0) * (fy * (1 - fx))[:, :, :, None]
           + gather(y0 + 1, x0 + 1) * (fy * fx)[:, :, :, None])
    if mask is not None:
        val = val * mask.reshape(N, dg, K, 1, OH, OW)
    wk = weight.reshape(weight.shape[0], dg, dpg, K)
    out = jnp.einsum("ngkcij,ogck->noij", val, wk)
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


alias("_contrib_DeformableConvolution", "deformable_convolution")


# ---------------------------------------------------------------------------
# interleaved attention matmuls — contrib/transformer.cc:651-826
# (the reference's fastest 1.x BERT path; kept so those scripts run
# verbatim.  On TPU each op is one einsum XLA maps straight onto the MXU —
# the flash path in ops/pallas_attention.py remains the preferred API.)
# ---------------------------------------------------------------------------
@register("interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """(T, B, H*3*D) interleaved qkv -> (B*H, T, T) scaled scores
    [transformer.cc:651; scale 1/sqrt(D) applied like :201]."""
    T, B, E3 = queries_keys_values.shape
    D = E3 // (heads * 3)
    x = queries_keys_values.reshape(T, B, heads, 3, D)
    q, k = x[..., 0, :], x[..., 1, :]
    scores = jnp.einsum("tbhd,sbhd->bhts", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(D))
    return scores.reshape(B * heads, T, T).astype(
        queries_keys_values.dtype)


@register("interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads=1):
    """attention (B*H, T, T) @ values -> (T, B, H*D)
    [transformer.cc:693]."""
    T, B, E3 = queries_keys_values.shape
    D = E3 // (heads * 3)
    v = queries_keys_values.reshape(T, B, heads, 3, D)[..., 2, :]
    att = attention.reshape(B, heads, T, T)
    out = jnp.einsum("bhts,sbhd->tbhd", att, v)
    return out.reshape(T, B, heads * D)


@register("interleaved_matmul_encdec_qk")
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """queries (Tq, B, H*D) x interleaved kv (Tk, B, H*2*D) ->
    (B*H, Tq, Tk) scaled scores [transformer.cc:740]."""
    Tq, B, E = queries.shape
    D = E // heads
    Tk = keys_values.shape[0]
    q = queries.reshape(Tq, B, heads, D)
    k = keys_values.reshape(Tk, B, heads, 2, D)[..., 0, :]
    scores = jnp.einsum("tbhd,sbhd->bhts", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(D))
    return scores.reshape(B * heads, Tq, Tk).astype(queries.dtype)


@register("interleaved_matmul_encdec_valatt")
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    """attention (B*H, Tq, Tk) @ interleaved values -> (Tq, B, H*D)
    [transformer.cc:786]."""
    Tk, B, E2 = keys_values.shape
    D = E2 // (heads * 2)
    v = keys_values.reshape(Tk, B, heads, 2, D)[..., 1, :]
    Tq = attention.shape[1]
    att = attention.reshape(B, heads, Tq, Tk)
    out = jnp.einsum("bhts,sbhd->tbhd", att, v)
    return out.reshape(Tq, B, heads * D)


@register("div_sqrt_dim")
def div_sqrt_dim(data):
    """data / sqrt(data.shape[-1]) [transformer.cc:838]."""
    return data / jnp.sqrt(jnp.float32(data.shape[-1])).astype(data.dtype)


for _n in ("interleaved_matmul_selfatt_qk",
           "interleaved_matmul_selfatt_valatt",
           "interleaved_matmul_encdec_qk",
           "interleaved_matmul_encdec_valatt", "div_sqrt_dim"):
    alias("_contrib_" + _n, _n)
