"""Core tensor ops (elemwise, broadcast, reduce, matrix, indexing, ordering).

Reference surface: src/operator/tensor/ (39k LoC of mshadow/cuBLAS kernels —
elemwise_binary_broadcast_op*, broadcast_reduce*, dot-inl.h, matrix_op*,
indexing_op, ordering_op) plus the numpy front-end ops (src/operator/numpy/).

TPU-native: every op is one pure jnp/lax expression; XLA fuses chains of
them into single kernels (replacing both mshadow expression templates and
the NVRTC FusedOp subsystem, src/operator/fusion/fused_op.h:58).
"""
# pylint: disable=redefined-builtin
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---- elemwise binary (broadcasting; reference elemwise_binary_broadcast) ---


@register("add")
def add(lhs, rhs):
    return jnp.add(lhs, rhs)


@register("subtract")
def subtract(lhs, rhs):
    return jnp.subtract(lhs, rhs)


@register("multiply")
def multiply(lhs, rhs):
    return jnp.multiply(lhs, rhs)


@register("divide")
def divide(lhs, rhs):
    return jnp.divide(lhs, rhs)


@register("floor_divide")
def floor_divide(lhs, rhs):
    return jnp.floor_divide(lhs, rhs)


@register("mod")
def mod(lhs, rhs):
    return jnp.mod(lhs, rhs)


@register("power")
def power(lhs, rhs):
    return jnp.power(lhs, rhs)


@register("maximum")
def maximum(lhs, rhs):
    return jnp.maximum(lhs, rhs)


@register("minimum")
def minimum(lhs, rhs):
    return jnp.minimum(lhs, rhs)


@register("hypot")
def hypot(lhs, rhs):
    return jnp.hypot(lhs, rhs)


@register("arctan2")
def arctan2(lhs, rhs):
    return jnp.arctan2(lhs, rhs)


@register("logaddexp")
def logaddexp(lhs, rhs):
    return jnp.logaddexp(lhs, rhs)


# comparisons (non-differentiable)
@register("equal", differentiable=False)
def equal(lhs, rhs):
    return jnp.equal(lhs, rhs)


@register("not_equal", differentiable=False)
def not_equal(lhs, rhs):
    return jnp.not_equal(lhs, rhs)


@register("greater", differentiable=False)
def greater(lhs, rhs):
    return jnp.greater(lhs, rhs)


@register("greater_equal", differentiable=False)
def greater_equal(lhs, rhs):
    return jnp.greater_equal(lhs, rhs)


@register("lesser", differentiable=False)
def lesser(lhs, rhs):
    return jnp.less(lhs, rhs)


@register("lesser_equal", differentiable=False)
def lesser_equal(lhs, rhs):
    return jnp.less_equal(lhs, rhs)


@register("logical_and", differentiable=False)
def logical_and(lhs, rhs):
    return jnp.logical_and(lhs, rhs)


@register("logical_or", differentiable=False)
def logical_or(lhs, rhs):
    return jnp.logical_or(lhs, rhs)


@register("logical_xor", differentiable=False)
def logical_xor(lhs, rhs):
    return jnp.logical_xor(lhs, rhs)


@register("logical_not", differentiable=False)
def logical_not(x):
    return jnp.logical_not(x)


# ---- elemwise unary --------------------------------------------------------


@register("negative")
def negative(x):
    return jnp.negative(x)


@register("abs")
def abs(x):
    return jnp.abs(x)


@register("sign")
def sign(x):
    return jnp.sign(x)


@register("round")
def round(x):
    return jnp.round(x)


@register("rint")
def rint(x):
    return jnp.rint(x)


@register("ceil")
def ceil(x):
    return jnp.ceil(x)


@register("floor")
def floor(x):
    return jnp.floor(x)


@register("trunc")
def trunc(x):
    return jnp.trunc(x)


@register("fix")
def fix(x):
    return jnp.fix(x)


@register("square")
def square(x):
    return jnp.square(x)


@register("sqrt")
def sqrt(x):
    return jnp.sqrt(x)


@register("rsqrt")
def rsqrt(x):
    return lax.rsqrt(x)


@register("cbrt")
def cbrt(x):
    return jnp.cbrt(x)


@register("rcbrt")
def rcbrt(x):
    return 1.0 / jnp.cbrt(x)


@register("exp")
def exp(x):
    return jnp.exp(x)


@register("expm1")
def expm1(x):
    return jnp.expm1(x)


@register("log")
def log(x):
    return jnp.log(x)


@register("log10")
def log10(x):
    return jnp.log10(x)


@register("log2")
def log2(x):
    return jnp.log2(x)


@register("log1p")
def log1p(x):
    return jnp.log1p(x)


@register("reciprocal")
def reciprocal(x):
    return jnp.reciprocal(x)


@register("sin")
def sin(x):
    return jnp.sin(x)


@register("cos")
def cos(x):
    return jnp.cos(x)


@register("tan")
def tan(x):
    return jnp.tan(x)


@register("arcsin")
def arcsin(x):
    return jnp.arcsin(x)


@register("arccos")
def arccos(x):
    return jnp.arccos(x)


@register("arctan")
def arctan(x):
    return jnp.arctan(x)


@register("sinh")
def sinh(x):
    return jnp.sinh(x)


@register("cosh")
def cosh(x):
    return jnp.cosh(x)


@register("tanh")
def tanh(x):
    return jnp.tanh(x)


@register("arcsinh")
def arcsinh(x):
    return jnp.arcsinh(x)


@register("arccosh")
def arccosh(x):
    return jnp.arccosh(x)


@register("arctanh")
def arctanh(x):
    return jnp.arctanh(x)


@register("degrees")
def degrees(x):
    return jnp.degrees(x)


@register("radians")
def radians(x):
    return jnp.radians(x)


@register("erf")
def erf(x):
    return jax.scipy.special.erf(x)


@register("erfinv")
def erfinv(x):
    return jax.scipy.special.erfinv(x)


@register("gamma")
def gamma(x):
    return jnp.exp(jax.scipy.special.gammaln(x))


@register("gammaln")
def gammaln(x):
    return jax.scipy.special.gammaln(x)


@register("digamma")
def digamma(x):
    return jax.scipy.special.digamma(x)


@register("isnan", differentiable=False)
def isnan(x):
    return jnp.isnan(x)


@register("isinf", differentiable=False)
def isinf(x):
    return jnp.isinf(x)


@register("isfinite", differentiable=False)
def isfinite(x):
    return jnp.isfinite(x)


@register("clip")
def clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


# ---- reductions (reference broadcast_reduce_op) ---------------------------


def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


@register("sum")
def sum(x, axis=None, keepdims=False, dtype=None):
    return jnp.sum(x, axis=_norm_axis(axis), keepdims=keepdims, dtype=dtype)


@register("mean")
def mean(x, axis=None, keepdims=False, dtype=None):
    return jnp.mean(x, axis=_norm_axis(axis), keepdims=keepdims, dtype=dtype)


@register("prod")
def prod(x, axis=None, keepdims=False):
    return jnp.prod(x, axis=_norm_axis(axis), keepdims=keepdims)


@register("max")
def max(x, axis=None, keepdims=False):
    return jnp.max(x, axis=_norm_axis(axis), keepdims=keepdims)


@register("min")
def min(x, axis=None, keepdims=False):
    return jnp.min(x, axis=_norm_axis(axis), keepdims=keepdims)


@register("std")
def std(x, axis=None, ddof=0, keepdims=False):
    return jnp.std(x, axis=_norm_axis(axis), ddof=ddof, keepdims=keepdims)


@register("var")
def var(x, axis=None, ddof=0, keepdims=False):
    return jnp.var(x, axis=_norm_axis(axis), ddof=ddof, keepdims=keepdims)


@register("norm")
def norm(x, ord=2, axis=None, keepdims=False):
    if ord == 2 and axis is None:
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.linalg.norm(x, ord=ord, axis=_norm_axis(axis),
                           keepdims=keepdims)


@register("argmax", differentiable=False)
def argmax(x, axis=None):
    return jnp.argmax(x, axis=axis)


@register("argmin", differentiable=False)
def argmin(x, axis=None):
    return jnp.argmin(x, axis=axis)


@register("cumsum")
def cumsum(x, axis=None, dtype=None):
    return jnp.cumsum(x, axis=axis, dtype=dtype)


@register("cumprod")
def cumprod(x, axis=None):
    return jnp.cumprod(x, axis=axis)


@register("logsumexp")
def logsumexp(x, axis=None, keepdims=False):
    return jax.scipy.special.logsumexp(x, axis=_norm_axis(axis),
                                       keepdims=keepdims)


# ---- matrix / linalg (reference dot-inl.h, la_op.cc; MXU-resident) --------


@register("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """MXU matmul.  Reference: src/operator/tensor/dot-inl.h (cuBLAS GEMM).
    Promotes to preferred_element_type=f32 accumulation on bf16 inputs."""
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2) if lhs.ndim > 1 else lhs
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2) if rhs.ndim > 1 else rhs
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    # MXNet semantics: contract the LAST axis of lhs with the FIRST of rhs
    return lax.dot_general(
        lhs, rhs,
        dimension_numbers=(((lhs.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32
        if lhs.dtype == jnp.bfloat16 else None,
    ).astype(jnp.result_type(lhs.dtype, rhs.dtype))


@register("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("matmul")
def matmul(a, b):
    return jnp.matmul(a, b)


@register("tensordot")
def tensordot(a, b, axes=2):
    return jnp.tensordot(a, b, axes=axes)


@register("einsum")
def einsum(*operands, subscripts=None, optimize=True):
    return jnp.einsum(subscripts, *operands, optimize=bool(optimize))


@register("outer")
def outer(a, b):
    return jnp.outer(a, b)


@register("inner")
def inner(a, b):
    return jnp.inner(a, b)


@register("kron")
def kron(a, b):
    return jnp.kron(a, b)


@register("trace")
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@register("diag")
def diag(x, k=0):
    return jnp.diag(x, k=k)


@register("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


# ---- shape manipulation (reference matrix_op*.cc) -------------------------


@register("transpose")
def transpose(x, axes=None):
    return jnp.transpose(x, axes=axes)


@register("swapaxes")
def swapaxes(x, dim1=0, dim2=1):
    return jnp.swapaxes(x, dim1, dim2)


@register("expand_dims")
def expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@register("squeeze")
def squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


@register("reshape")
def reshape(x, shape=None):
    return jnp.reshape(x, shape)


@register("flatten")
def flatten(x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("broadcast_to")
def broadcast_to(x, shape=None):
    shape = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@register("tile")
def tile(x, reps=None):
    return jnp.tile(x, reps)


@register("repeat")
def repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("flip")
def flip(x, axis=None):
    return jnp.flip(x, axis=axis)


@register("roll")
def roll(x, shift=None, axis=None):
    return jnp.roll(x, shift, axis=axis)


@register("rot90")
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@register("concat")
def concat(*xs, dim=1):
    return jnp.concatenate(xs, axis=dim)


@register("stack")
def stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


@register("split", num_outputs=None)
def split(x, num_outputs=None, axis=1, squeeze_axis=False):
    outs = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs)


@register("array_split", num_outputs=None)
def array_split(x, indices_or_sections, axis=0):
    return tuple(jnp.array_split(x, indices_or_sections, axis=axis))


@register("slice_axis")
def slice_axis(x, axis=0, begin=0, end=None):
    return lax.slice_in_dim(x, begin, end if end is not None else x.shape[axis],
                            axis=axis)


@register("slice_like")
def slice_like(x, shape_like, axes=None):
    # bound by the second input's SHAPE (slice_like.cc semantics), never
    # its values — shape_like[ax] would read the array's data
    from ..base import MXNetError

    if axes is None and x.ndim != shape_like.ndim:
        # reference slice_like.cc CHECK_EQs the ranks when no axes are
        # given; failing loudly beats silently slicing a prefix
        raise MXNetError(
            "slice_like without axes needs equal ranks, got %d vs %d; "
            "pass axes= to slice a subset" % (x.ndim, shape_like.ndim))
    slices = [slice(None)] * x.ndim
    axes_ = axes if axes is not None else range(x.ndim)
    for ax in axes_:
        slices[ax] = slice(0, shape_like.shape[ax])
    return x[tuple(slices)]


@register("pad")
def pad(x, pad_width=None, mode="constant", constant_value=0):
    # the legacy Pad op (pad.cc) passes a FLAT 2*ndim tuple
    # (before_0, after_0, before_1, after_1, ...); accept that layout on
    # top of everything jnp.pad takes (scalar, (n,), ((b,a),...))
    pw = pad_width
    if isinstance(pw, (tuple, list)) and pw \
            and not isinstance(pw[0], (tuple, list)) \
            and len(pw) == 2 * x.ndim:
        pw = tuple((int(pw[2 * i]), int(pw[2 * i + 1]))
                   for i in range(x.ndim))
    if mode == "constant":
        return jnp.pad(x, pw, mode=mode, constant_values=constant_value)
    return jnp.pad(x, pw, mode=mode)


@register("where")
def where(cond, x, y):
    return jnp.where(cond, x, y)


@register("tril")
def tril(x, k=0):
    return jnp.tril(x, k=k)


@register("triu")
def triu(x, k=0):
    return jnp.triu(x, k=k)


@register("meshgrid", num_outputs=None)
def meshgrid(*xs, indexing="xy"):
    return tuple(jnp.meshgrid(*xs, indexing=indexing))


# ---- indexing (reference indexing_op.cc) ----------------------------------


@register("take")
def take(x, indices, axis=0, mode="clip"):
    return jnp.take(x, indices.astype(jnp.int32) if hasattr(indices, "astype")
                    else indices, axis=axis, mode=mode)


@register("pick")
def pick(x, index, axis=-1, keepdims=False):
    idx = jnp.expand_dims(index.astype(jnp.int32), axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return out if keepdims else jnp.squeeze(out, axis=axis)


@register("take_along_axis")
def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return data[idx]


@register("scatter_nd")
def scatter_nd(data, indices, shape=None):
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return jnp.zeros(shape, data.dtype).at[idx].set(data)


@register("embedding")
def embedding(indices, weight):
    """Reference: src/operator/tensor/indexing_op.cc Embedding."""
    return jnp.take(weight, indices.astype(jnp.int32), axis=0)


@register("one_hot")
def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dtype)
    return oh * (on_value - off_value) + off_value


@register("boolean_mask", differentiable=False)
def boolean_mask(data, mask):
    # dynamic-shape op: executes un-jitted (reference contrib/boolean_mask)
    return data[mask.astype(bool)]


# ---- ordering (reference ordering_op.cc) ----------------------------------


@register("sort")
def sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", differentiable=False)
def argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype)


@register("topk", differentiable=False, num_outputs=None)
def topk(x, k=1, axis=-1, ret_typ="indices", is_ascend=False, dtype="float32"):
    neg = not is_ascend
    xm = x if neg else -x
    xs = jnp.moveaxis(xm, axis, -1)
    vals, idx = lax.top_k(xs, k)
    if not neg:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "indices":
        return idx.astype(dtype)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idx.astype(dtype))
    raise ValueError("unknown ret_typ %s" % ret_typ)


@register("unique", differentiable=False)
def unique(x):
    return jnp.unique(x)


@register("nonzero", differentiable=False)
def nonzero(x):
    # dynamic output shape: host fallback path (SURVEY §7 hard part 1)
    return jnp.stack(jnp.nonzero(x), axis=-1)


@register("histogram", differentiable=False, num_outputs=2)
def histogram(x, bins=10, range=None):
    cnt, edges = jnp.histogram(x, bins=bins, range=range)
    return cnt, edges


# ---- sequence ops (reference sequence_*.cc) -------------------------------


@register("sequence_mask")
def sequence_mask(data, sequence_length=None, use_sequence_length=True,
                  value=0.0, axis=0):
    """Reference: src/operator/sequence_mask.cc — mask time steps beyond
    per-batch lengths.  data: (T, B, ...) for axis=0."""
    if sequence_length is None or not use_sequence_length:
        return data
    T = data.shape[axis]
    pos = jnp.arange(T)
    sl = sequence_length.astype(jnp.int32)
    if axis == 0:      # (T, B, ...)
        mask = pos[:, None] < sl[None, :]
    else:              # (B, T, ...)
        mask = pos[None, :] < sl[:, None]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, value)


@register("sequence_last")
def sequence_last(data, sequence_length=None, use_sequence_length=False,
                  axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (sequence_length.astype(jnp.int32) - 1)
    batch = jnp.arange(data.shape[1 - axis])
    if axis == 0:
        return data[idx, batch]
    return data[batch, idx]


@register("sequence_reverse")
def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    T = data.shape[axis]
    pos = jnp.arange(T)
    sl = sequence_length.astype(jnp.int32)
    # reversed index within each sequence, identity beyond length
    rev = jnp.where(pos[:, None] < sl[None, :], sl[None, :] - 1 - pos[:, None],
                    pos[:, None])
    batch = jnp.arange(data.shape[1])
    return data[rev, batch[None, :]]


# ---- casting / misc -------------------------------------------------------


@register("cast")
def cast(x, dtype="float32"):
    from ..base import _as_np_dtype

    return jnp.asarray(x, dtype=_as_np_dtype(dtype))


@register("identity")
def identity(x):
    return x


@register("stop_gradient", differentiable=False)
def stop_gradient(x):
    return lax.stop_gradient(x)


@register("shape_array", differentiable=False)
def shape_array(x):
    return jnp.array(x.shape, dtype=jnp.int64)


@register("size_array", differentiable=False)
def size_array(x):
    return jnp.array([x.size], dtype=jnp.int64)


@register("zeros_like")
def zeros_like(x):
    return jnp.zeros_like(x)


@register("ones_like")
def ones_like(x):
    return jnp.ones_like(x)


@register("full_like")
def full_like(x, fill_value=0.0):
    return jnp.full_like(x, fill_value)


@register("add_n")
def add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out
