"""Operator registry + imperative dispatch.

Reference design: 586 ``NNVM_REGISTER_OP`` registrations, each carrying
FInferShape/FInferType/FCompute attrs (include/mxnet/op_attr_types.h:218-316),
invoked through Imperative::Invoke → SetShapeType → PushFCompute → engine
(src/imperative/imperative.cc:49,98; imperative_utils.h:169,636).

TPU-native redesign: an op is a *pure JAX function* ``fn(*arrays, **attrs)``.
- Shape/type inference: ``jax.eval_shape`` derives it from the same fn —
  there is no separate FInferShape table to keep in sync.
- FCompute<tpu>: the fn itself; XLA lowers and fuses it.  Hot ops override
  with Pallas kernels (mxnet_tpu/ops/pallas/*).
- The async engine: PJRT's async dispatch — calling fn returns immediately
  with a future-backed jax.Array, which is exactly the reference engine's
  "push returns, var carries pending write" contract.
- Autograd: at record time the op runs under ``jax.vjp``; the vjp closure is
  the tape node (see mxnet_tpu/autograd.py).
"""
from __future__ import annotations

import functools

import jax

from .. import telemetry as _tel
from ..base import MXNetError, thread_state

__all__ = ["Operator", "register", "get_op", "list_ops", "invoke", "apply_op"]

_OP_REGISTRY = {}
# bumped on every register()/alias(); cheap staleness token for caches
# built over the registry (amp classification)
_REG_VERSION = [0]


def registration_version():
    return _REG_VERSION[0]


class Operator:
    """A registered op: name, pure fn, doc, and dispatch metadata.

    ``num_outputs``/``mutates`` may be callables of the attr dict, mirroring
    the reference's ``set_num_outputs(lambda attrs: ...)`` /
    ``FMutateInputs`` registrations (optimizer_op.cc:322,941).  A mutating
    op's fn stays PURE: it returns ``(*primary_outputs, *new_state_values)``
    and invoke() writes the trailing values back into the NDArray handles at
    the declared input positions — the functional rendering of the
    reference's in-place state update contract.
    """

    __slots__ = ("name", "fn", "num_outputs", "differentiable", "doc",
                 "mutates")

    def __init__(self, name, fn, num_outputs=1, differentiable=True, doc=None,
                 mutates=None):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.doc = doc or fn.__doc__
        self.mutates = mutates

    def __call__(self, *inputs, **attrs):
        return invoke(self, inputs, attrs)

    def __repr__(self):
        return "Operator(%s)" % self.name


def register(name=None, num_outputs=1, differentiable=True, mutates=None):
    """Register a pure JAX function as a framework op.

    Usage::

        @register("relu")
        def relu(x):
            return jnp.maximum(x, 0)
    """

    def deco(fn):
        opname = name or fn.__name__
        if opname in _OP_REGISTRY:
            raise MXNetError("op '%s' registered twice" % opname)
        op = Operator(opname, fn, num_outputs, differentiable, mutates=mutates)
        _OP_REGISTRY[opname] = op
        _REG_VERSION[0] += 1
        return op

    return deco


def alias(new_name, existing):
    """Register an additional registry name for an existing op (the
    reference's ``.add_alias`` — e.g. ``Flatten``/``flatten``,
    elemwise_op_common.h usage throughout)."""
    op = existing if isinstance(existing, Operator) else get_op(existing)
    if new_name in _OP_REGISTRY:
        raise MXNetError("op '%s' registered twice" % new_name)
    _OP_REGISTRY[new_name] = op
    _REG_VERSION[0] += 1
    return op


def get_op(name):
    try:
        return _OP_REGISTRY[name]
    except KeyError:
        raise MXNetError("unknown op '%s'" % name) from None


def list_ops():
    return sorted(_OP_REGISTRY)


def _is_float(x):
    import jax.numpy as jnp

    return jnp.issubdtype(x.dtype, jnp.floating) or jnp.issubdtype(
        x.dtype, jnp.complexfloating)


# ---- eager vjp signature cache -------------------------------------------
# The reference built a whole FFI layer for ~2x python->kernel overhead
# (SURVEY §2.1 "New FFI"); here the recorded eager path costs a jax.vjp
# RETRACE per call — ~50x the unrecorded path on small tensors
# (benchmark/opperf --dispatch).  Repeated (op, attrs, avals, train, amp)
# signatures therefore reuse a jitted forward + jitted vjp rebuilt from
# the same pure fn.  Excluded: ops that draw RNG keys during trace (the
# mask would be baked in), array-valued attrs, and inputs above
# MXNET_EAGER_VJP_CACHE_MAX_ELEMS (the cached backward recomputes the
# forward, which only pays off while python dispatch dominates device
# time).  Disable wholesale with MXNET_EAGER_VJP_CACHE=0.
_VJP_CACHE = {}
_VJP_CACHE_CAP = 4096
# ops whose fn concretizes array values (static axes etc.) — their vjp
# cannot be rebuilt under jit; discovered at first failing backward
_VJP_UNJITTABLE = set()


def _vjp_cache_key(op, attrs, datas, train):
    from ..base import get_env

    # ONLY registry-registered ops are cacheable: their fn is a stable
    # module-level pure function fully described by (name, attrs).
    # apply_op one-offs (mx.np adapter, autograd._recorded_vjp closures)
    # close over per-call state — two closures with identical name+avals
    # would collide and replay the wrong captured data.
    if _OP_REGISTRY.get(op.name) is not op:
        return None
    if op.name in _VJP_UNJITTABLE:
        return None
    if not get_env("MXNET_EAGER_VJP_CACHE", bool, True):
        return None
    limit = get_env("MXNET_EAGER_VJP_CACHE_MAX_ELEMS", int, 1 << 16)
    total = 0
    sig = []
    for d in datas:
        if hasattr(d, "shape") and hasattr(d, "dtype"):
            total += d.size
            sig.append((tuple(d.shape), str(d.dtype)))
        elif isinstance(d, (int, float, bool, str, bytes, type(None))):
            # immutable scalars only: they get BAKED into the cached
            # backward's closure, so a mutable arg (list) could be
            # mutated after caching while its repr-key still matched
            sig.append(("py", repr(d)))
        else:
            return None
    if total > limit:
        return None
    if attrs and any(hasattr(v, "shape") and hasattr(v, "dtype")
                     for v in attrs.values()):
        # array-valued attrs are baked into the partial closure; NDArray
        # hashes by id so hash() would NOT catch them, and a cached
        # backward would replay a stale buffer after in-place updates
        return None
    try:
        # scalar values key by repr like positional scalars: hash/
        # equality folds 1 / True / 1.0 (and 0.0 / -0.0) into ONE cache
        # entry, replaying a backward traced for a differently-typed
        # attr; strings join the repr set so 1 and "1" stay distinct
        attrs_key = tuple(sorted(
            (k, repr(v) if isinstance(v, (bool, int, float, complex,
                                          str)) else v)
            for k, v in attrs.items())) if attrs else ()
        hash(attrs_key)
    except TypeError:
        return None       # unhashable attrs
    from ..contrib import amp as _amp

    return (op.name, attrs_key, bool(train), _amp.is_active(),
            _amp.target_dtype(), tuple(sig))


def vjp_cache_info():
    """(entries,) introspection for tests/benchmarks."""
    return {"entries": len(_VJP_CACHE)}


def vjp_cache_clear():
    _VJP_CACHE.clear()
    _VJP_UNJITTABLE.clear()   # re-registration under a name starts fresh


def invoke(op, inputs, attrs):
    """Imperative invoke: run ``op`` on NDArray inputs, record if needed.

    Mirrors Imperative::Invoke + RecordOp (imperative.cc:98,204) with XLA as
    the executor.
    """
    from ..ndarray.ndarray import NDArray

    if _tel.ENABLED:
        # the imperative invoke IS the engine push of the reference
        # (PushFCompute); the facade's Engine.push counts separately
        _tel.ENGINE_PUSH.inc()
    out_arg = attrs.pop("out", None) if attrs else None
    datas = [x._data if isinstance(x, NDArray) else x for x in inputs]
    raw_attrs = attrs
    if attrs:
        # array-valued attrs (e.g. length masks) ride along as constants
        attrs = {k: (v._data if isinstance(v, NDArray) else v)
                 for k, v in attrs.items()}
        fn = functools.partial(op.fn, **attrs)
    else:
        fn = op.fn
    fn = _amp_rewrite(op.name, fn)

    recordable = (
        thread_state.is_recording
        and op.differentiable
        and any(_on_tape(x) for x in inputs if isinstance(x, NDArray))
    )
    if recordable:
        from .. import random as _random
        from ..autograd import TapeNode

        # Pin the op's stochastic identity at record time (ADVICE r3): the
        # create_graph backward re-executes this fn to rebuild the vjp, and
        # it must see the SAME RNG keys and the SAME train-mode flag the
        # real forward saw, or Dropout/rrelu silently use a fresh mask.
        keylog = _random.KeyLog()
        train_at_record = thread_state.is_training

        def tuple_fn(*args, _log=keylog, _train=train_at_record):
            prev_train = thread_state.is_training
            thread_state.is_training = _train
            try:
                with _random.logged_keys(_log):
                    out = fn(*args)
            finally:
                thread_state.is_training = prev_train
            return out if isinstance(out, tuple) else (out,)

        nd_inputs = [x for x in inputs if isinstance(x, NDArray)]
        # the vjp covers every positional arg; non-NDArray args get dropped
        positions = [i for i, x in enumerate(inputs) if isinstance(x, NDArray)]

        cache_key = _vjp_cache_key(op, raw_attrs, datas, train_at_record)
        if cache_key is not None:
            # the cached backward bakes gradient positions: a raw-array
            # vs NDArray input mix with identical avals must not collide
            cache_key = cache_key + (tuple(positions),)
        bwd_jit = _VJP_CACHE.get(cache_key) if cache_key is not None \
            else None
        arr_idx = tuple(i for i, d in enumerate(datas)
                        if hasattr(d, "shape") and hasattr(d, "dtype"))
        if bwd_jit is not None:
            # hit: forward runs EAGERLY (identical math, and eager jnp
            # dispatch beats a jit call for trivial ops); the backward
            # reuses the jitted vjp-rebuild
            out = fn(*datas)
            out_datas = out if isinstance(out, tuple) else (out,)

            def vjp_wrapper(out_cts, _bwd=bwd_jit, _p=tuple(datas),
                            _ai=arr_idx, _key=cache_key, _tf=tuple_fn,
                            _pos=positions):
                try:
                    return list(_bwd(tuple(_p[i] for i in _ai),
                                     tuple(out_cts)))
                except Exception as exc:  # noqa: BLE001
                    # an op that concretizes a primal (static axis from
                    # an array value) cannot ride the jitted backward:
                    # drop ALL of its entries (none can ever hit again
                    # once blacklisted), log once, recompute eagerly
                    if _key[0] not in _VJP_UNJITTABLE:
                        import logging

                        logging.getLogger("mxnet_tpu").warning(
                            "eager vjp cache: op %r backward is not "
                            "jittable (%s); falling back to per-call "
                            "retrace for this op", _key[0], exc)
                    _VJP_UNJITTABLE.add(_key[0])
                    for k in [k for k in _VJP_CACHE if k[0] == _key[0]]:
                        _VJP_CACHE.pop(k, None)
                    grads = jax.vjp(_tf, *_p)[1](tuple(out_cts))
                    return [grads[i] for i in _pos]
        else:
            out_datas, vjp_fn = jax.vjp(tuple_fn, *datas)

            def vjp_wrapper(out_cts, _vjp=vjp_fn, _pos=positions):
                all_grads = _vjp(tuple(out_cts))
                return [all_grads[i] for i in _pos]

            if cache_key is not None and not keylog.keys:
                # deterministic signature: cache a backward that rebuilds
                # the vjp inside jit (recompute-based — cheap at cached
                # sizes), returning grads at tape positions.  Only ARRAY
                # args are traced; python scalars are baked as closure
                # constants (they are part of the cache key, and some
                # fns use them statically — a tracer would break them)
                const = {i: d for i, d in enumerate(datas)
                         if i not in arr_idx}

                def _bwd_fn(arr_primals, cts, _fn=tuple_fn,
                            _pos=tuple(positions), _ai=arr_idx,
                            _const=const, _n=len(datas)):
                    it = iter(arr_primals)
                    full = [_const[i] if i in _const else next(it)
                            for i in range(_n)]
                    grads = jax.vjp(_fn, *full)[1](tuple(cts))
                    return tuple(grads[i] for i in _pos)

                if len(_VJP_CACHE) >= _VJP_CACHE_CAP:
                    _VJP_CACHE.clear()
                _VJP_CACHE[cache_key] = jax.jit(_bwd_fn)

        node = TapeNode(
            vjp_wrapper, nd_inputs, len(out_datas),
            out_avals=[(o.shape, o.dtype) for o in out_datas],
            name=op.name, fwd_fn=tuple_fn, all_datas=list(datas),
            positions=positions)
        outs = [NDArray(o) for o in out_datas]
        for i, o in enumerate(outs):
            if _is_float(o._data):
                o._entry = (node, i)
        n_rec = op.num_outputs(attrs) if callable(op.num_outputs) \
            else op.num_outputs
        one = n_rec == 1 and len(outs) == 1
        return _deliver(outs[0] if one else tuple(outs), out_arg)

    out = fn(*datas)
    if not isinstance(out, tuple):
        return _deliver(NDArray(out), out_arg)
    outs = list(out)
    n_primary = op.num_outputs(attrs) if callable(op.num_outputs) \
        else op.num_outputs
    mut = op.mutates(attrs) if callable(op.mutates) else op.mutates
    if mut:
        # reference FMutateInputs: trailing fn outputs are the new values of
        # the state inputs at these positions; write them back to the handles
        for pos, val in zip(mut, outs[n_primary:]):
            tgt = inputs[pos]
            if isinstance(tgt, NDArray):
                tgt._data = val
        outs = outs[:n_primary]
    result = (NDArray(outs[0]) if len(outs) == 1
              else tuple(NDArray(o) for o in outs))
    return _deliver(result, out_arg)


def _deliver(result, out_arg):
    """Honor the generated-wrapper ``out=`` contract (reference
    register.py:265 wrappers forward ``out`` to MXImperativeInvoke): write
    the result into the caller-provided handle(s) and return them."""
    if out_arg is None:
        return result
    results = result if isinstance(result, tuple) else (result,)
    targets = out_arg if isinstance(out_arg, (tuple, list)) else (out_arg,)
    if len(results) != len(targets):
        raise MXNetError("out= expects %d arrays, got %d"
                         % (len(results), len(targets)))
    for tgt, res in zip(targets, results):
        tgt._data = res._data
        tgt._entry = getattr(res, "_entry", None)
    return out_arg if isinstance(out_arg, tuple) or not isinstance(
        out_arg, (tuple, list)) else tuple(targets)


def _on_tape(x):
    return getattr(x, "_marked", False) or getattr(x, "_entry", None) is not None


def _amp_rewrite(op_name, fn):
    """AMP per-op dtype rewrite (reference low_precision_pass.cc applied a
    graph pass; here EVERY path — eager and traced — funnels through
    invoke, so wrapping the op fn at this chokepoint IS the pass).  The
    casts live INSIDE the differentiated function so vjp cotangents cast
    back to each input's original dtype automatically."""
    from ..contrib import amp as _amp

    if not _amp.is_active():
        return fn
    import jax.numpy as jnp

    from ..contrib.amp import lists as _lists

    table = _lists.classification()
    cat = table.get(op_name)
    if cat is None and op_name.startswith("np."):
        cat = table.get(op_name[3:])   # np adapter inherits the base op
    if cat is None:
        if "." in op_name or op_name == "lambda":
            return fn                  # anonymous apply_op fns
        cat = _lists.category_of(op_name)  # warn-once path

    if cat == "target_dtype":
        to = jnp.dtype(_amp.target_dtype())

        def low_fn(*args):
            return fn(*[a.astype(to)
                        if hasattr(a, "dtype") and a.dtype == jnp.float32
                        else a for a in args])

        low_fn.__name__ = getattr(fn, "__name__", op_name)
        return low_fn
    if cat == "fp32":
        low = (jnp.bfloat16, jnp.float16)

        def high_fn(*args):
            return fn(*[a.astype(jnp.float32)
                        if hasattr(a, "dtype") and a.dtype in low else a
                        for a in args])

        high_fn.__name__ = getattr(fn, "__name__", op_name)
        return high_fn
    if cat == "widest":
        def widest_fn(*args):
            fdts = [a.dtype for a in args
                    if hasattr(a, "dtype")
                    and jnp.issubdtype(a.dtype, jnp.floating)]
            if len(set(map(str, fdts))) > 1:
                to = jnp.result_type(*fdts)
                args = [a.astype(to)
                        if hasattr(a, "dtype")
                        and jnp.issubdtype(a.dtype, jnp.floating) else a
                        for a in args]
            return fn(*args)

        widest_fn.__name__ = getattr(fn, "__name__", op_name)
        return widest_fn
    return fn


def apply_op(fn, *inputs, **attrs):
    """One-off invoke of an unregistered pure fn through the same record path."""
    op = Operator(getattr(fn, "__name__", "lambda"), fn)
    return invoke(op, inputs, attrs)
