"""Random sampling ops — the reference's sample_*/random_* op families.

Reference: src/operator/random/sample_op.cc (scalar-parameter random_*
family), multisample_op.cc (tensor-parameter sample_* family — each element
of the parameter tensors parameterizes its own distribution, drawing
``shape`` extra trailing dims), shuffle_op.cc.

TPU-native rendering: every draw pulls a fresh key from the framework RNG
stream (mxnet_tpu/random.py take_key — counter-folded so eager call order
reproduces under seed) and lowers to jax.random.* — stateless threefry on
device, so samples are reproducible per (seed, call-index) which is a
stronger contract than the reference's resource-pool RNG.

All sampling ops are non-differentiable (reference: MakeZeroGradNodes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _key():
    from .. import random as _random

    return _random.take_key()


def _mshape(param, shape):
    """MultiSample shape rule (multisample_op.cc MultiSampleOpShape):
    output = param.shape + shape."""
    if shape is None:
        return param.shape
    extra = (shape,) if isinstance(shape, int) else tuple(shape)
    return param.shape + extra


def _bcast(param, shape):
    """Broadcast a param tensor against trailing sample dims."""
    out = _mshape(param, shape)
    return jnp.broadcast_to(
        param.reshape(param.shape + (1,) * (len(out) - param.ndim)), out), out


@register("sample_uniform", differentiable=False)
def sample_uniform(low, high, shape=None, dtype="float32"):
    """Per-element uniform draws [multisample_op.cc uniform_desc]."""
    lo, out = _bcast(low, shape)
    hi, _ = _bcast(high, shape)
    u = jax.random.uniform(_key(), out, jnp.dtype(dtype))
    return lo + u * (hi - lo)


@register("sample_normal", differentiable=False)
def sample_normal(mu, sigma, shape=None, dtype="float32"):
    m, out = _bcast(mu, shape)
    s, _ = _bcast(sigma, shape)
    return m + s * jax.random.normal(_key(), out, jnp.dtype(dtype))


@register("sample_gamma", differentiable=False)
def sample_gamma(alpha, beta, shape=None, dtype="float32"):
    """Gamma(shape=alpha, scale=beta) — the reference's (alpha, beta)
    parameterization is shape/scale."""
    a, out = _bcast(alpha, shape)
    b, _ = _bcast(beta, shape)
    return jax.random.gamma(_key(), a.astype(jnp.dtype(dtype)),
                            dtype=jnp.dtype(dtype)) * b


@register("sample_exponential", differentiable=False)
def sample_exponential(lam, shape=None, dtype="float32"):
    l, out = _bcast(lam, shape)
    return jax.random.exponential(_key(), out, jnp.dtype(dtype)) / l


@register("sample_poisson", differentiable=False)
def sample_poisson(lam, shape=None, dtype="float32"):
    l, out = _bcast(lam, shape)
    return jax.random.poisson(_key(), l, out).astype(jnp.dtype(dtype))


@register("sample_negative_binomial", differentiable=False)
def sample_negative_binomial(k, p, shape=None, dtype="float32"):
    """NB(k failures, success prob p) via the Gamma-Poisson mixture
    (sampler.h NegativeBinomialSampler uses the same construction)."""
    kk, out = _bcast(k, shape)
    pp, _ = _bcast(p, shape)
    kf = jnp.asarray(kk, jnp.float32)
    rate = jax.random.gamma(_key(), kf) * (1.0 - pp) / pp
    return jax.random.poisson(_key(), rate, out).astype(jnp.dtype(dtype))


@register("sample_generalized_negative_binomial", differentiable=False)
def sample_generalized_negative_binomial(mu, alpha, shape=None,
                                         dtype="float32"):
    """GNB(mu, alpha): Poisson with Gamma(1/alpha, mu*alpha) rate
    [sampler.h GeneralizedNegativeBinomialSampler]."""
    m, out = _bcast(mu, shape)
    a, _ = _bcast(alpha, shape)
    kf = 1.0 / jnp.maximum(a, 1e-12)
    rate = jax.random.gamma(_key(), kf.astype(jnp.float32)) * m * a
    return jax.random.poisson(_key(), rate, out).astype(jnp.dtype(dtype))


@register("sample_multinomial", differentiable=False)
def sample_multinomial(data, shape=None, get_prob=False, dtype="int32"):
    """Categorical draws from (batch, k) probabilities
    [sample_multinomial_op.cc]: output (batch,) + shape indices."""
    n = 1
    if shape:
        for s in ((shape,) if isinstance(shape, int) else shape):
            n *= s
    logits = jnp.log(jnp.maximum(data, 1e-37))
    flat = jax.random.categorical(_key(), logits, axis=-1,
                                  shape=(n,) + data.shape[:-1])
    axes = tuple(range(1, flat.ndim)) + (0,)
    out_shape = data.shape[:-1] + (
        () if not shape else ((shape,) if isinstance(shape, int)
                              else tuple(shape)))
    out = jnp.transpose(flat, axes).reshape(out_shape).astype(
        jnp.dtype(dtype))
    if get_prob:
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1),
            out.reshape(data.shape[:-1] + (-1,)).astype(jnp.int32),
            axis=-1).reshape(out.shape)
        return out, logp
    return out


# ---- scalar-parameter family (sample_op.cc random_* aliases) --------------
@register("random_uniform", differentiable=False)
def random_uniform(low=0.0, high=1.0, shape=(1,), dtype="float32"):
    return jax.random.uniform(_key(), tuple(shape), jnp.dtype(dtype),
                              low, high)


@register("random_normal", differentiable=False)
def random_normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32"):
    return loc + scale * jax.random.normal(_key(), tuple(shape),
                                           jnp.dtype(dtype))


@register("random_gamma", differentiable=False)
def random_gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32"):
    return jax.random.gamma(_key(), alpha, tuple(shape),
                            jnp.dtype(dtype)) * beta


@register("random_exponential", differentiable=False)
def random_exponential(lam=1.0, shape=(1,), dtype="float32"):
    return jax.random.exponential(_key(), tuple(shape),
                                  jnp.dtype(dtype)) / lam


@register("random_poisson", differentiable=False)
def random_poisson(lam=1.0, shape=(1,), dtype="float32"):
    return jax.random.poisson(_key(), lam, tuple(shape)).astype(
        jnp.dtype(dtype))


@register("random_negative_binomial", differentiable=False)
def random_negative_binomial(k=1, p=1.0, shape=(1,), dtype="float32"):
    rate = jax.random.gamma(_key(), float(k), tuple(shape)) * (1.0 - p) / p
    return jax.random.poisson(_key(), rate).astype(jnp.dtype(dtype))


@register("random_generalized_negative_binomial", differentiable=False)
def random_generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,),
                                         dtype="float32"):
    rate = jax.random.gamma(_key(), 1.0 / max(alpha, 1e-12),
                            tuple(shape)) * mu * alpha
    return jax.random.poisson(_key(), rate).astype(jnp.dtype(dtype))


@register("random_randint", differentiable=False)
def random_randint(low=0, high=1, shape=(1,), dtype="int32"):
    return jax.random.randint(_key(), tuple(shape), low, high,
                              jnp.dtype(dtype))


@register("random_uniform_like", differentiable=False)
def random_uniform_like(data, low=0.0, high=1.0):
    return jax.random.uniform(_key(), data.shape, data.dtype, low, high)


@register("random_normal_like", differentiable=False)
def random_normal_like(data, loc=0.0, scale=1.0):
    return loc + scale * jax.random.normal(_key(), data.shape, data.dtype)


@register("shuffle", differentiable=False)
def shuffle(data):
    """Random permutation along axis 0 [shuffle_op.cc:128 _shuffle]."""
    return jax.random.permutation(_key(), data, axis=0)


@register("random_exponential_like", differentiable=False)
def random_exponential_like(data, lam=1.0):
    return jax.random.exponential(_key(), data.shape, data.dtype) / lam


@register("random_gamma_like", differentiable=False)
def random_gamma_like(data, alpha=1.0, beta=1.0):
    return jax.random.gamma(_key(), alpha, data.shape, data.dtype) * beta


@register("random_poisson_like", differentiable=False)
def random_poisson_like(data, lam=1.0):
    return jax.random.poisson(_key(), lam, data.shape).astype(data.dtype)


@register("random_negative_binomial_like", differentiable=False)
def random_negative_binomial_like(data, k=1, p=1.0):
    lam = jax.random.gamma(_key(), float(k), data.shape) * (1 - p) / p
    return jax.random.poisson(_key(), lam, data.shape).astype(data.dtype)


@register("random_generalized_negative_binomial_like", differentiable=False)
def random_generalized_negative_binomial_like(data, mu=1.0, alpha=1.0):
    lam = jax.random.gamma(_key(), 1.0 / alpha, data.shape) * alpha * mu
    return jax.random.poisson(_key(), lam, data.shape).astype(data.dtype)
