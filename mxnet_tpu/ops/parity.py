"""Reference op-name parity layer: the registry tail to the full
``NNVM_REGISTER_OP`` universe.

Three kinds of entries (OPS_PARITY.md is generated from the same tables by
tools/ops_parity.py):

1. **Aliases** — reference names that are pure renames of ops this registry
   already holds (legacy CamelCase elemwise family, ``_linalg_*`` /
   ``_sample_*`` underscore prefixes, ``broadcast_*`` comparison spellings,
   ``max_axis``-style 0.x names).  Reference: the ``.add_alias`` chains in
   elemwise_binary_broadcast_op_basic.cc, elemwise_unary_op_basic.cc and
   the 586-op registry at large.
2. **Scalar-operand family** — ``_plus_scalar``/``_rdiv_scalar``/… from
   elemwise_binary_scalar_op_basic.cc.  One generic jnp expression each:
   XLA constant-folds the scalar, so there is no reason for the reference's
   specialized kernels — but the NAMES must resolve for 1.x code.
3. **Real tail ops** — init ops (init_op.cc), the random-pdf family
   (random/pdf_op.cc), functional slice/scatter assignment
   (matrix_op.cc _slice_assign:700, indexing_op.cc scatter_set_nd),
   split_v2 (matrix_op.cc), make_loss (make_loss.cc), STE rounding
   (contrib/stes_op.cc), quadratic (contrib/quadratic_op.cc),
   gradient multiplier (contrib/gradient_multiplier_op.cc), group/sparse
   adagrad (contrib/optimizer_op.cc), multi-tensor adamw/lamb/lans mp
   variants (contrib/adamw.cc, multi_lamb.cc, multi_lans.cc), the
   quantized-op tail (quantization/), unique zipfian sampling
   (random/unique_sample_op.cc), and allclose (contrib/allclose_op.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError, _as_np_dtype
from . import contrib_tail, core, nn, optimizer_ops  # noqa: F401 (dep order)
from .registry import alias, get_op, register

__all__ = []


# ---------------------------------------------------------------------------
# 1. pure aliases
# ---------------------------------------------------------------------------
# reference name -> existing registry name
ALIASES = {
    # legacy CamelCase binary broadcast ops (elemwise_binary_broadcast_op
    # _basic.cc .add_alias("_Plus") etc.)
    "_Plus": "broadcast_add", "_plus": "broadcast_add",
    "_add": "broadcast_add", "_grad_add": "broadcast_add",
    "_Minus": "broadcast_sub", "_minus": "broadcast_sub",
    "_sub": "broadcast_sub",
    "_Mul": "broadcast_mul", "_mul": "broadcast_mul",
    "_Div": "broadcast_div", "_div": "broadcast_div",
    "_Mod": "mod", "_mod": "mod",
    "_Power": "power", "_power": "power",
    "_Maximum": "maximum", "_maximum": "maximum",
    "_Minimum": "minimum", "_minimum": "minimum",
    "_Hypot": "hypot", "_hypot": "hypot",
    # comparisons (CamelCase + lowercase + broadcast_ spellings)
    "_Equal": "equal", "_equal": "equal", "broadcast_equal": "equal",
    "_Not_Equal": "not_equal", "_not_equal": "not_equal",
    "broadcast_not_equal": "not_equal",
    "_Greater": "greater", "_greater": "greater",
    "broadcast_greater": "greater",
    "_Greater_Equal": "greater_equal", "_greater_equal": "greater_equal",
    "broadcast_greater_equal": "greater_equal",
    "_Lesser": "lesser", "_lesser": "lesser",
    "broadcast_lesser": "lesser",
    "_Lesser_Equal": "lesser_equal", "_lesser_equal": "lesser_equal",
    "broadcast_lesser_equal": "lesser_equal",
    "_Logical_And": "logical_and", "_logical_and": "logical_and",
    "broadcast_logical_and": "logical_and",
    "_Logical_Or": "logical_or", "_logical_or": "logical_or",
    "broadcast_logical_or": "logical_or",
    "_Logical_Xor": "logical_xor", "_logical_xor": "logical_xor",
    "broadcast_logical_xor": "logical_xor",
    "broadcast_maximum": "maximum", "broadcast_minimum": "minimum",
    "broadcast_hypot": "hypot", "broadcast_power": "power",
    "broadcast_mod": "mod",
    "broadcast_plus": "broadcast_add", "broadcast_minus": "broadcast_sub",
    # 0.x axis-suffixed reductions (broadcast_reduce_op registrations)
    "max_axis": "max", "min_axis": "min", "sum_axis": "sum",
    # misc renames
    "ElementWiseSum": "add_n", "BlockGrad": "stop_gradient",
    "make_loss_legacy": "identity",
    "SoftmaxActivation": "softmax",
    "_copy": "identity", "_copyto": "identity",
    "choose_element_0index": "pick", "crop": "slice",
    "normal": "random_normal", "uniform": "random_uniform",
    "_histogram": "histogram", "_shuffle": "shuffle",
    "_unravel_index": "unravel_index",
    "_ravel_multi_index": "ravel_multi_index",
    "_rnn_param_concat": "concat",
    "_npi_rnn_param_concat": "concat",
    "batch_flatten": "flatten",
    "_contrib_AdaptiveAvgPooling2D": "adaptive_avg_pooling",
    "_contrib_BilinearResize2D": "bilinear_resize",
    "_contrib_box_non_maximum_suppression": "box_nms",
    "_contrib_ctc_loss": "ctc_loss",
    "_contrib_CTCLoss": "CTCLoss",
    "_random_uniform": "random_uniform",
    "_random_normal": "random_normal",
    "_random_exponential": "random_exponential",
    "_random_gamma": "random_gamma",
    "_random_poisson": "random_poisson",
    "_random_negative_binomial": "random_negative_binomial",
    "_random_generalized_negative_binomial":
        "random_generalized_negative_binomial",
    "_random_randint": "random_randint",
    "_random_uniform_like": "random_uniform_like",
    "_random_normal_like": "random_normal_like",
    "_sample_uniform": "sample_uniform",
    "_sample_normal": "sample_normal",
    "_sample_gamma": "sample_gamma",
    "_sample_exponential": "sample_exponential",
    "_sample_poisson": "sample_poisson",
    "_sample_negative_binomial": "sample_negative_binomial",
    "_sample_generalized_negative_binomial":
        "sample_generalized_negative_binomial",
    "_sample_multinomial": "sample_multinomial",
}

# _linalg_* underscore aliases (la_op.cc registers the underscored names;
# this registry standardized on the python-surface linalg_* spelling)
_LINALG = ["det", "extractdiag", "extracttrian", "gelqf", "gemm", "gemm2",
           "inverse", "makediag", "maketrian", "potrf", "potri", "slogdet",
           "sumlogdiag", "syevd", "syrk", "trmm", "trsm"]


def _install_aliases():
    for la in _LINALG:
        ALIASES["_linalg_" + la] = "linalg_" + la
    for ref, ours in ALIASES.items():
        try:
            get_op(ref)
        except MXNetError:
            alias(ref, ours)


# ---------------------------------------------------------------------------
# 2. scalar-operand family (elemwise_binary_scalar_op_basic.cc etc.)
# ---------------------------------------------------------------------------
_SCALAR_FAMILY = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(jnp.full_like(x, s), x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(jnp.full_like(x, s), x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.full_like(x, s)),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_logical_and_scalar": lambda x, s: jnp.logical_and(
        x != 0, bool(s)).astype(x.dtype),
    "_logical_or_scalar": lambda x, s: jnp.logical_or(
        x != 0, bool(s)).astype(x.dtype),
    "_logical_xor_scalar": lambda x, s: jnp.logical_xor(
        x != 0, bool(s)).astype(x.dtype),
}

_SCALAR_CAMEL = {
    "_PlusScalar": "_plus_scalar", "_MinusScalar": "_minus_scalar",
    "_RMinusScalar": "_rminus_scalar", "_MulScalar": "_mul_scalar",
    "_DivScalar": "_div_scalar", "_RDivScalar": "_rdiv_scalar",
    "_ModScalar": "_mod_scalar", "_RModScalar": "_rmod_scalar",
    "_PowerScalar": "_power_scalar", "_RPowerScalar": "_rpower_scalar",
    "_MaximumScalar": "_maximum_scalar", "_MinimumScalar": "_minimum_scalar",
    "_HypotScalar": "_hypot_scalar", "_EqualScalar": "_equal_scalar",
    "_NotEqualScalar": "_not_equal_scalar",
    "_GreaterScalar": "_greater_scalar",
    "_GreaterEqualScalar": "_greater_equal_scalar",
    "_LesserScalar": "_lesser_scalar",
    "_LesserEqualScalar": "_lesser_equal_scalar",
    "_LogicalAndScalar": "_logical_and_scalar",
    "_LogicalOrScalar": "_logical_or_scalar",
    "_LogicalXorScalar": "_logical_xor_scalar",
}


def _install_scalar_family():
    non_diff = {"_equal_scalar", "_not_equal_scalar", "_greater_scalar",
                "_greater_equal_scalar", "_lesser_scalar",
                "_lesser_equal_scalar", "_logical_and_scalar",
                "_logical_or_scalar", "_logical_xor_scalar"}
    for name, expr in _SCALAR_FAMILY.items():
        def fn(data, scalar=1.0, is_int=None, _e=expr, **_ignored):
            return _e(data, scalar)

        fn.__name__ = name
        register(name, differentiable=name not in non_diff)(fn)
    for camel, lower in _SCALAR_CAMEL.items():
        alias(camel, lower)


# ---------------------------------------------------------------------------
# 3. init ops (init_op.cc) — registry-level, shape comes as an attr
# ---------------------------------------------------------------------------
def _install_init_ops():
    def _shape(s):
        return (s,) if isinstance(s, int) else tuple(s)

    @register("_zeros", differentiable=False)
    def _zeros(shape=(1,), dtype="float32", ctx=None, **_kw):
        return jnp.zeros(_shape(shape), _as_np_dtype(dtype))

    @register("_ones", differentiable=False)
    def _ones(shape=(1,), dtype="float32", ctx=None, **_kw):
        return jnp.ones(_shape(shape), _as_np_dtype(dtype))

    @register("_full", differentiable=False)
    def _full(shape=(1,), value=0.0, dtype="float32", ctx=None, **_kw):
        return jnp.full(_shape(shape), value, _as_np_dtype(dtype))

    @register("_zeros_without_dtype", differentiable=False)
    def _zeros_without_dtype(shape=(1,), ctx=None, **_kw):
        return jnp.zeros(_shape(shape), jnp.float32)

    @register("_arange", differentiable=False)
    def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32",
                ctx=None, **_kw):
        out = jnp.arange(start, stop, step, _as_np_dtype(dtype))
        return jnp.repeat(out, repeat) if repeat > 1 else out

    @register("_linspace", differentiable=False)
    def _linspace(start=0.0, stop=1.0, num=50, endpoint=True,
                  dtype="float32", ctx=None, **_kw):
        return jnp.linspace(start, stop, int(num), endpoint=endpoint,
                            dtype=_as_np_dtype(dtype))

    @register("_eye", differentiable=False)
    def _eye(N=1, M=0, k=0, dtype="float32", ctx=None, **_kw):
        return jnp.eye(int(N), int(M) if M else None, int(k),
                       dtype=_as_np_dtype(dtype))


# ---------------------------------------------------------------------------
# 4. random-pdf family (random/pdf_op.cc)
# ---------------------------------------------------------------------------
def _param_view(sample, parm):
    """Broadcast per-distribution params against sample's trailing dims
    (pdf_op.cc: index = start / sample_size)."""
    extra = sample.ndim - parm.ndim
    return parm.reshape(parm.shape + (1,) * extra)


def _pdf(name, lpdf_fn, n_parms=2, event_dim=0):
    def fn(sample, *parms, is_log=False):
        views = [_param_view(sample if event_dim == 0 else
                             sample[..., 0], p) for p in parms]
        lp = lpdf_fn(sample, *views)
        return lp if is_log else jnp.exp(lp)

    fn.__name__ = name
    register(name)(fn)


def _install_pdf_family():
    _pdf("_random_pdf_uniform",
         lambda x, lo, hi: -jnp.log(hi - lo) * jnp.ones_like(x))
    _pdf("_random_pdf_normal",
         lambda x, mu, sig: -0.5 * jnp.square((x - mu) / sig)
         - jnp.log(sig * jnp.sqrt(2 * jnp.pi)))
    # rate parameterization: a*log(b) + (a-1)log x - b x - lgamma(a)
    # (pdf_op.h:121 PDF_Gamma)
    _pdf("_random_pdf_gamma",
         lambda x, a, b: a * jnp.log(b) + (a - 1) * jnp.log(x) - b * x
         - lax.lgamma(a))
    _pdf("_random_pdf_exponential",
         lambda x, lam: jnp.log(lam) - lam * x, n_parms=1)
    _pdf("_random_pdf_poisson",
         lambda x, lam: x * jnp.log(lam) - lam - lax.lgamma(x + 1.0),
         n_parms=1)
    # p is the FAILURE probability (pdf_op.h:246 comment)
    _pdf("_random_pdf_negative_binomial",
         lambda x, l, p: lax.lgamma(x + l) - lax.lgamma(x + 1.0)
         - lax.lgamma(l) + l * jnp.log(p) + x * jnp.log(1 - p))

    def _gnb(x, mu, alpha):
        l = 1.0 / alpha
        p = 1.0 / (mu * alpha + 1.0)
        return (lax.lgamma(x + l) - lax.lgamma(x + 1.0) - lax.lgamma(l)
                + l * jnp.log(p) + x * jnp.log(1 - p))

    _pdf("_random_pdf_generalized_negative_binomial", _gnb)

    @register("_random_pdf_dirichlet")
    def _random_pdf_dirichlet(sample, alpha, is_log=False):
        """pdf_op.h:325 PDF_Dirichlet — sample carries a trailing event
        dim of size k; alpha is params_shape + (k,), broadcast across any
        extra sample dims between them."""
        extra = sample.ndim - alpha.ndim
        a = alpha.reshape(alpha.shape[:-1] + (1,) * extra
                          + alpha.shape[-1:])
        lp = jnp.sum((a - 1.0) * jnp.log(sample), axis=-1) \
            + lax.lgamma(jnp.sum(a, axis=-1)) \
            - jnp.sum(lax.lgamma(a), axis=-1)
        return lp if is_log else jnp.exp(lp)


# ---------------------------------------------------------------------------
# 5. functional slice/scatter assignment (matrix_op.cc, indexing_op.cc)
# ---------------------------------------------------------------------------
def _slices(shape, begin, end, step=None):
    step = step or [None] * len(begin)
    out = []
    for i, (b, e) in enumerate(zip(begin, end)):
        s = step[i] if i < len(step) and step[i] not in (None, 0) else 1
        out.append(slice(b, e, s))
    while len(out) < len(shape):
        out.append(slice(None))
    return tuple(out)


def _install_assign_family():
    @register("_slice_assign")
    def _slice_assign(lhs, rhs, begin=(), end=(), step=None):
        """out = lhs with lhs[begin:end:step] = rhs (matrix_op.cc
        _slice_assign — functional: returns a new array, the NDArray
        ``out=`` contract handles in-place semantics)."""
        return lhs.at[_slices(lhs.shape, begin, end, step)].set(
            rhs.astype(lhs.dtype))

    @register("_slice_assign_scalar")
    def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=None):
        return data.at[_slices(data.shape, begin, end, step)].set(scalar)

    alias("_crop_assign", "_slice_assign")
    alias("_crop_assign_scalar", "_slice_assign_scalar")

    @register("_scatter_set_nd")
    def _scatter_set_nd(lhs, rhs, indices, shape=None):
        """lhs with lhs[indices] = rhs (indexing_op.cc _scatter_set_nd:
        the functional form of scatter_nd writing into an existing
        array).  ``indices`` is (M, N) selecting N cells across M axes."""
        idx = tuple(indices.astype(jnp.int32))
        return lhs.at[idx].set(rhs.astype(lhs.dtype))

    @register("split_v2", num_outputs=lambda attrs: max(
        1, int(attrs.get("_num_outputs", attrs.get("sections", 1)))
        if not attrs.get("indices") else len(attrs["indices"]) + 1))
    def split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0,
                 **_kw):
        """numpy-style split (matrix_op.cc _split_v2): ``sections`` equal
        parts or explicit boundary ``indices``."""
        if sections:
            parts = jnp.split(data, int(sections), axis=axis)
        else:
            parts = jnp.split(data, list(indices), axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)

    alias("_split_v2", "split_v2")

    @register("broadcast_axis")
    def broadcast_axis(data, axis=(), size=(), **_kw):
        """Broadcast size-1 axes to given sizes (broadcast_reduce_op.cc)."""
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        sizes = (size,) if isinstance(size, int) else tuple(size)
        target = list(data.shape)
        for a, s in zip(axes, sizes):
            target[a] = s
        return jnp.broadcast_to(data, tuple(target))

    alias("broadcast_axes", "broadcast_axis")


# ---------------------------------------------------------------------------
# 6. misc tail
# ---------------------------------------------------------------------------
def _install_misc():
    @register("make_loss")
    def make_loss(data):
        """Forward identity; gradient = ones (make_loss.cc / MakeLoss
        FGradient MakeZeroGrad... the 2.0 op returns ones_like as the
        head-grad seed so a non-scalar 'loss' output backprops as-if
        summed)."""
        @jax.custom_vjp
        def _ml(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_res, g):
            return (jnp.ones_like(g),)

        _ml.defvjp(fwd, bwd)
        return _ml(data)

    @register("_identity_with_attr_like_rhs")
    def _identity_with_attr_like_rhs(lhs, rhs):
        """Identity on lhs; rhs only donates shape/stype attrs
        (elemwise_unary_op_basic.cc — internal sparse-grad plumbing)."""
        return lhs

    @register("_square_sum", differentiable=False)
    def _square_sum(data, axis=None, keepdims=False):
        """sum(x^2) fused (square_sum.cc — row_sparse-aware there; the
        dense rendering is the same contraction XLA fuses anyway)."""
        return jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims)

    @register("_contrib_quadratic")
    def _contrib_quadratic(data, a=0.0, b=0.0, c=0.0):
        """a*x^2 + b*x + c (contrib/quadratic_op.cc — the tutorial op)."""
        return a * jnp.square(data) + b * data + c

    alias("quadratic", "_contrib_quadratic")

    @register("_contrib_gradientmultiplier")
    def _contrib_gradientmultiplier(data, scalar=1.0):
        """Identity forward, grad scaled by ``scalar`` (contrib/
        gradient_multiplier_op.cc — gradient-reversal trick when
        scalar<0)."""
        @jax.custom_vjp
        def _gm(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_res, g):
            return (g * scalar,)

        _gm.defvjp(fwd, bwd)
        return _gm(data)

    @register("_contrib_round_ste")
    def _contrib_round_ste(data):
        """round with straight-through gradient (contrib/stes_op.cc)."""
        return data + lax.stop_gradient(jnp.round(data) - data)

    @register("_contrib_sign_ste")
    def _contrib_sign_ste(data):
        return data + lax.stop_gradient(jnp.sign(data) - data)

    @register("_contrib_dynamic_reshape", differentiable=False)
    def _contrib_dynamic_reshape(data, shape_like):
        """Reshape with a TENSOR shape argument (contrib/
        dynamic_shape_ops.cc) — eager-only on XLA: the shape must be
        concrete by execution time, exactly the reference's dynamic-shape
        dispatch falling off the static path."""
        import numpy as _onp

        target = [int(v) for v in _onp.asarray(shape_like)]
        return jnp.reshape(data, target)

    @register("_contrib_allclose", differentiable=False)
    def _contrib_allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
        """1 if all |a-b| <= atol + rtol*|b| (contrib/allclose_op.cc)."""
        return jnp.all(jnp.isclose(a, b, rtol=rtol, atol=atol,
                                   equal_nan=equal_nan)).astype(jnp.float32)

    alias("allclose", "_contrib_allclose")

    @register("_npx_constraint_check", differentiable=False)
    def _npx_constraint_check(data, msg="constraint violated"):
        """np_constraint_check.cc: returns True and errors eagerly when the
        boolean tensor has any False (XLA has no device-side assert; the
        eager check IS the reference CPU behavior)."""
        import numpy as _onp

        ok = bool(_onp.asarray(jnp.all(data)))
        if not ok:
            raise MXNetError(str(msg))
        return jnp.asarray(True)

    alias("constraint_check", "_npx_constraint_check")

    @register("index_update")
    def index_update(data, indices, updates):
        """Functional x[idx] = updates (npx.index_update,
        np_index_update.cc)."""
        idx = tuple(indices.astype(jnp.int32).T) \
            if indices.ndim > 1 else (indices.astype(jnp.int32),)
        return data.at[idx].set(updates.astype(data.dtype))

    @register("categorical", differentiable=False)
    def categorical(logits, shape=None):
        """Sample class ids from (batched) logits — npx.random.categorical
        (np_random ops)."""
        from .. import random as _random

        out_shape = None if shape is None else (
            (shape,) if isinstance(shape, int) else tuple(shape))
        return jax.random.categorical(_random.take_key(), logits, axis=-1,
                                      shape=out_shape)

    @register("_sample_unique_zipfian", differentiable=False,
              num_outputs=2)
    def _sample_unique_zipfian(range_max=1, shape=(1,)):
        """Unique zipfian draws + expected-count outputs
        (random/unique_sample_op.cc — the sampled-softmax helper).
        Deduplication is per row; counts follow the log-uniform class
        distribution the reference uses."""
        import numpy as _onp

        from .. import random as _random

        shp = (shape,) if isinstance(shape, int) else tuple(shape)
        n_rows = 1 if len(shp) == 1 else int(shp[0])
        n = int(shp[-1])
        key = _onp.asarray(_random.take_key())
        rs = _onp.random.default_rng(int(key[0]) << 32 | int(key[-1]))
        rows, counts = [], []
        log_range = _onp.log(range_max + 1.0)
        for _r in range(n_rows):
            seen, out = {}, []
            num_tries = 0
            while len(out) < n:
                num_tries += 1
                v = int(_onp.exp(rs.random() * log_range)) - 1
                v = min(max(v, 0), range_max - 1)
                if v not in seen:
                    seen[v] = True
                    out.append(v)
            rows.append(out)
            # expected count per sampled class given num_tries draws
            p = [-_onp.expm1(num_tries * _onp.log1p(
                -_onp.log1p(1.0 / (c + 1.0)) / log_range)) for c in out]
            counts.append(p)
        samples = _onp.asarray(rows, dtype=_onp.int64).reshape(shp)
        cnt = _onp.asarray(counts, dtype=_onp.float32).reshape(shp)
        return jnp.asarray(samples), jnp.asarray(cnt)


# ---------------------------------------------------------------------------
# 7. optimizer tail (contrib/optimizer_op.cc, adamw.cc, multi_lamb.cc)
# ---------------------------------------------------------------------------
def _install_optimizer_tail():
    @register("group_adagrad_update", differentiable=False, mutates=(2,))
    def group_adagrad_update(weight, grad, history, lr, rescale_grad=1.0,
                             clip_gradient=-1.0, epsilon=1e-5):
        """Group AdaGrad (contrib/optimizer_op.cc GroupAdagradUpdate):
        history accumulates the MEAN square over the trailing dims per
        row."""
        g = grad * rescale_grad
        if clip_gradient is not None and clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        axes = tuple(range(1, g.ndim))
        new_h = history + (jnp.mean(jnp.square(g), axis=axes, keepdims=True)
                           if axes else jnp.square(g))
        new_w = weight - lr * g / (jnp.sqrt(new_h) + epsilon)
        return new_w, new_h

    alias("_contrib_group_adagrad_update", "group_adagrad_update")

    @register("_sparse_adagrad_update", differentiable=False, mutates=(2,))
    def _sparse_adagrad_update(weight, grad, history, lr, epsilon=1e-7,
                               wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
        """AdaGrad with the reference's sparse-update semantics rendered
        dense: rows with all-zero grad keep weight AND history untouched
        (optimizer_op.cc AdagradUpdateEx row_sparse path)."""
        g = grad * rescale_grad
        if clip_gradient is not None and clip_gradient >= 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        live = jnp.any(g != 0, axis=tuple(range(1, g.ndim)), keepdims=True) \
            if g.ndim > 1 else (g != 0)
        new_h = jnp.where(live, history + jnp.square(g), history)
        step = lr * g / (jnp.sqrt(new_h) + epsilon)
        new_w = jnp.where(live, weight * (1.0 - lr * wd) - step, weight)
        return new_w, new_h

    from .contrib_tail import _multi_lamb_fn, _multi_lans_fn

    def _mp_multi(base_fn, stride=5):
        """mp variants interleave (w, g, mean, var, weight32); math runs on
        weight32, output weight re-cast (multi_lamb.cc MP path)."""
        def fn(*arrays, **attrs):
            n = len(arrays) // stride
            slim, w32s, orig = [], [], []
            for i in range(n):
                w, g, m, v, w32 = arrays[i * stride:(i + 1) * stride]
                slim.extend([w32, g, m, v])
                w32s.append(w32)
                orig.append(w)
            attrs.pop("num_tensors", None)
            outs = base_fn(*slim, num_tensors=n, **attrs)
            new_w32 = outs[:n]
            states = outs[n:]
            final = [nw.astype(orig[i].dtype) for i, nw in
                     enumerate(new_w32)]
            return tuple(final) + tuple(states) + tuple(new_w32)

        return fn

    def _mp_meta(stride=5):
        def num_outputs(attrs):
            return int(attrs["num_tensors"])

        def mutates(attrs):
            n = int(attrs["num_tensors"])
            pos = []
            for i in range(n):
                pos.extend([i * stride + 2, i * stride + 3])
            for i in range(n):
                pos.append(i * stride + 4)
            return pos

        return num_outputs, mutates

    _no, _mut = _mp_meta()
    f = _mp_multi(_multi_lamb_fn)
    f.__name__ = "_multi_mp_lamb_update"
    register("_multi_mp_lamb_update", differentiable=False,
             num_outputs=_no, mutates=_mut)(f)
    f2 = _mp_multi(_multi_lans_fn)
    f2.__name__ = "_multi_mp_lans_update"
    register("_multi_mp_lans_update", differentiable=False,
             num_outputs=_no, mutates=_mut)(f2)

    from .contrib_tail import _adamw_math

    def _multi_adamw(*arrays, lrs=None, wds=None, etas=None, beta1=0.9,
                     beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                     step_count=None, num_tensors=None, mp=False):
        """Fused multi-tensor AdamW (adamw.cc _multi_adamw_update:143):
        trailing input is the shared rescale_grad TENSOR — non-finite
        scale skips the whole update."""
        stride = 5 if mp else 4
        rescale = arrays[-1]
        arrays = arrays[:-1]
        n = len(arrays) // stride
        outs, states, w32outs = [], [], []
        for i in range(n):
            grp = arrays[i * stride:(i + 1) * stride]
            if mp:
                w, g, m, v, w32 = grp
            else:
                w, g, m, v = grp
                w32 = w.astype(jnp.float32)
            nw32, nm, nv = _adamw_math(
                w32, g.astype(jnp.float32), m, v, rescale, lrs[i], etas[i],
                beta1, beta2, epsilon, wds[i], clip_gradient)
            outs.append(nw32.astype(w.dtype))
            states.extend([nm, nv])
            if mp:
                w32outs.append(nw32)
        return tuple(outs) + tuple(states) + tuple(w32outs)

    def _adamw_meta(stride):
        def num_outputs(attrs):
            return int(attrs["num_tensors"])

        def mutates(attrs):
            n = int(attrs["num_tensors"])
            pos = []
            for i in range(n):
                pos.extend([i * stride + 2, i * stride + 3])
            if stride == 5:
                for i in range(n):
                    pos.append(i * stride + 4)
            return pos

        return num_outputs, mutates

    _no4, _mut4 = _adamw_meta(4)
    g4 = lambda *a, **kw: _multi_adamw(*a, mp=False, **kw)  # noqa: E731
    g4.__name__ = "_multi_adamw_update"
    register("_multi_adamw_update", differentiable=False,
             num_outputs=_no4, mutates=_mut4)(g4)
    _no5, _mut5 = _adamw_meta(5)
    g5 = lambda *a, **kw: _multi_adamw(*a, mp=True, **kw)  # noqa: E731
    g5.__name__ = "_multi_mp_adamw_update"
    register("_multi_mp_adamw_update", differentiable=False,
             num_outputs=_no5, mutates=_mut5)(g5)


# ---------------------------------------------------------------------------
# 8. quantized-op tail (quantization/*.cc)
# ---------------------------------------------------------------------------
def _install_quantized_tail():
    def _rng_of(q, mn, mx):
        return mn, mx

    @register("quantized_pooling", differentiable=False, num_outputs=3)
    def quantized_pooling(data, min_range, max_range, kernel=(2, 2),
                          stride=None, pad=(0, 0), pool_type="max",
                          **kw):
        """int8 pooling straight on quantized values (quantized_pooling.cc
        — order-preserving, range passes through)."""
        from .nn import pooling

        out = pooling.fn(data.astype(jnp.float32), kernel=kernel,
                         stride=stride, pad=pad, pool_type=pool_type, **kw)
        out = jnp.clip(jnp.round(out), -127, 127).astype(data.dtype)
        return out, min_range, max_range

    @register("quantized_act", differentiable=False, num_outputs=3)
    def quantized_act(data, min_range, max_range, act_type="relu"):
        """int8 relu (quantized_activation.cc — relu only there too)."""
        if act_type != "relu":
            raise MXNetError("quantized_act supports relu only (reference "
                             "quantized_activation.cc)")
        out = jnp.maximum(data, 0)
        return out, jnp.maximum(jnp.asarray(min_range, jnp.float32), 0.0), \
            max_range

    @register("quantized_flatten", differentiable=False, num_outputs=3)
    def quantized_flatten(data, min_range, max_range):
        return data.reshape(data.shape[0], -1), min_range, max_range

    @register("quantized_concat", differentiable=False, num_outputs=3)
    def quantized_concat(*args, num_args=None, dim=1):
        """Concat int8 inputs after rescaling to the widest range
        (quantized_concat.cc)."""
        n = len(args) // 3
        datas, mins, maxs = args[:n], args[n:2 * n], args[2 * n:]
        out_min = jnp.minimum(*mins) if n > 1 else mins[0]
        out_max = jnp.maximum(*maxs) if n > 1 else maxs[0]
        out_amax = jnp.maximum(jnp.abs(out_min), jnp.abs(out_max))
        parts = []
        for d, mn, mx in zip(datas, mins, maxs):
            amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
            parts.append(jnp.clip(jnp.round(
                d.astype(jnp.float32) * (amax / out_amax)), -127, 127))
        return (jnp.concatenate(parts, axis=dim).astype(datas[0].dtype),
                out_min, out_max)

    @register("quantized_elemwise_add", differentiable=False, num_outputs=3)
    def quantized_elemwise_add(lhs, rhs, lhs_min, lhs_max, rhs_min,
                               rhs_max):
        """int8 add via f32 accumulate + requantize to the summed range
        (quantized_elemwise_add.cc)."""
        ls = jnp.maximum(jnp.abs(lhs_min), jnp.abs(lhs_max)) / 127.0
        rs = jnp.maximum(jnp.abs(rhs_min), jnp.abs(rhs_max)) / 127.0
        f = lhs.astype(jnp.float32) * ls + rhs.astype(jnp.float32) * rs
        out_amax = jnp.maximum(jnp.abs(lhs_min) + jnp.abs(rhs_min),
                               jnp.abs(lhs_max) + jnp.abs(rhs_max))
        q = jnp.clip(jnp.round(f * (127.0 / out_amax)), -127, 127)
        return q.astype(lhs.dtype), -out_amax, out_amax

    @register("quantized_elemwise_mul", differentiable=False, num_outputs=3)
    def quantized_elemwise_mul(lhs, rhs, lhs_min, lhs_max, rhs_min,
                               rhs_max):
        ls = jnp.maximum(jnp.abs(lhs_min), jnp.abs(lhs_max)) / 127.0
        rs = jnp.maximum(jnp.abs(rhs_min), jnp.abs(rhs_max)) / 127.0
        f = (lhs.astype(jnp.float32) * ls) * (rhs.astype(jnp.float32) * rs)
        out_amax = (jnp.maximum(jnp.abs(lhs_min), jnp.abs(lhs_max))
                    * jnp.maximum(jnp.abs(rhs_min), jnp.abs(rhs_max)))
        out_amax = jnp.maximum(out_amax, 1e-12)
        q = jnp.clip(jnp.round(f * (127.0 / out_amax)), -127, 127)
        return q.astype(lhs.dtype), -out_amax, out_amax

    @register("quantized_embedding", differentiable=False, num_outputs=3)
    def quantized_embedding(data, weight_q, w_min, w_max, input_dim=None,
                            output_dim=None):
        """int8 embedding gather (quantized_indexing_op.cc)."""
        return weight_q[data.astype(jnp.int32)], w_min, w_max

    @register("quantized_batch_norm", differentiable=False, num_outputs=3)
    def quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                             d_min, d_max, eps=1e-3, **_kw):
        """int8 BN folded to a per-channel affine then requantized
        (quantized_batch_norm.cc — inference only)."""
        scale_in = jnp.maximum(jnp.abs(d_min), jnp.abs(d_max)) / 127.0
        x = data.astype(jnp.float32) * scale_in
        inv = gamma / jnp.sqrt(moving_var + eps)
        shape = (1, -1) + (1,) * (data.ndim - 2)
        y = (x - moving_mean.reshape(shape)) * inv.reshape(shape) \
            + beta.reshape(shape)
        amax = jnp.maximum(jnp.max(jnp.abs(y)), 1e-12)
        q = jnp.clip(jnp.round(y * (127.0 / amax)), -127, 127)
        return q.astype(data.dtype), -amax, amax

    for ref, ours in {
            "_contrib_quantize": "quantize",
            "_contrib_quantize_v2": "quantize_v2",
            "_contrib_dequantize": "dequantize",
            "_contrib_requantize": "requantize",
            "_contrib_quantized_conv": "quantized_conv",
            "_contrib_quantized_fully_connected":
                "quantized_fully_connected",
            "_contrib_quantized_pooling": "quantized_pooling",
            "_contrib_quantized_act": "quantized_act",
            "_contrib_quantized_flatten": "quantized_flatten",
            "_contrib_quantized_concat": "quantized_concat",
            "_contrib_quantized_elemwise_add": "quantized_elemwise_add",
            "_contrib_quantized_elemwise_mul": "quantized_elemwise_mul",
            "_contrib_quantized_embedding": "quantized_embedding",
            "_contrib_quantized_batch_norm": "quantized_batch_norm",
    }.items():
        try:
            get_op(ref)
        except MXNetError:
            alias(ref, ours)

    @register("_contrib_calibrate_entropy", differentiable=False,
              num_outputs=2)
    def _contrib_calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
        """KL-divergence threshold search over a histogram
        (quantization/calibrate.cc) — delegates to the python calibrator
        which implements the same search."""
        import numpy as _onp

        from ..contrib.quantization import calib_entropy_threshold

        h = _onp.asarray(hist)
        e = _onp.asarray(hist_edges)
        thr = calib_entropy_threshold(h, e, int(num_quantized_bins))
        return (jnp.asarray(-thr, jnp.float32),
                jnp.asarray(thr, jnp.float32))


def _install_misc_tail():
    bn = get_op("BatchNorm")

    @register("_contrib_BatchNormWithReLU",
              num_outputs=lambda attrs: 1 if not attrs.get(
                  "output_mean_var") else 3)
    def _contrib_BatchNormWithReLU(data, gamma, beta, moving_mean,
                                   moving_var, **attrs):
        """BN + fused ReLU (contrib/batch_norm_relu.cc).  XLA fuses the
        max into the normalization epilogue on its own; the op exists for
        name parity with imported 1.x graphs."""
        out = bn.fn(data, gamma, beta, moving_mean, moving_var, **attrs)
        if isinstance(out, tuple):
            return (jnp.maximum(out[0], 0),) + out[1:]
        return jnp.maximum(out, 0)

    @register("_npi_boolean_mask_assign_scalar")
    def _npi_boolean_mask_assign_scalar(data, mask, value=0.0):
        """data[mask] = scalar, functional (np_boolean_mask_assign.cc)."""
        m = mask.astype(bool)
        m = m.reshape(m.shape + (1,) * (data.ndim - m.ndim))
        return jnp.where(m, jnp.asarray(value, data.dtype), data)

    @register("_npi_boolean_mask_assign_tensor")
    def _npi_boolean_mask_assign_tensor(data, mask, value):
        """data[mask] = tensor broadcast against the masked region.  The
        general gather-shaped rhs needs a concrete mask (eager), matching
        the reference's dynamic-shape dispatch; the broadcastable case
        stays traceable."""
        m = mask.astype(bool)
        m = m.reshape(m.shape + (1,) * (data.ndim - m.ndim))
        try:
            return jnp.where(m, value.astype(data.dtype), data)
        except (TypeError, ValueError):
            import numpy as _onp

            host = _onp.asarray(data).copy()
            host[_onp.asarray(m).reshape(mask.shape)] = _onp.asarray(value)
            return jnp.asarray(host)

    @register("cast_storage", differentiable=False)
    def cast_storage(data, stype="default"):
        """Dense-side cast_storage (cast_storage.cc): on the registry path
        (dense jax arrays) every stype is stored dense, so this is the
        identity; real sparse handles convert via
        ndarray.sparse.cast_storage / .tostype (FComputeEx equivalent)."""
        return data

    @register("_sparse_retain", differentiable=False)
    def _sparse_retain(data, indices):
        """Dense rendering of sparse_retain (sparse_retain.cc): keep the
        given rows, zero the rest.  RowSparseNDArray handles route through
        ndarray.sparse (RowSparseNDArray.retain) instead."""
        keep = jnp.zeros((data.shape[0],), bool).at[
            indices.astype(jnp.int32)].set(True)
        return jnp.where(keep.reshape((-1,) + (1,) * (data.ndim - 1)),
                         data, jnp.zeros_like(data))

    for like in ["exponential", "gamma", "poisson", "negative_binomial",
                 "generalized_negative_binomial"]:
        alias("_random_%s_like" % like, "random_%s_like" % like)
    alias("_contrib_MultiBoxTarget", "multibox_target")
    alias("_contrib_RROIAlign", "rroi_align")


_install_aliases()
_install_scalar_family()
_install_init_ops()
_install_pdf_family()
_install_assign_family()
_install_misc()
_install_optimizer_tail()
_install_quantized_tail()
_install_misc_tail()
