"""RecordIO (reference python/mxnet/recordio.py — MXRecordIO:36,
MXIndexedRecordIO:215, IRHeader:343 pack/unpack; C++ side dmlc recordio).

Pure-python implementation of the same container: magic-delimited length-
prefixed records, usable by the IO iterators and ImageRecordDataset.  Image
payloads are stored as raw npy bytes (no OpenCV in this environment);
pack_img/unpack_img keep the reference signatures.
"""
from __future__ import annotations

import collections
import io as _io
import os
import struct

import numpy as _np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a


class MXRecordIO:
    """Sequential record file (reference recordio.py:36)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def tell(self):
        return self.handle.tell()

    def write(self, buf):
        assert self.writable
        self.handle.write(struct.pack("<II", _MAGIC, len(buf)))
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, length = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic")
        buf = self.handle.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Random-access record file with .idx sidecar (reference :215)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.exists(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) == 2:
                        key = key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)

    def close(self):
        if getattr(self, "is_open", False) and self.writable:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write("%s\t%d\n" % (key, self.idx[key]))
        super().close()

    def seek(self, idx):
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = collections.namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack IRHeader + payload (reference recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                          header.id2)
        return hdr + s
    label = _np.asarray(header.label, dtype=_np.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array.  JPEG via the native libjpeg encoder (reference
    pack_img uses cv2.imencode); npy fallback when the native runtime is
    unavailable or a lossless payload is requested (img_fmt='.npy')."""
    from . import native

    arr = _np.asarray(img)
    jpeg_able = (arr.dtype == _np.uint8
                 and (arr.ndim == 2 or (arr.ndim == 3
                                        and arr.shape[2] in (1, 3))))
    if img_fmt.lower() in (".jpg", ".jpeg") and native.available() \
            and jpeg_able:
        try:
            return pack(header, native.encode_jpeg(arr, quality=quality))
        except Exception:
            pass  # fall through to lossless npy payload
    buf = _io.BytesIO()
    _np.save(buf, arr)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    header, payload = unpack(s)
    if payload[:2] == b"\xff\xd8":  # JPEG magic
        from . import native

        if native.available():
            return header, native.decode_jpeg(payload)
        try:  # pure-python fallback decoder
            from PIL import Image

            img = _np.asarray(Image.open(_io.BytesIO(payload))
                              .convert("RGB"))
            return header, img
        except ImportError as exc:
            raise MXNetError("JPEG payload needs the native runtime or "
                             "PIL") from exc
    img = _np.load(_io.BytesIO(payload), allow_pickle=False)
    return header, img
