"""Network visualization (reference python/mxnet/visualization.py —
print_summary:37, plot_network:214).

print_summary walks the serialized symbol op tree (symbol/__init__.py
json_repr — the same graph plot_network draws) and prints the reference's
layer table: name, output shape, params, connections.  plot_network emits
graphviz when the ``graphviz`` package is importable and raises with
guidance otherwise (same hard dependency as the reference).
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _walk(root, out, edges=None):
    """Iterative DFS with a visited set: shared subgraphs (residual /
    weight-sharing diamonds) list each NODE once but keep EVERY edge, and
    deep chains cannot blow the recursion limit.  ``out`` receives
    (ident, name, node, first_parent); ``edges`` (optional list) receives
    every (child_ident, parent_ident) pair."""
    if not isinstance(root, dict):
        return
    seen = set()
    stack = [(root, None)]
    while stack:
        node, parent = stack.pop()
        if not isinstance(node, dict):
            continue
        ident = id(node)
        if edges is not None and parent is not None:
            edges.append((ident, id(parent)))
        if ident in seen:
            continue
        seen.add(ident)
        name = node.get("op", "?")
        if name == "null":
            name = "var:" + str(node.get("name"))
        out.append((ident, name, node,
                    id(parent) if parent is not None else None))
        for child in reversed(node.get("inputs", []) or []):
            stack.append((child, node))


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print the layer table of a Symbol [visualization.py:37]."""
    from .symbol import Symbol

    if not isinstance(symbol, Symbol):
        raise MXNetError("print_summary expects a Symbol")
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    out_shapes = None
    if shape is not None:
        res = symbol.infer_shape(**shape)
        if res and res[1]:
            out_shapes = res[1]

    nodes = []
    _walk(symbol._json, nodes)
    nodes.reverse()  # inputs first, output last
    by_id = {ident: nm for ident, nm, _n, _p in nodes}

    def row(fields):
        line = ""
        for i, f in enumerate(fields):
            line = (line[:positions[i] - len(str(f)) - 1]
                    if len(line) > positions[i] - len(str(f)) - 1 else line)
            line += str(f)
            line = line.ljust(positions[i])
        print(line.rstrip())

    print("=" * line_length)
    row(headers)
    print("=" * line_length)
    for i, (ident, name, node, parent) in enumerate(nodes):
        oshape = ""
        if out_shapes is not None and i == len(nodes) - 1:
            oshape = out_shapes[0]
        prev = by_id.get(parent, "") if parent else ""
        row([name, oshape, "", prev])
    print("=" * line_length)
    print("Nodes: %d" % len(nodes))
    print("=" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz digraph of the symbol graph [visualization.py:214]."""
    from .symbol import Symbol

    if not isinstance(symbol, Symbol):
        raise MXNetError("plot_network expects a Symbol")
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires the graphviz python "
                         "package (zero-egress build: not installed; use "
                         "print_summary for a text view)") from None
    node_attrs = node_attrs or {}
    dot = Digraph(name=title, format=save_format)
    nodes, edges = [], []
    _walk(symbol._json, nodes, edges)
    hidden = set()
    for ident, name, node, _parent in nodes:
        if hide_weights and name.startswith("var:") and \
                any(k in name for k in ("weight", "bias", "gamma", "beta")):
            hidden.add(ident)
            continue
        dot.node(str(ident), name, **node_attrs)
    for child, parent in edges:  # every consumer edge, diamonds included
        if child not in hidden:
            dot.edge(str(child), str(parent))
    return dot
