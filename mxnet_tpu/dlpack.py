"""DLPack interop (reference python/mxnet/dlpack.py — ndarray_to_dlpack_*
/ ndarray_from_dlpack, the zero-copy tensor exchange used by
``mx.nd.to_dlpack_for_read`` and torch/cupy bridges).

TPU-native path: jax.Array implements the DLPack protocol natively
(``__dlpack__``), so the capsule flows straight through — CPU buffers
exchange zero-copy with torch/numpy; device buffers follow jax's dlpack
rules."""
from __future__ import annotations

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["to_dlpack_for_read", "to_dlpack_for_write", "from_dlpack"]


def to_dlpack_for_read(data):
    """NDArray -> DLPack exporter (read view) [reference dlpack.py:57].

    Returns the underlying jax.Array, which implements ``__dlpack__`` /
    ``__dlpack_device__`` — the modern DLPack exchange object accepted by
    ``torch.from_dlpack`` / ``np.from_dlpack`` (the capsule-only protocol
    the reference used is deprecated across the ecosystem)."""
    if not isinstance(data, NDArray):
        raise MXNetError("to_dlpack_for_read expects an NDArray")
    return data._data


def to_dlpack_for_write(data):
    """Functional arrays have no writable aliasing; the capsule is the
    same read view (documented divergence: XLA buffers are immutable —
    reference semantics relied on in-place engine writes)."""
    return to_dlpack_for_read(data)


def from_dlpack(dlpack):
    """DLPack exporter (``__dlpack__`` object) -> NDArray
    [reference dlpack.py:92]."""
    import jax.numpy as jnp

    if not hasattr(dlpack, "__dlpack__"):
        raise MXNetError(
            "from_dlpack expects an object implementing __dlpack__ (raw "
            "capsules are no longer exchanged; pass the tensor itself)")
    return NDArray(jnp.from_dlpack(dlpack))
