"""Pluggable graph partitioning — the reference's SubgraphProperty /
CustomPartitioner surface.

Reference: src/operator/subgraph/subgraph_property.h (SubgraphProperty
registry keyed by backend name, SelectSubgraphNode pattern matching),
include/mxnet/lib_api.h:827 (external-library CustomPartitioner), invoked
from python Symbol.optimize_for (python/mxnet/symbol/symbol.py:1477).

TPU-native redesign: the compiler (XLA) already does fusion/placement, so a
partitioner here is NOT a performance tool — it is the *extension hook* the
reference exposes: a backend registers op-chain patterns and a fuse rule;
``Symbol.optimize_for(backend)`` rewrites matching chains in the serialized
op tree (symbol/__init__.py json_repr) into a single ``_subgraph`` node.
The fused node either calls the backend's fuse fn or replays the recorded
chain — XLA compiles the replayed chain as one fused kernel anyway, so
correctness never depends on the backend doing anything clever.

Usage::

    prop = SubgraphProperty("mybackend")
    prop.add_pattern(["dense", "relu"], name="dense_relu")
    register_backend(prop)
    optimized = sym.optimize_for("mybackend")
"""
from __future__ import annotations

import ast
import functools

from .base import MXNetError

_BACKENDS = {}


class SubgraphProperty:
    """A named backend holding op-chain patterns and optional fuse fns."""

    def __init__(self, name):
        self.name = name
        self.patterns = []  # list of (op_chain, fused_name, fuse_fn|None)

    def add_pattern(self, op_chain, name=None, fuse_fn=None):
        """op_chain: outermost-first op names, e.g. ['relu', 'dense'] means
        relu(dense(x, ...)).  fuse_fn(*leaf_arrays, attrs_list=...) -> array;
        None replays the original ops (XLA fuses them into one kernel)."""
        if not op_chain:
            raise MXNetError("empty pattern")
        fused = name or "_fused_" + "_".join(op_chain)
        self.patterns.append((list(op_chain), fused, fuse_fn))
        return self


def register_backend(prop):
    """Register a SubgraphProperty under its backend name (reference
    MXNET_REGISTER_SUBGRAPH_BACKEND)."""
    if not isinstance(prop, SubgraphProperty):
        raise MXNetError("register_backend expects a SubgraphProperty")
    _BACKENDS[prop.name.lower()] = prop
    return prop


def get_backend(name):
    return _BACKENDS.get(str(name).lower())


# compiler backends that are always valid no-op names (XLA is the one
# real compiler; reference accepted its builtin names the same way)
BUILTIN_BACKENDS = frozenset(["", "xla", "tpu", "default"])


def validate_backend(name):
    """Raise for a backend string that is neither builtin nor a registered
    SubgraphProperty — shared by Symbol.optimize_for and
    HybridBlock.optimize_for so the rule cannot drift."""
    if name is None:
        return None
    if get_backend(name) is not None:
        return get_backend(name)
    if str(name).lower() in BUILTIN_BACKENDS:
        return None
    raise MXNetError(
        "unknown partitioning backend %r: the TPU build has one compiler "
        "backend (XLA); register a SubgraphProperty "
        "(mxnet_tpu.subgraph) for custom partitioning" % (name,))


def list_backends():
    return sorted(_BACKENDS)


def _match_chain(node, chain):
    """Match an outermost-first op-name chain down the FIRST input edge.
    Returns node list [outermost .. innermost] or None."""
    nodes, cur = [], node
    for opname in chain:
        if not isinstance(cur, dict) or cur.get("op") != opname:
            return None
        nodes.append(cur)
        kids = cur.get("inputs", [])
        cur = kids[0] if kids else None
    return nodes


def partition_json(tree, prop):
    """Rewrite matching chains into _subgraph nodes (the SubgraphProperty
    graph pass, subgraph_property.h:211).  Returns (new_tree, n_matches).

    The fused node's ``inputs`` hold, in order: for every chain node from
    outermost to innermost, that node's non-chain inputs (all inputs for
    the innermost, inputs[1:] for the rest); ``chain`` records each node's
    op, attrs, and how many of those inputs it owns (arity)."""
    if not isinstance(tree, dict):
        return tree, 0
    for chain_ops, fused_name, _fn in prop.patterns:
        nodes = _match_chain(tree, chain_ops)
        if nodes:
            child_json, chain_meta = [], []
            inner = nodes[-1]
            for nd_ in nodes:
                own = nd_.get("inputs", []) if nd_ is inner \
                    else nd_.get("inputs", [])[1:]
                chain_meta.append({"op": nd_["op"],
                                   "attrs": nd_.get("attrs", {}),
                                   "arity": len(own)})
                child_json.extend(own)
            total = 1
            new_inputs = []
            for k in child_json:
                nk, c = partition_json(k, prop)
                new_inputs.append(nk)
                total += c
            return ({"op": "_subgraph", "backend": prop.name,
                     "fused": fused_name, "chain": chain_meta,
                     "inputs": new_inputs}, total)
    total = 0
    kids = tree.get("inputs")
    if kids:
        new_kids = []
        for k in kids:
            nk, c = partition_json(k, prop)
            new_kids.append(nk)
            total += c
        tree = dict(tree, inputs=new_kids)
    return tree, total


def _parse_attrs(a):
    out = {}
    for k, v in (a or {}).items():
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = v
    return out


def rebuild_subgraph_node(node, rebuild):
    """Turn a _subgraph json node back into an executable Symbol (hooked
    from symbol._rebuild)."""
    from .ops.registry import get_op
    from .symbol import Symbol

    prop = get_backend(node.get("backend"))
    children = [rebuild(c) for c in node.get("inputs", [])]
    chain = node.get("chain", [])
    fuse_fn = None
    if prop is not None:
        for _ops, fused_name, fn in prop.patterns:
            if fused_name == node.get("fused"):
                fuse_fn = fn

    def run_chain(vals):
        # slice each chain node's own leaf values (outermost..innermost)
        slices, off = [], 0
        for meta in chain:
            slices.append(vals[off:off + meta["arity"]])
            off += meta["arity"]
        acc = None
        for meta, own in zip(reversed(chain), reversed(slices)):
            args = own if acc is None else [acc] + list(own)
            op = get_op(meta["op"])
            attrs = _parse_attrs(meta["attrs"])
            f = op.fn if not attrs else functools.partial(op.fn, **attrs)
            acc = f(*args)
        return acc

    if fuse_fn is not None:
        def fn(env):
            vals = [c._fn(env) for c in children]
            return fuse_fn(*vals, attrs_list=[_parse_attrs(m["attrs"])
                                              for m in chain])
    else:
        def fn(env):
            return run_chain([c._fn(env) for c in children])

    inputs = []
    for c in children:
        inputs.extend(c._inputs)
    return Symbol(fn, inputs, name=node.get("fused", "_subgraph"),
                  json_repr=node)
