"""Random state management.

Reference: per-device ``RandGenerator`` (include/mxnet/random_generator.h —
Philox on GPU, per-thread mt19937 on CPU) seeded via ``mx.random.seed``.

TPU-native redesign: XLA's *stateless* threefry PRNG.  A module-level key is
split on every imperative draw (same user-facing contract: global seed,
reproducible streams).  Inside a hybridized trace, draws fold a step counter
into a traced base key, so the compiled computation takes one fresh key per
call — randomness stays inside the fused XLA program instead of a host RNG.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import _as_np_dtype

__all__ = ["seed", "take_key", "uniform", "normal", "randn", "randint",
           "gamma", "exponential", "poisson", "multinomial", "bernoulli",
           "shuffle", "trace_rng"]

_state = {"key": jax.random.PRNGKey(0)}
_trace_stack = []


class _TraceRNG:
    __slots__ = ("base_key", "counter")

    def __init__(self, base_key):
        self.base_key = base_key
        self.counter = 0


class trace_rng:
    """Context: route key draws through a traced base key (hybridize path)."""

    def __init__(self, base_key):
        self._rng = _TraceRNG(base_key)

    def __enter__(self):
        _trace_stack.append(self._rng)
        return self._rng

    def __exit__(self, *a):
        _trace_stack.pop()


def seed(seed_state, ctx="all"):
    """Set the global seed (reference python/mxnet/random.py)."""
    _state["key"] = jax.random.PRNGKey(int(seed_state))


def take_key():
    if _trace_stack:
        rng = _trace_stack[-1]
        rng.counter += 1
        return jax.random.fold_in(rng.base_key, rng.counter)
    _state["key"], sub = jax.random.split(_state["key"])
    return sub


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _wrap(data, ctx=None, out=None):
    from .ndarray.ndarray import NDArray

    if out is not None:
        out._data = data
        return out
    return NDArray(data, ctx=ctx)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None,
            out=None, **kw):
    dt = _as_np_dtype(dtype)
    data = jax.random.uniform(take_key(), _shape(shape), dtype=dt,
                              minval=low, maxval=high)
    return _wrap(data, ctx, out)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None,
           out=None, **kw):
    dt = _as_np_dtype(dtype)
    data = jax.random.normal(take_key(), _shape(shape), dtype=dt) * scale + loc
    return _wrap(data, ctx, out)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape, dtype=dtype, ctx=ctx)


def randint(low, high=None, shape=(1,), dtype="int32", ctx=None, out=None):
    if high is None:
        low, high = 0, low
    data = jax.random.randint(take_key(), _shape(shape), low, high,
                              dtype=_as_np_dtype(dtype))
    return _wrap(data, ctx, out)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None,
          out=None):
    from .ndarray.ndarray import NDArray

    a = alpha._data if isinstance(alpha, NDArray) else alpha
    b = beta._data if isinstance(beta, NDArray) else beta
    data = jax.random.gamma(take_key(), a, _shape(shape),
                            dtype=_as_np_dtype(dtype)) * b
    return _wrap(data, ctx, out)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, out=None):
    data = jax.random.exponential(take_key(), _shape(shape),
                                  dtype=_as_np_dtype(dtype)) * scale
    return _wrap(data, ctx, out)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None):
    data = jax.random.poisson(take_key(), lam, _shape(shape)).astype(
        _as_np_dtype(dtype))
    return _wrap(data, ctx, out)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    """Sample category indices from (batched) probability rows."""
    from .ndarray.ndarray import NDArray

    p = data._data if isinstance(data, NDArray) else data
    n = 1 if shape is None else shape
    logits = jnp.log(jnp.maximum(p, 1e-37))
    if p.ndim == 1:
        out_shape = _shape(n) if shape is not None else ()
        idx = jax.random.categorical(take_key(), logits, shape=out_shape)
    else:
        out_shape = (p.shape[0],) + (_shape(n) if shape is not None else ())
        idx = jax.random.categorical(take_key(), logits[:, None, :] if shape
                                     is not None else logits, axis=-1,
                                     shape=out_shape)
    return _wrap(idx.astype(_as_np_dtype(dtype)))


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, out=None):
    data = jax.random.bernoulli(take_key(), prob, _shape(shape)).astype(
        _as_np_dtype(dtype))
    return _wrap(data, ctx, out)


def shuffle(data, **kw):
    from .ndarray.ndarray import NDArray

    x = data._data if isinstance(data, NDArray) else data
    return _wrap(jax.random.permutation(take_key(), x, axis=0))
