"""Random state management.

Reference: per-device ``RandGenerator`` (include/mxnet/random_generator.h —
Philox on GPU, per-thread mt19937 on CPU) seeded via ``mx.random.seed``.

TPU-native redesign: XLA's *stateless* threefry PRNG.  A module-level key is
split on every imperative draw (same user-facing contract: global seed,
reproducible streams).  Inside a hybridized trace, draws fold a step counter
into a traced base key, so the compiled computation takes one fresh key per
call — randomness stays inside the fused XLA program instead of a host RNG.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from .base import MXNetError, _as_np_dtype

__all__ = ["seed", "take_key", "uniform", "normal", "randn", "randint",
           "gamma", "exponential", "poisson", "multinomial", "bernoulli",
           "shuffle", "trace_rng", "KeyLog", "logged_keys", "laplace",
           "pareto", "weibull", "rayleigh", "gumbel", "logistic", "choice",
           "categorical"]

# Key is created lazily: jax.random.PRNGKey executes a device computation,
# and module scope here runs during `import mxnet_tpu` — a backend touch at
# import time means a wedged TPU tunnel hangs the import (VERDICT r3).
_state = {"key": None, "seed": 0}
_trace_stack = []


class _TraceRNG:
    __slots__ = ("base_key", "counter")

    def __init__(self, base_key):
        self.base_key = base_key
        self.counter = 0


class KeyLog:
    """Per-recorded-op key journal (ADVICE r3: create_graph replay).

    The first execution of a recorded op's forward (inside invoke's
    jax.vjp) RECORDS every key it draws; any re-execution of the same
    forward — the create_graph backward rebuilds the vjp by replaying the
    stored fn — gets the SAME keys back in draw order, so stochastic ops
    (Dropout, rrelu) use the mask the real forward used.  This is the eager
    counterpart of gluon/block.py pinning ``_rng`` for hybridized blocks.
    """

    __slots__ = ("keys", "finalized", "pos")

    def __init__(self):
        self.keys = []
        self.finalized = False
        self.pos = 0


_keylog_stack = []


@contextlib.contextmanager
def logged_keys(log):
    """Route take_key() through ``log``: record on first entry, replay after."""
    _keylog_stack.append(log)
    log.pos = 0
    try:
        yield
    finally:
        _keylog_stack.pop()
        log.finalized = True


class trace_rng:
    """Context: route key draws through a traced base key (hybridize path)."""

    def __init__(self, base_key):
        self._rng = _TraceRNG(base_key)

    def __enter__(self):
        _trace_stack.append(self._rng)
        return self._rng

    def __exit__(self, *a):
        _trace_stack.pop()


def seed(seed_state, ctx="all"):
    """Set the global seed (reference python/mxnet/random.py)."""
    _state["seed"] = int(seed_state)
    _state["key"] = jax.random.PRNGKey(int(seed_state))


def take_key():
    if _trace_stack:
        # hybridize trace: keys are traced values derived from the program's
        # base-key argument; replay identity is the compiled program's job
        rng = _trace_stack[-1]
        rng.counter += 1
        return jax.random.fold_in(rng.base_key, rng.counter)
    if _keylog_stack:
        log = _keylog_stack[-1]
        if log.finalized:  # replay: hand back the recorded stream
            if log.pos >= len(log.keys):
                raise MXNetError(
                    "RNG replay mismatch: recorded op drew %d key(s) at "
                    "record time but its replayed forward asked for more "
                    "— the op's control flow must not depend on state that "
                    "changed since recording" % len(log.keys))
            key = log.keys[log.pos]
            log.pos += 1
            return key
        key = _fresh_key()
        log.keys.append(key)
        return key
    return _fresh_key()


def _fresh_key():
    if _state["key"] is None:
        _state["key"] = jax.random.PRNGKey(_state["seed"])
    _state["key"], sub = jax.random.split(_state["key"])
    return sub


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _wrap(data, ctx=None, out=None):
    from .ndarray.ndarray import NDArray

    if out is not None:
        out._data = data
        return out
    return NDArray(data, ctx=ctx)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None,
            out=None, **kw):
    dt = _as_np_dtype(dtype)
    data = jax.random.uniform(take_key(), _shape(shape), dtype=dt,
                              minval=low, maxval=high)
    return _wrap(data, ctx, out)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None,
           out=None, **kw):
    dt = _as_np_dtype(dtype)
    data = jax.random.normal(take_key(), _shape(shape), dtype=dt) * scale + loc
    return _wrap(data, ctx, out)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape, dtype=dtype, ctx=ctx)


def randint(low, high=None, shape=(1,), dtype="int32", ctx=None, out=None):
    if high is None:
        low, high = 0, low
    data = jax.random.randint(take_key(), _shape(shape), low, high,
                              dtype=_as_np_dtype(dtype))
    return _wrap(data, ctx, out)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None,
          out=None):
    from .ndarray.ndarray import NDArray

    a = alpha._data if isinstance(alpha, NDArray) else alpha
    b = beta._data if isinstance(beta, NDArray) else beta
    data = jax.random.gamma(take_key(), a, _shape(shape),
                            dtype=_as_np_dtype(dtype)) * b
    return _wrap(data, ctx, out)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, out=None):
    data = jax.random.exponential(take_key(), _shape(shape),
                                  dtype=_as_np_dtype(dtype)) * scale
    return _wrap(data, ctx, out)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None):
    data = jax.random.poisson(take_key(), lam, _shape(shape)).astype(
        _as_np_dtype(dtype))
    return _wrap(data, ctx, out)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    """Sample category indices from (batched) probability rows."""
    from .ndarray.ndarray import NDArray

    p = data._data if isinstance(data, NDArray) else data
    n = 1 if shape is None else shape
    logits = jnp.log(jnp.maximum(p, 1e-37))
    if p.ndim == 1:
        out_shape = _shape(n) if shape is not None else ()
        idx = jax.random.categorical(take_key(), logits, shape=out_shape)
    else:
        out_shape = (p.shape[0],) + (_shape(n) if shape is not None else ())
        idx = jax.random.categorical(take_key(), logits[:, None, :] if shape
                                     is not None else logits, axis=-1,
                                     shape=out_shape)
    return _wrap(idx.astype(_as_np_dtype(dtype)))


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, out=None):
    data = jax.random.bernoulli(take_key(), prob, _shape(shape)).astype(
        _as_np_dtype(dtype))
    return _wrap(data, ctx, out)


def shuffle(data, **kw):
    from .ndarray.ndarray import NDArray

    x = data._data if isinstance(data, NDArray) else data
    return _wrap(jax.random.permutation(take_key(), x, axis=0))


# ---- distribution tail (reference np_random ops: _npi_laplace/_npi_pareto/
# _npi_weibull/_npi_rayleigh/_npi_gumbel/_npi_logistic/_npi_choice) --------
def laplace(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None,
            out=None):
    data = jax.random.laplace(take_key(), _shape(shape),
                              dtype=_as_np_dtype(dtype)) * scale + loc
    return _wrap(data, ctx, out)


def pareto(a=1.0, shape=None, dtype="float32", ctx=None, out=None):
    """Lomax-style pareto (np.random.pareto: (1-U)^{-1/a} - 1)."""
    u = jax.random.uniform(take_key(), _shape(shape),
                           dtype=_as_np_dtype(dtype))
    return _wrap(jnp.expm1(-jnp.log1p(-u) / a), ctx, out)


def weibull(a=1.0, shape=None, dtype="float32", ctx=None, out=None):
    u = jax.random.uniform(take_key(), _shape(shape),
                           dtype=_as_np_dtype(dtype))
    return _wrap(jnp.power(-jnp.log1p(-u), 1.0 / a), ctx, out)


def rayleigh(scale=1.0, shape=None, dtype="float32", ctx=None, out=None):
    u = jax.random.uniform(take_key(), _shape(shape),
                           dtype=_as_np_dtype(dtype))
    return _wrap(scale * jnp.sqrt(-2.0 * jnp.log1p(-u)), ctx, out)


def gumbel(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None,
           out=None):
    data = jax.random.gumbel(take_key(), _shape(shape),
                             dtype=_as_np_dtype(dtype)) * scale + loc
    return _wrap(data, ctx, out)


def logistic(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None,
             out=None):
    data = jax.random.logistic(take_key(), _shape(shape),
                               dtype=_as_np_dtype(dtype)) * scale + loc
    return _wrap(data, ctx, out)


def choice(a, size=None, replace=True, p=None, ctx=None, out=None):
    """np.random.choice (reference _npi_choice)."""
    from .ndarray.ndarray import NDArray

    arr = a._data if isinstance(a, NDArray) else a
    if isinstance(arr, int):
        arr = jnp.arange(arr)
    pv = p._data if isinstance(p, NDArray) else p
    data = jax.random.choice(take_key(), arr, _shape(size),
                             replace=replace, p=pv)
    return _wrap(data, ctx, out)


def categorical(logits, shape=None, ctx=None, out=None):
    """npx.random.categorical (reference _npx__random_categorical)."""
    from .ndarray.ndarray import NDArray

    lg = logits._data if isinstance(logits, NDArray) else logits
    out_shape = None if shape is None else _shape(shape)
    data = jax.random.categorical(take_key(), lg, axis=-1, shape=out_shape)
    return _wrap(data, ctx, out)
