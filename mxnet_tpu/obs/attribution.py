"""Step-time attribution: where did each training step's wall time go?

``observe_step`` is called once per step by the step engines with the
phase durations they already bracket in trace spans — the captured
path passes slots / stage / dispatch / writeback / publish (the
``train_step`` child spans), the stitched path passes forward /
backward / step.  Attribution adds the **data-wait** share itself
from the ``dataloader_batch_wait_seconds`` histogram delta between
steps (loader wait happens *outside* the step span, so no engine can
measure it), normalizes everything into shares of the step total, and
estimates **MFU** from the captured program's FLOP count
(``step/capture.py`` stores XLA's ``cost_analysis()`` flops on each
program; ``StepProgram.report()`` surfaces it) against the chip's
peak (``MXNET_OBS_PEAK_TFLOPS`` override, else a device-kind table;
unknown kinds — CPU drills — report ``mfu: null`` honestly rather
than inventing a peak).

Each record is one compact JSON line appended to
``MXNET_OBS_ATTRIBUTION`` (schema below) — the per-step feature
stream for a learned performance model over real traces:

    {"ver": 1, "time": ..., "step": n, "path": "captured",
     "total_s": ..., "parts_s": {...}, "shares": {..., "other": r},
     "flops": ..., "mfu": ...}

``shares`` always sums to <= 1 (+eps): parts are clamped to the step
total and the residual lands in ``other``.  Fail-soft like every obs
hook: a full disk or bad path counts nothing and never raises into
the step."""
from __future__ import annotations

import json
import threading
import time

from .. import telemetry as _tel
from ..base import get_env
from . import core

__all__ = ["observe_step", "summary", "reset", "peak_flops",
           "stream_path", "SCHEMA_KEYS"]

SCHEMA_KEYS = ("ver", "time", "step", "path", "total_s", "parts_s",
               "shares", "flops", "mfu")

_LOCK = threading.Lock()
_STREAM = [None, None]   # (path, handle)
_COUNT = [0]
_LAST = [None]
_WAIT_SUM = [None]       # last seen dataloader wait-histogram sum


def stream_path():
    """JSONL destination (``MXNET_OBS_ATTRIBUTION``), or None."""
    return get_env("MXNET_OBS_ATTRIBUTION", str, None)


def peak_flops():
    """Per-chip peak FLOP/s for the MFU estimate:
    ``MXNET_OBS_PEAK_TFLOPS`` when set, else a bf16 device-kind
    table; None for unknown kinds (CPU) — an MFU against an invented
    peak would be worse than no MFU."""
    override = get_env("MXNET_OBS_PEAK_TFLOPS", float, None)
    if override:
        return float(override) * 1e12
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 - backend down
        return None
    for pat, tflops in (("v5 lite", 197.0), ("v5e", 197.0),
                        ("v5lite", 197.0), ("v4", 275.0),
                        ("v5p", 459.0), ("v5", 459.0), ("v6", 918.0)):
        if pat in kind:
            return tflops * 1e12
    return None


def _data_wait_delta():
    """Loader wait accumulated since the previous step (seconds),
    from the dataloader_batch_wait_seconds histogram sum."""
    m = _tel.get_metric("dataloader_batch_wait_seconds")
    if m is None or m.kind != "histogram":
        return 0.0
    _count, total, _cum = _tel._merged_read(m)
    prev, _WAIT_SUM[0] = _WAIT_SUM[0], total
    if prev is None:
        return 0.0
    return max(0.0, total - prev)


def _stream_write(rec):
    path = stream_path()
    if not path:
        return
    if _STREAM[0] != path:
        if _STREAM[1] is not None:
            _STREAM[1].close()
        _STREAM[0], _STREAM[1] = path, open(path, "a")
    _STREAM[1].write(json.dumps(rec) + "\n")
    _STREAM[1].flush()


def observe_step(step, total_s, parts=None, flops=None,
                 path="captured"):
    """Record one step's attribution.  ``parts`` maps phase name ->
    seconds (the engine's span-bracketed durations); data-wait is
    added here; the un-attributed residual lands in ``other``.
    Returns the record, or None when obs is off / the step total is
    unusable.  Never raises."""
    if not core.ENABLED:
        return None
    try:
        total_s = float(total_s)
        if total_s <= 0:
            return None
        parts_s = {k: max(0.0, float(v))
                   for k, v in (parts or {}).items()}
        wait = _data_wait_delta()
        if wait > 0:
            parts_s["data_wait"] = wait
        shares, used = {}, 0.0
        for k, v in parts_s.items():
            s = min(1.0, v / total_s)
            shares[k] = round(s, 6)
            used += s
        shares["other"] = round(max(0.0, 1.0 - used), 6)
        flops = None if flops is None else float(flops)
        peak = peak_flops() if flops else None
        mfu = None if not flops or not peak \
            else round(flops / total_s / peak, 6)
        rec = {"ver": 1, "time": time.time(), "step": int(step),
               "path": str(path), "total_s": round(total_s, 6),
               "parts_s": {k: round(v, 6) for k, v in parts_s.items()},
               "shares": shares, "flops": flops, "mfu": mfu}
        with _LOCK:
            _COUNT[0] += 1
            _LAST[0] = rec
            _stream_write(rec)
        if _tel.ENABLED:
            _tel.OBS_ATTRIB_RECORDS.inc()
        return rec
    except Exception:  # noqa: BLE001 - never raise into the step
        return None


def summary():
    """{records, last} for diagnose and bench rows."""
    with _LOCK:
        return {"records": _COUNT[0], "last": _LAST[0]}


def reset():
    """Tests / between bench rows: close the stream, zero the state."""
    with _LOCK:
        if _STREAM[1] is not None:
            try:
                _STREAM[1].close()
            except OSError:
                pass
        _STREAM[0] = _STREAM[1] = None
        _COUNT[0] = 0
        _LAST[0] = None
        _WAIT_SUM[0] = None
