"""mx.obs core — the enable flag, per-rank step cadence, and the
KV publisher that rides the membership heartbeat.

The publisher is deliberately dumb transport: one JSON record per
(generation, rank) under ``obs/<gen>/<rank>`` in the SAME KV backend
mx.dist membership already heartbeats through (FileKV / CoordKV /
MemKV).  Records are overwritten in place — the fleet view only ever
wants the latest — and carry their own wall clock, so staleness is
judged exactly like membership judges liveness (no mtime games).

Publish cadence piggybacks on the membership heartbeat thread
(``Membership.on_beat``) rate-limited to ``MXNET_OBS_PUBLISH_SECONDS``
— obs adds ZERO threads of its own.  A failing publish (lost shared
FS, dead coordinator) counts ``obs_publish_failures_total`` and
degrades the fleet to local-only snapshots; it never raises into the
heartbeat thread or the training loop.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

from .. import telemetry as _tel
from ..base import get_env

_LOG = logging.getLogger("mxnet_tpu.obs")

__all__ = ["ENABLED", "enable", "disable", "is_enabled", "note_step",
           "step_stats", "local_payload", "Publisher", "attach",
           "detach", "publisher", "obs_key"]

ENABLED = get_env("MXNET_OBS", bool, False)


def enable():
    """Arm the obs plane for this process (equivalent MXNET_OBS=1)."""
    global ENABLED
    ENABLED = True


def disable():
    global ENABLED
    ENABLED = False


def is_enabled():
    return ENABLED


# ---------------------------------------------------------------------------
# step cadence: the per-rank series the straggler detector feeds on
# ---------------------------------------------------------------------------

_STEP_LOCK = threading.Lock()
_STEP_WINDOW = deque(maxlen=256)
_STEP_COUNT = 0


def note_step(dur):
    """Record one training-step wall duration (seconds).  Called from
    ``Trainer.step`` and the captured-step dispatch — disabled cost is
    one flag check; enabled cost is a deque append + one histogram
    observe.  Never raises."""
    global _STEP_COUNT
    if not ENABLED:
        return
    try:
        dur = float(dur)
        with _STEP_LOCK:
            _STEP_WINDOW.append(dur)
            _STEP_COUNT += 1
        if _tel.ENABLED:
            _tel.OBS_STEP_SECONDS.observe(dur)
        pub = _PUBLISHER[0]
        if pub is not None:
            pub.maybe_publish()
    except Exception:  # noqa: BLE001 - obs must never raise into step()
        pass


def step_stats():
    """{steps_observed, step_p50_s, step_last_s} over the recent
    window (the straggler detector's per-rank feed)."""
    with _STEP_LOCK:
        window = list(_STEP_WINDOW)
        n = _STEP_COUNT
    if not window:
        return {"steps_observed": n, "step_p50_s": None,
                "step_last_s": None}
    ordered = sorted(window)
    return {"steps_observed": n,
            "step_p50_s": ordered[len(ordered) // 2],
            "step_last_s": window[-1]}


def reset_steps():
    """Tests / between bench rows: forget the cadence window."""
    global _STEP_COUNT
    with _STEP_LOCK:
        _STEP_WINDOW.clear()
        _STEP_COUNT = 0


# ---------------------------------------------------------------------------
# the published payload
# ---------------------------------------------------------------------------

def _monitor_health():
    """Compact mx.monitor health for the payload, or None when the
    monitor plane is off (fail-soft: obs must publish even when the
    numerics plane is sick)."""
    try:
        from .. import monitor

        if not monitor.is_enabled():
            return None
        return monitor.core.health()
    except Exception:  # noqa: BLE001
        return None


def local_payload(rank=None, step=None):
    """This process's publishable observability record: telemetry
    snapshot + step cadence + collective-wait quantiles + monitor
    health.  The unit the fleet view merges."""
    cadence = step_stats()
    coll = _tel.histogram_quantiles("collective_seconds", qs=(0.5,))
    return {
        "rank": int(rank or 0),
        "pid": os.getpid(),
        "wall": time.time(),
        "step": step,
        "steps_observed": cadence["steps_observed"],
        "step_p50_s": cadence["step_p50_s"],
        "step_last_s": cadence["step_last_s"],
        "collective_wait_p50_s": coll.get(0.5),
        "monitor": _monitor_health(),
        "metrics": _tel.snapshot(),
    }


# ---------------------------------------------------------------------------
# publisher
# ---------------------------------------------------------------------------

def obs_key(generation, rank):
    return "obs/%d/%d" % (int(generation), int(rank))


class Publisher:
    """Publishes this rank's payload into the membership KV, at most
    every ``MXNET_OBS_PUBLISH_SECONDS`` (heartbeat-piggybacked)."""

    def __init__(self, membership, interval=None):
        self.membership = membership
        self.interval = get_env(
            "MXNET_OBS_PUBLISH_SECONDS", float, 5.0) \
            if interval is None else float(interval)
        self._last = None
        self._lock = threading.Lock()
        self.publishes = 0
        self.failures = 0

    def maybe_publish(self):
        """Rate-limited publish; the heartbeat/on_beat entry point."""
        if not ENABLED:
            return False
        now = time.monotonic()
        with self._lock:
            if self._last is not None and \
                    now - self._last < self.interval:
                return False
            self._last = now
        return self.publish()

    def publish(self):
        """Publish NOW (drills and step boundaries force it).  Returns
        True on success; a failing KV counts
        ``obs_publish_failures_total`` and degrades to local-only —
        never raises."""
        if not ENABLED:
            return False
        m = self.membership
        if m is None or m.generation is None:
            return False
        try:
            payload = local_payload(rank=m.rank,
                                    step=getattr(m, "_step", None))
            m.kv.set(obs_key(m.generation, m.rank), payload)
            self.publishes += 1
            if _tel.ENABLED:
                _tel.OBS_PUBLISHES.inc()
            return True
        except Exception as exc:  # noqa: BLE001 - degrade, never raise
            self.failures += 1
            if _tel.ENABLED:
                _tel.OBS_PUBLISH_FAILURES.inc()
            _LOG.warning("obs publish failed (local-only until the KV "
                         "recovers): %s", exc)
            return False


# module-global publisher: one per process, like the monitor publisher
_PUBLISHER = [None]
_BEAT_CB = [None]


def attach(membership, interval=None):
    """Wire the obs publisher to a joined :class:`~mxnet_tpu.dist.
    Membership`: payloads ride the heartbeat thread from here on
    (plus a forced publish per ``note_step`` window).  Returns the
    :class:`Publisher`.  Re-attaching replaces the previous wiring."""
    detach()
    pub = Publisher(membership, interval=interval)
    _PUBLISHER[0] = pub

    def _on_beat(mem):
        if mem is pub.membership:
            pub.maybe_publish()

    try:
        from ..dist import membership as _mm

        _mm.on_beat(_on_beat)
        _BEAT_CB[0] = _on_beat
    except Exception:  # noqa: BLE001 - publisher still usable directly
        _BEAT_CB[0] = None
    pub.maybe_publish()
    return pub


def detach():
    """Unhook the publisher (tests / world teardown)."""
    cb = _BEAT_CB[0]
    if cb is not None:
        try:
            from ..dist import membership as _mm

            _mm.remove_beat_listener(cb)
        except Exception:  # noqa: BLE001
            pass
    _BEAT_CB[0] = None
    _PUBLISHER[0] = None


def publisher():
    """The attached :class:`Publisher`, or None."""
    return _PUBLISHER[0]
