"""mx.obs — the fleet-wide observability plane.

The fourth observability layer (README "Observability"): ``telemetry``
aggregates one process, ``trace`` records one process's timeline,
``monitor`` watches one process's numerics — ``obs`` is the first
layer that sees the *fleet*.  Four coupled pieces, all riding existing
machinery rather than inventing transport:

- **cross-rank aggregation** (``core.attach`` + :class:`FleetView`):
  every process periodically publishes its ``telemetry.snapshot()``
  (plus step cadence and monitor health) into the mx.dist membership
  KV, piggybacked on the heartbeat thread; any rank merges the
  per-rank payloads into one pod-level snapshot (counter sums,
  histogram bucket merges, a per-rank table) — exported as Prometheus
  text with a ``rank`` label, ``tools/diagnose.py --fleet``, and the
  ``/fleetz`` endpoint on ``serve.Server``;
- **straggler detection** (``FleetView.check_stragglers``): a rank
  whose step p50 drifts past ``MXNET_OBS_STRAGGLER_FACTOR`` x the
  fleet median fires one rate-limited flight-record dump
  (``reason="straggler"``) and an ``obs_stragglers_total{rank}``
  count — the classic slow-host/slow-chip failure, caught from
  metrics instead of a human eyeballing per-rank logs;
- **SLO engine** (``slo_engine.py``): declarative objectives over
  live telemetry (``obs.slo("serve_p99", histogram=
  "serve_request_seconds", q=0.99, target=0.2)``) evaluated with
  multi-window burn rates (fast/slow windows, the standard SRE
  formulation); states OK/WARN/PAGE surface in ``/statz``,
  ``/healthz`` (degraded), telemetry gauges, and the periodic log
  line — the load/health signal contract a fleet router consumes;
- **step-time attribution** (``attribution.py``): a rolling per-step
  breakdown (data-wait / dispatch / writeback / publish shares from
  the existing ``train_step`` child phases, plus an MFU estimate from
  captured-program FLOP accounting) written as a compact JSONL
  stream (``MXNET_OBS_ATTRIBUTION``) — the feature source for a
  learned performance model over real traces.

Everything is fail-soft and cheap: with ``MXNET_OBS=0`` (the default)
every hook costs one cached flag check; a dead/partitioned KV degrades
to local-only snapshots with ``obs_publish_failures_total`` counted;
no obs failure can ever raise into ``Trainer.step`` or the serve
dispatch loop.  Enable with ``MXNET_OBS=1`` or ``mx.obs.enable()``.

Env knobs: ``MXNET_OBS``, ``MXNET_OBS_PUBLISH_SECONDS``,
``MXNET_OBS_STRAGGLER_FACTOR``, ``MXNET_OBS_SLO_FAST_SECONDS`` /
``_SLOW_SECONDS``, ``MXNET_OBS_ATTRIBUTION``,
``MXNET_OBS_PEAK_TFLOPS``, ``MXNET_OBS_REGRESSION_PCT``
(``tools/bench_gate.py``).
"""
from __future__ import annotations

from . import attribution, core, fleet, slo_engine
from .core import (attach, detach, disable, enable, is_enabled,
                   local_payload, note_step, publisher)
from .fleet import FleetView, fleet_summary, fleetz, merge_metrics
from .slo_engine import slo  # obs.slo(...) registers an objective

__all__ = [
    "core", "fleet", "slo_engine", "attribution",
    "enable", "disable", "is_enabled",
    "attach", "detach", "publisher", "note_step", "local_payload",
    "FleetView", "fleetz", "fleet_summary", "merge_metrics", "slo",
]


def __getattr__(name):
    # obs.ENABLED mirrors core.ENABLED (a mutable module flag —
    # re-exporting the value at import would freeze it)
    if name == "ENABLED":
        return core.ENABLED
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
