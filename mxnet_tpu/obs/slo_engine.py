"""Declarative SLOs over live telemetry, with multi-window burn rates.

An objective binds a target to a metric family already being
collected — no second measurement path:

- **latency**: ``obs.slo("serve_p99", histogram="serve_request_seconds",
  q=0.99, target=0.2)`` — "99% of requests complete under 200 ms".
  "Bad" events are observations above ``target``, counted from the
  histogram's cumulative buckets (linear interpolation inside the
  covering bucket; observations in the +Inf overflow bucket count as
  bad — the buckets cannot prove them good).
- **error rate**: ``obs.slo("serve_errors", counter=
  "serve_requests_total", bad={"result": "error"}, objective=0.999)``
  — "99.9% of requests succeed".

Evaluation follows the standard SRE multi-window burn-rate
formulation: the error-budget burn rate over a window is
``(bad/total over the window) / (1 - objective)`` — burn 1.0 consumes
exactly the budget over the SLO period; burn 14.4 exhausts a 30-day
budget in 2 days.  Two windows guard against both noise and slow
leaks: **PAGE** when BOTH the fast (``MXNET_OBS_SLO_FAST_SECONDS``,
default 5 m) and slow (``MXNET_OBS_SLO_SLOW_SECONDS``, default 1 h)
windows burn >= ``page_burn`` (default 14.4); **WARN** when both
burn >= ``warn_burn`` (default 6.0); else **OK**.  A quiet window
(no traffic) burns 0 — absence of traffic is not an outage here.

States surface as telemetry gauges (``obs_slo_state`` 0/1/2,
``obs_slo_burn_rate{slo,window}``), in ``serve.Server`` ``/statz`` +
``/healthz`` (degraded), and in the periodic telemetry log line.
Everything is windowed from cumulative counters sampled at evaluate
time — the engine keeps a bounded series per objective and never
touches a hot path.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque

from .. import telemetry as _tel
from ..base import get_env

__all__ = ["SLObjective", "slo", "remove", "clear", "registered",
           "evaluate", "states", "worst", "STATE_LEVELS"]

STATE_LEVELS = {"OK": 0, "WARN": 1, "PAGE": 2}

_LOCK = threading.Lock()
_REGISTRY = {}


def _windows():
    return (get_env("MXNET_OBS_SLO_FAST_SECONDS", float, 300.0),
            get_env("MXNET_OBS_SLO_SLOW_SECONDS", float, 3600.0))


def _le_count(cum, bound):
    """Observations <= ``bound`` from cumulative buckets [(ub, c)]
    (linear interpolation inside the covering bucket).  Overflow
    (+Inf) observations are NOT counted below any finite bound — the
    buckets cannot prove them good, so they count against the SLO."""
    prev_ub, prev_c = 0.0, 0.0
    for ub, c in cum:
        if ub == float("inf"):
            return prev_c
        if bound < ub:
            width = ub - prev_ub
            if width <= 0:
                return float(c)
            frac = max(0.0, (bound - prev_ub)) / width
            return prev_c + (c - prev_c) * frac
        prev_ub, prev_c = ub, float(c)
    return prev_c


class SLObjective:
    """One declarative objective + its bounded cumulative series."""

    def __init__(self, name, histogram=None, q=0.99, target=None,
                 counter=None, bad=None, objective=None,
                 warn_burn=6.0, page_burn=14.4, labels=None):
        if (histogram is None) == (counter is None):
            raise ValueError(
                "slo %r: exactly one of histogram=/counter= required"
                % name)
        self.name = str(name)
        self.histogram = histogram
        self.counter = counter
        self.bad_labels = dict(bad or {})
        self.labels = dict(labels or {}) or None
        self.q = float(q)
        self.target = None if target is None else float(target)
        if histogram is not None:
            if self.target is None:
                raise ValueError("slo %r: latency objective needs "
                                 "target= (seconds)" % name)
            self.objective = self.q
        else:
            self.objective = 0.999 if objective is None \
                else float(objective)
        self.budget = max(1e-9, 1.0 - self.objective)
        self.warn_burn = float(warn_burn)
        self.page_burn = float(page_burn)
        self._series = deque()  # (t, bad, total) cumulative samples
        self._lock = threading.Lock()
        self.state = "OK"

    # -- cumulative (bad, total) from live telemetry -------------------------
    def _read(self):
        if self.histogram is not None:
            m = _tel.get_metric(self.histogram)
            if m is None or m.kind != "histogram":
                return 0.0, 0.0
            count, _total, cum = _tel._merged_read(m, match=self.labels)
            if not count:
                return 0.0, 0.0
            good = _le_count(cum, self.target)
            return max(0.0, count - good), float(count)
        total = _tel.value(self.counter)
        bad = _tel.value(self.counter, labels=self.bad_labels)
        return float(bad), float(total)

    def _burn(self, now, window):
        """Error-budget burn rate over the trailing ``window``: the
        windowed bad fraction divided by the budget fraction."""
        with self._lock:
            series = list(self._series)
        if len(series) < 2:
            return 0.0
        latest = series[-1]
        base = series[0]
        for s in series:
            if s[0] <= now - window:
                base = s
            else:
                break
        dbad = latest[1] - base[1]
        dtotal = latest[2] - base[2]
        if dtotal <= 0 or dbad <= 0:
            return 0.0
        return (dbad / dtotal) / self.budget

    def evaluate(self, now=None):
        """Sample the cumulative counters, prune the series, compute
        fast/slow burn rates, and resolve the state."""
        now = time.monotonic() if now is None else now
        fast_w, slow_w = _windows()
        bad, total = self._read()
        with self._lock:
            self._series.append((now, bad, total))
            horizon = now - (slow_w * 1.5 + 60.0)
            while len(self._series) > 2 and self._series[1][0] < horizon:
                self._series.popleft()
        fast = self._burn(now, fast_w)
        slow = self._burn(now, slow_w)
        if fast >= self.page_burn and slow >= self.page_burn:
            self.state = "PAGE"
        elif fast >= self.warn_burn and slow >= self.warn_burn:
            self.state = "WARN"
        else:
            self.state = "OK"
        return {"state": self.state,
                "burn_fast": round(fast, 4),
                "burn_slow": round(slow, 4),
                "bad": bad if not math.isnan(bad) else 0.0,
                "total": total,
                "objective": self.objective,
                "target_s": self.target,
                "windows_s": [fast_w, slow_w]}


# ---------------------------------------------------------------------------
# registry + module API
# ---------------------------------------------------------------------------

def slo(name, histogram=None, q=0.99, target=None, counter=None,
        bad=None, objective=None, warn_burn=6.0, page_burn=14.4,
        labels=None):
    """Register (or replace) a declarative objective; returns it.
    See the module docstring for the two forms.  ``labels=`` scopes a
    histogram objective to matching children only (e.g. per-tenant
    TTFT: ``labels={"tenant": "acme"}``)."""
    obj = SLObjective(name, histogram=histogram, q=q, target=target,
                      counter=counter, bad=bad, objective=objective,
                      warn_burn=warn_burn, page_burn=page_burn,
                      labels=labels)
    with _LOCK:
        _REGISTRY[obj.name] = obj
    return obj


def remove(name):
    with _LOCK:
        _REGISTRY.pop(str(name), None)


def clear():
    with _LOCK:
        _REGISTRY.clear()


def registered():
    """Registered objective names (evaluation order)."""
    with _LOCK:
        return list(_REGISTRY)


def evaluate(now=None):
    """Evaluate every objective: {name: {state, burn_fast, burn_slow,
    ...}}; refreshes the ``obs_slo_state`` / ``obs_slo_burn_rate``
    gauges.  Fail-soft per objective — one sick objective cannot take
    the rest (or the caller) down."""
    with _LOCK:
        objs = list(_REGISTRY.values())
    out = {}
    for obj in objs:
        try:
            res = obj.evaluate(now=now)
        except Exception as exc:  # noqa: BLE001
            res = {"state": "OK", "error": str(exc)[:200],
                   "burn_fast": 0.0, "burn_slow": 0.0}
        out[obj.name] = res
        if _tel.ENABLED:
            _tel.OBS_SLO_STATE.labels(slo=obj.name).set(
                STATE_LEVELS.get(res["state"], 0))
            _tel.OBS_SLO_BURN.labels(slo=obj.name, window="fast").set(
                res.get("burn_fast", 0.0))
            _tel.OBS_SLO_BURN.labels(slo=obj.name, window="slow").set(
                res.get("burn_slow", 0.0))
    return out


def states(now=None):
    """Condensed {name: state} (evaluates first)."""
    return {k: v["state"] for k, v in evaluate(now=now).items()}


def worst(now=None):
    """The worst current state across objectives ("OK" when none)."""
    best = "OK"
    for st in states(now=now).values():
        if STATE_LEVELS.get(st, 0) > STATE_LEVELS[best]:
            best = st
    return best
