"""Fleet view: merge per-rank obs payloads into one pod-level picture.

:class:`FleetView` reads every ``obs/<gen>/<rank>`` record of the
current generation from the membership KV and merges the telemetry
snapshots: counters/gauges sum across ranks, histograms merge bucket
counts (every rank registers the same families with the same edges —
they are code constants), and the per-rank header fields become the
rank table diagnose/``/fleetz`` render.

A dead or partitioned KV (or a world that never published) degrades
to a LOCAL-ONLY view — this process's own payload under its own rank
— flagged ``local_only`` so a dashboard can tell "fleet of one" from
"fleet unreachable".

Straggler detection lives here because it is a *fleet* property: a
rank whose step p50 exceeds ``MXNET_OBS_STRAGGLER_FACTOR`` x the
median p50 of its peers fires one ``obs_stragglers_total{rank}`` count and
one rate-limited flight-record dump (``reason="straggler"``, the PR 6
anomaly path).  A flagged rank re-fires only after recovering below
the threshold first — repeated checks of a persistently slow rank
produce exactly one event per episode.
"""
from __future__ import annotations

import statistics
import threading
import time

from .. import telemetry as _tel
from ..base import get_env
from . import core

__all__ = ["FleetView", "merge_metrics", "fleetz", "fleet_summary"]


def merge_metrics(snapshots):
    """Merge per-rank ``telemetry.snapshot()`` dicts into one
    pod-level snapshot of the same shape: counter/gauge samples sum
    per label set, histogram samples sum count/sum/bucket counts."""
    merged = {}
    for snap in snapshots:
        for name, fam in (snap or {}).items():
            dst = merged.setdefault(
                name, {"type": fam.get("type"),
                       "help": fam.get("help", ""), "samples": {}})
            for s in fam.get("samples", ()):
                key = tuple(sorted((s.get("labels") or {}).items()))
                if fam.get("type") == "histogram":
                    d = dst["samples"].get(key)
                    if d is None:
                        dst["samples"][key] = {
                            "labels": dict(s.get("labels") or {}),
                            "count": s.get("count", 0),
                            "sum": s.get("sum", 0.0),
                            "buckets": dict(s.get("buckets") or {})}
                    else:
                        d["count"] += s.get("count", 0)
                        d["sum"] += s.get("sum", 0.0)
                        for le, c in (s.get("buckets") or {}).items():
                            d["buckets"][le] = d["buckets"].get(le, 0) + c
                else:
                    d = dst["samples"].get(key)
                    if d is None:
                        dst["samples"][key] = {
                            "labels": dict(s.get("labels") or {}),
                            "value": s.get("value", 0)}
                    else:
                        d["value"] += s.get("value", 0)
    return {name: {"type": fam["type"], "help": fam["help"],
                   "samples": list(fam["samples"].values())}
            for name, fam in merged.items()}


# ranks already flagged as stragglers (cleared on recovery), shared
# across FleetView instances so periodic re-checks fire once/episode
_FLAG_LOCK = threading.Lock()
_FLAGGED = set()


def _reset_flags():
    with _FLAG_LOCK:
        _FLAGGED.clear()


class FleetView:
    """One rank's merged view of every rank's published payload.

    Construct from a joined ``Membership`` (the normal path) or a raw
    ``(kv, generation, rank)`` triple (tests, offline snapshots)."""

    def __init__(self, membership=None, kv=None, generation=None,
                 rank=None):
        if membership is not None:
            kv = membership.kv
            generation = membership.generation
            rank = membership.rank
        self.kv = kv
        self.generation = generation
        self.rank = int(rank or 0)
        self.local_only = False
        self._payloads = {}

    # -- collection ----------------------------------------------------------
    def refresh(self):
        """Re-read every rank's payload.  An unreachable KV (or an
        empty prefix) degrades to this process's OWN payload — the
        fleet view never raises and never goes blank."""
        payloads = {}
        if self.kv is not None and self.generation is not None:
            try:
                prefix = "obs/%d" % int(self.generation)
                for name in self.kv.list(prefix):
                    try:
                        r = int(name)
                    except ValueError:
                        continue
                    rec = self.kv.get(core.obs_key(self.generation, r))
                    if rec is not None:
                        payloads[r] = rec
            except Exception:  # noqa: BLE001 - degrade to local-only
                payloads = {}
        self.local_only = not payloads
        if self.local_only:
            payloads = {self.rank: core.local_payload(rank=self.rank)}
        self._payloads = payloads
        if _tel.ENABLED:
            _tel.OBS_FLEET_RANKS.set(len(payloads))
        return payloads

    def payloads(self):
        if not self._payloads:
            self.refresh()
        return self._payloads

    @property
    def ranks(self):
        return sorted(self.payloads())

    # -- merged snapshot -----------------------------------------------------
    def merged(self):
        """Pod-level telemetry snapshot (counter sums, histogram
        bucket merges) across every published rank."""
        return merge_metrics(
            p.get("metrics") for p in self.payloads().values())

    def totals(self, nonzero=True):
        """Flat {name: fleet-summed value} from the merged snapshot
        (histograms contribute _count/_sum) — the compact form bench
        rows and ``/fleetz`` carry."""
        out = {}
        for name, fam in self.merged().items():
            if fam["type"] == "histogram":
                out[name + "_count"] = sum(
                    s["count"] for s in fam["samples"])
                out[name + "_sum"] = round(
                    sum(s["sum"] for s in fam["samples"]), 6)
            else:
                out[name] = sum(s["value"] for s in fam["samples"])
        if nonzero:
            out = {k: v for k, v in out.items() if v}
        return out

    def table(self, now=None):
        """Per-rank rows for diagnose/``/fleetz``: publish age, step,
        cadence, collective wait, straggler flag."""
        now = time.time() if now is None else now
        flagged = self.stragglers()
        rows = []
        for r in self.ranks:
            p = self._payloads[r]
            rows.append({
                "rank": r,
                "pid": p.get("pid"),
                "age_s": round(max(0.0, now - float(p.get("wall", now))),
                               3),
                "step": p.get("step"),
                "steps_observed": p.get("steps_observed", 0),
                "step_p50_s": p.get("step_p50_s"),
                "collective_wait_p50_s": p.get("collective_wait_p50_s"),
                "monitor": (p.get("monitor") or {}).get("enabled"),
                "straggler": r in flagged,
            })
        return rows

    # -- straggler detection -------------------------------------------------
    def stragglers(self, factor=None):
        """Ranks whose step p50 exceeds ``factor`` x the median p50 of
        their PEERS (leave-one-out median; needs >= 2 ranks reporting
        cadence).  Excluding the candidate itself matters in small
        fleets: with 2 ranks an all-rank median averages the slow rank
        in, so a 50x straggler would never clear a 2x factor."""
        if factor is None:
            factor = get_env("MXNET_OBS_STRAGGLER_FACTOR", float, 2.0)
        if factor <= 0:
            return []
        p50s = {r: p.get("step_p50_s")
                for r, p in self.payloads().items()
                if p.get("step_p50_s")}
        if len(p50s) < 2:
            return []
        out = []
        for r, v in p50s.items():
            peers = [x for rr, x in p50s.items() if rr != r]
            peer_median = statistics.median(peers)
            if peer_median > 0 and v > factor * peer_median:
                out.append(r)
        return sorted(out)

    def check_stragglers(self, factor=None, fire=True):
        """Detect stragglers and fire the anomaly path for NEWLY
        flagged ranks: one ``obs_stragglers_total{rank}`` count + one
        rate-limited flight-record dump (``reason="straggler"``) per
        episode.  Recovered ranks unflag and may fire again later.
        Returns the currently-flagged rank list.  Never raises."""
        try:
            slow = set(self.stragglers(factor=factor))
            p50s = {r: p.get("step_p50_s")
                    for r, p in self.payloads().items()}
            with _FLAG_LOCK:
                fresh = slow - _FLAGGED
                _FLAGGED.difference_update(
                    r for r in list(_FLAGGED)
                    if r in p50s and r not in slow)
                _FLAGGED.update(fresh)
            if fire:
                for r in sorted(fresh):
                    if _tel.ENABLED:
                        _tel.OBS_STRAGGLERS.labels(rank=str(r)).inc()
                    from ..trace import anomaly

                    anomaly.straggler(extra={
                        "rank": r,
                        "step_p50_s": p50s.get(r),
                        "fleet_median_p50_s": statistics.median(
                            v for v in p50s.values() if v),
                        "factor": factor if factor is not None else
                        get_env("MXNET_OBS_STRAGGLER_FACTOR",
                                float, 2.0),
                        "detected_by_rank": self.rank})
            return sorted(slow)
        except Exception:  # noqa: BLE001 - detector must never raise
            return []

    # -- prometheus export ---------------------------------------------------
    def prometheus(self):
        """Prometheus text exposition of every rank's samples with a
        ``rank`` label appended (aggregation across ranks belongs to
        the TSDB; HELP/TYPE once per family)."""
        fams = {}
        payloads = self.payloads()
        for r in sorted(payloads):
            for name, fam in (payloads[r].get("metrics") or {}).items():
                fams.setdefault(name, (fam.get("type", "counter"),
                                       fam.get("help", "")))
        lines = []
        for name in sorted(fams):
            kind, help_ = fams[name]
            lines.append("# HELP %s %s"
                         % (name, _tel._esc_help(help_ or name)))
            lines.append("# TYPE %s %s" % (name, kind))
            for r in sorted(payloads):
                fam = (payloads[r].get("metrics") or {}).get(name)
                if fam is None:
                    continue
                for s in fam.get("samples", ()):
                    labels = dict(s.get("labels") or {})
                    labels["rank"] = str(r)
                    if kind == "histogram":
                        for le, c in (s.get("buckets") or {}).items():
                            lines.append("%s_bucket%s %d" % (
                                name,
                                _labelstr(dict(labels, le=le)), c))
                        lines.append("%s_sum%s %s" % (
                            name, _labelstr(labels),
                            repr(float(s.get("sum", 0.0)))))
                        lines.append("%s_count%s %d" % (
                            name, _labelstr(labels),
                            s.get("count", 0)))
                    else:
                        lines.append("%s%s %s" % (
                            name, _labelstr(labels),
                            repr(float(s.get("value", 0.0)))))
        return "\n".join(lines) + "\n"


def _labelstr(labels):
    if not labels:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, _tel._esc(v)) for k, v in sorted(labels.items()))


# ---------------------------------------------------------------------------
# module-level conveniences (serve /fleetz, bench rows, diagnose)
# ---------------------------------------------------------------------------

def _attached_view():
    pub = core.publisher()
    if pub is not None and pub.membership is not None \
            and pub.membership.generation is not None:
        return FleetView(membership=pub.membership)
    return FleetView(rank=0)  # local-only world of one


def fleetz():
    """The ``/fleetz`` JSON document: enabled flag, rank table, fleet
    totals, straggler flags, SLO states.  Fail-soft: always returns a
    dict, degraded sections omitted."""
    if not core.ENABLED:
        return {"enabled": False}
    try:
        view = _attached_view()
        view.refresh()
        doc = {
            "enabled": True,
            "generation": view.generation,
            "rank": view.rank,
            "local_only": view.local_only,
            "ranks": view.table(),
            "stragglers": view.stragglers(),
            "totals": view.totals(),
        }
        try:
            from . import slo_engine

            if slo_engine.registered():
                doc["slo"] = slo_engine.states()
        except Exception:  # noqa: BLE001
            pass
        return doc
    except Exception as exc:  # noqa: BLE001 - endpoint must not 500
        return {"enabled": True, "error": str(exc)[:200]}


def fleet_summary():
    """Compact fleet block for bench rows (fail-soft like bench's
    ``_monitor_summary``): ranks seen, straggler flags, SLO states."""
    if not core.ENABLED:
        return {}
    try:
        view = _attached_view()
        view.refresh()
        out = {"ranks_seen": len(view.ranks),
               "local_only": view.local_only,
               "stragglers": view.stragglers()}
        try:
            from . import slo_engine

            if slo_engine.registered():
                out["slo"] = slo_engine.states()
        except Exception:  # noqa: BLE001
            pass
        return out
    except Exception:  # noqa: BLE001
        return {}
