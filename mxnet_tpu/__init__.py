"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's capability
surface.

Brand-new design (NOT a port) targeting JAX/XLA/Pallas/pjit:

- ``mx.nd`` / ``mx.np``: imperative NDArray backed by jax.Array (PJRT HBM
  buffers); async semantics come from XLA dispatch, not a threaded engine.
- ``mx.autograd``: dynamic tape whose nodes are jax.vjp closures.
- ``mx.gluon``: Block/HybridBlock/Trainer; hybridize() traces the block into
  one jit-compiled XLA computation (the CachedOp equivalent).
- ``mx.kvstore`` + ``mxnet_tpu.parallel``: data/tensor/pipeline/sequence
  parallelism via jax.sharding Mesh + collectives over ICI.
- Hot ops as Pallas TPU kernels (mxnet_tpu/ops/pallas_*).

Reference capability map: SURVEY.md at the repo root (mozga-intel/
incubator-mxnet structural survey).
"""
from __future__ import annotations

__version__ = "2.0.0-tpu0"


def _maybe_init_distributed():
    """Join the process group when launched by tools/launch.py.

    The launcher exports MXNET_DIST_{COORDINATOR,NUM_WORKERS,RANK}; this
    replaces the ps-lite scheduler handshake (reference tools/launch.py +
    kvstore_dist.h rendezvous) with jax.distributed's coordination
    service.  Must run before the first jax backend initialization."""
    import os
    import sys

    coord = os.environ.get("MXNET_DIST_COORDINATOR")
    if not coord:
        return
    strip = os.environ.get("MXNET_DIST_STRIP_AXON", "")
    if strip.lower() not in ("", "0", "false", "off", "no"):
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    import jax

    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["MXNET_DIST_NUM_WORKERS"]),
        process_id=int(os.environ["MXNET_DIST_RANK"]))


_maybe_init_distributed()

from . import base, telemetry  # telemetry first: instrumented layers use it
from . import trace  # structured tracing + flight recorder (uses telemetry)
from . import autograd, context, engine
from . import ndarray
from . import ndarray as nd
from . import random
from .base import MXNetError, get_env
from .context import (Context, cpu, cpu_pinned, current_context, gpu,
                      num_gpus, num_tpus, tpu)
from .ndarray.ndarray import NDArray, waitall

# lazily-importable heavy submodules
from . import initializer  # noqa: E402
from . import optimizer  # noqa: E402
from . import gluon  # noqa: E402
from . import numpy as np  # noqa: E402
from . import numpy_extension as npx  # noqa: E402
from . import kvstore as kv  # noqa: E402
from . import kvstore  # noqa: E402
from . import io  # noqa: E402
from . import recordio  # noqa: E402
from . import symbol  # noqa: E402
from . import symbol as sym  # noqa: E402
from . import profiler  # noqa: E402
from . import runtime  # noqa: E402
from . import util  # noqa: E402
from . import parallel  # noqa: E402
from . import test_utils  # noqa: E402
from . import contrib  # noqa: E402
from . import metric  # noqa: E402  (alias of gluon.metric, reference layout)
from . import operator  # noqa: E402  (mx.operator CustomOp API)
from . import library  # noqa: E402  (extension .so loading)
from . import image  # noqa: E402
from . import checkpoint  # noqa: E402  (async/sharded/atomic persistence)
from . import serve  # noqa: E402  (dynamic-batching inference serving)
from . import compile  # noqa: E402,A004  (persistent compile cache + AOT)
from . import autotune  # noqa: E402  (self-tuning kernels/buckets/flags)
from . import monitor  # noqa: E402  (training-health numerics + sentinel)
from . import resilience  # noqa: E402  (fault injection + preempt + supervisor)
from . import dist  # noqa: E402  (multi-host membership + pod checkpoints)
from . import obs  # noqa: E402  (fleet-wide observability plane)
from . import fleet  # noqa: E402  (multi-replica serving fleet)
from . import tenant  # noqa: E402  (multi-tenant serving: LoRA banks + WFQ)
from . import shard  # noqa: E402  (global mesh + ZeRO weight-update sharding)
from . import step  # noqa: E402  (whole-program training-step capture)
from . import data  # noqa: E402  (sharded streaming input pipeline)
from . import elastic  # noqa: E402  (failure detection + auto-resume)
from . import config  # noqa: E402  (env-var registry, reference env_var.md)
from . import subgraph  # noqa: E402  (SubgraphProperty partitioner hooks)
from . import callback  # noqa: E402  (Speedometer/checkpoint callbacks)
from . import dlpack  # noqa: E402  (DLPack interop)
from . import error  # noqa: E402  (structured error classes)
from . import visualization  # noqa: E402  (print_summary/plot_network)
from .optimizer import lr_scheduler  # noqa: E402  (mx.lr_scheduler)
from .dlpack import (from_dlpack, to_dlpack_for_read,  # noqa: E402
                     to_dlpack_for_write)

if base.get_env("MXNET_PROFILER_AUTOSTART", bool, False):
    profiler.set_state("run")  # reference env_var.md MXNET_PROFILER_AUTOSTART
from .util import is_np_array, set_np, reset_np, use_np  # noqa: E402
