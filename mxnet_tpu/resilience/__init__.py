"""mx.resilience — deterministic fault injection, preemption-aware
shutdown, hardened restart supervision.

The stack can see itself (telemetry / trace / monitor) and persist
itself (checkpoint); this subsystem makes it *survive* itself:

- ``resilience.inject`` — a step/site-keyed fault plan
  (``MXNET_FAULTS`` or ``resilience.plan()``) with named injection
  sites at trainer step launch, collective ``pushpull_all``,
  checkpoint writer IO, compile-cache commit, and serve batch
  dispatch.  Faults fire deterministically by (site, sequence), so
  every recovery drill replays identically on CPU under Tier-1.
- ``resilience.preempt`` — SIGTERM handling with a grace budget
  (``MXNET_PREEMPT_GRACE_SECONDS``): the supervisor stops at the next
  step boundary, flushes an emergency checkpoint, drains serve, and
  exits with the distinct ``MXNET_PREEMPT_EXIT_CODE``.
- ``resilience.supervisor`` — transient-vs-fatal exception taxonomy,
  exponential backoff with jitter, a restart budget over a sliding
  step window, wall-clock-bounded device health checks, and
  restore-on-divergence wired to the mx.monitor feed.  It absorbs
  (and deprecates) ``elastic.FaultTolerantRunner``.

Serve-side graceful degradation (bisect-isolate poisoned requests,
per-bucket circuit breakers) lives in ``mx.serve`` and is counted in
the same ``resilience_*``/``serve_*`` telemetry family.  Drills:
``tools/faults_smoke.py`` / ``make faults-smoke``.
"""
from __future__ import annotations

from ..base import get_env
from . import inject, preempt, supervisor
from .inject import (FaultPlan, InjectedFault, InjectedIOError, clear,
                     fire, plan, poisoned, refresh_env)
from .preempt import (graceful_shutdown, install, preemption_imminent,
                      request, requested)
from .supervisor import (Backoff, GluonStepLoop, RestartBudget,
                         Supervisor, classify, health_check,
                         recent_restarts, register_fatal,
                         register_transient)

__all__ = [
    "inject", "preempt", "supervisor",
    "FaultPlan", "InjectedFault", "InjectedIOError",
    "plan", "clear", "fire", "poisoned", "refresh_env",
    "install", "request", "requested", "preemption_imminent",
    "graceful_shutdown",
    "Supervisor", "GluonStepLoop", "Backoff", "RestartBudget",
    "classify", "health_check", "recent_restarts",
    "register_transient", "register_fatal",
]

# arm the SIGTERM handler at import when asked (PERF_PLAN: set this
# during live tunnel windows so a dying tunnel leaves an emergency
# checkpoint instead of a dead bench)
if get_env("MXNET_PREEMPT_INSTALL", bool, False):  # pragma: no cover
    install()
