"""Hardened restart supervisor (absorbs ``elastic.FaultTolerantRunner``).

The old runner was a 146-line retry loop with three documented gaps:
no backoff (a crash-looping job hammered the checkpoint store), no
transient-vs-fatal classification (a shape bug got three pointless
restarts before surfacing), and a ``device_health_check`` that could
hang the supervisor forever on a dead tunnel.  This module closes all
three and adds the preemption + divergence hooks:

- **exception taxonomy** (``classify``): transient device/collective/
  IO errors (``OSError``, ``TimeoutError``, ``ConnectionError``,
  PJRT's ``RuntimeError`` family, injected transients) are retried;
  fatal shape/user errors (``ValueError``/``TypeError``/``KeyError``/
  framework ``MXNetError`` contract violations) raise immediately —
  restarting cannot fix a wrong model.
- **exponential backoff with jitter** (``Backoff``) between restarts,
  and a **restart budget over a sliding step window**
  (``RestartBudget``) instead of a lifetime cap: a job that hits one
  flaky hour after a week of progress should not burn budget it
  "spent" days ago.
- **bounded health probes** (``health_check(timeout=...)``): each
  device probed in its own worker thread; a hung transfer reports
  ``"error: timeout"`` instead of blocking the supervisor forever.
- **preemption**: ``preempt.requested()`` is polled at every step
  boundary; when set the supervisor takes an emergency checkpoint
  (through the manager's async writer, then ``wait()``), runs the
  registered shutdown hooks (serve drain), and exits with the
  distinct preemption code.
- **divergence restore**: with ``restore_on_divergence=True`` the
  supervisor subscribes to the mx.monitor divergence feed and rolls
  back to the latest checkpoint at the next step boundary when
  training health goes bad — the automated version of "the loss went
  to NaN an hour ago, reload and lower the LR".
- a **flight-record dump** (reason ``restart``) on every restart, so
  each recovery leaves the trace of what preceded the failure.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque

from .. import telemetry, trace
from ..base import MXNetError, get_env
from . import preempt
from .inject import InjectedFault

__all__ = ["classify", "register_transient", "register_fatal",
           "Backoff", "RestartBudget", "health_check", "Supervisor",
           "GluonStepLoop", "RECENT_RESTARTS", "recent_restarts"]

_LOG = logging.getLogger("mxnet_tpu.resilience")

# ---------------------------------------------------------------------------
# exception taxonomy
# ---------------------------------------------------------------------------

_TRANSIENT_EXTRA = []
_FATAL_EXTRA = []

# user/shape/programming errors: a restart replays the same code on
# the same shapes and fails the same way — surface immediately
_FATAL_BUILTIN = (ValueError, TypeError, KeyError, IndexError,
                  AttributeError, AssertionError, ZeroDivisionError,
                  NotImplementedError)
# infrastructure errors: storage hiccups, dead/hung chips, lost
# tunnels — the restart-from-checkpoint loop exists for these
_TRANSIENT_BUILTIN = (OSError, TimeoutError, ConnectionError)


def register_transient(*exc_types):
    """Teach the taxonomy extra retryable types (a custom data-loader
    error, a vendor RPC exception, ...)."""
    _TRANSIENT_EXTRA.extend(exc_types)


def register_fatal(*exc_types):
    _FATAL_EXTRA.extend(exc_types)


def classify(exc):
    """``"transient"`` (retry from checkpoint) or ``"fatal"`` (raise).

    Order matters: explicit marks beat registrations beat built-ins,
    and ``MXNetError`` — this framework's contract-violation type — is
    fatal even though it subclasses ``RuntimeError``, while a plain
    ``RuntimeError`` (how PJRT/XLA surface device loss) is transient.
    Unknown exception types default to transient: on a pod, retrying
    an unknown error and hitting the restart budget beats killing a
    week-long job on the first novel hiccup.
    """
    kind = getattr(exc, "mx_fault_kind", None)
    if kind in ("transient", "fatal"):
        return kind
    if isinstance(exc, InjectedFault):
        return "fatal" if exc.kind == "fatal" else "transient"
    for t in _FATAL_EXTRA:
        if isinstance(exc, t):
            return "fatal"
    for t in _TRANSIENT_EXTRA:
        if isinstance(exc, t):
            return "transient"
    if isinstance(exc, _TRANSIENT_BUILTIN):
        return "transient"
    if isinstance(exc, MXNetError):
        return "fatal"
    if isinstance(exc, _FATAL_BUILTIN):
        return "fatal"
    return "transient"


# ---------------------------------------------------------------------------
# backoff + budget
# ---------------------------------------------------------------------------

class Backoff:
    """``base * factor**attempt`` capped at ``max_delay``, stretched by
    up to ``jitter`` fraction (decorrelates a pod's workers so N
    restarting processes don't stampede the checkpoint store in
    lockstep).  ``seed`` pins the jitter stream for deterministic
    drills."""

    def __init__(self, base=None, factor=2.0, max_delay=None,
                 jitter=0.1, seed=None):
        self.base = get_env("MXNET_RESTART_BACKOFF_BASE", float, 1.0) \
            if base is None else float(base)
        self.factor = float(factor)
        self.max_delay = get_env("MXNET_RESTART_BACKOFF_MAX", float,
                                 60.0) if max_delay is None \
            else float(max_delay)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    def delay(self, attempt):
        d = min(self.base * self.factor ** max(0, int(attempt)),
                self.max_delay)
        if self.jitter > 0 and d > 0:
            d *= 1.0 + self._rng.random() * self.jitter
        return d


class RestartBudget:
    """At most ``max_restarts`` restarts within the trailing
    ``window_steps`` training steps (``None`` = over the whole run,
    the old lifetime semantics)."""

    def __init__(self, max_restarts, window_steps=None):
        self.max_restarts = int(max_restarts)
        self.window_steps = None if window_steps is None \
            else int(window_steps)
        self._steps = deque()

    def record(self, step):
        """Count a restart at ``step``; returns restarts currently in
        the window (including this one)."""
        self._steps.append(int(step))
        return self.count(step)

    def count(self, step):
        if self.window_steps is not None:
            while self._steps and \
                    step - self._steps[0] >= self.window_steps:
                self._steps.popleft()
        return len(self._steps)

    def exceeded(self, step):
        return self.count(step) > self.max_restarts


# ---------------------------------------------------------------------------
# bounded device health check
# ---------------------------------------------------------------------------

def _default_probe(device):
    import jax
    import numpy as _np

    val = _np.asarray(jax.device_put(_np.float32(2.0), device) * 2)
    if float(val) != 4.0:
        raise MXNetError("bad arithmetic: %r" % (val,))


def health_check(timeout=None, devices=None, probe=None):
    """Probe every local device with a trivial program + host transfer;
    returns ``{device_str: "ok" | "error: ..."}``.

    Each probe runs in its own worker thread and the whole check is
    bounded by ``timeout`` seconds (shared wall-clock, not per
    device): a hung transfer — the dead-axon-tunnel signature — is
    reported as ``"error: timeout"`` instead of hanging the caller.
    ``timeout=None`` preserves the old unbounded behavior."""
    if devices is None:
        import jax

        devices = jax.local_devices()
    probe = probe or _default_probe
    report, threads = {}, []
    lock = threading.Lock()

    def run(d):
        try:
            probe(d)
            out = "ok"
        except Exception as exc:  # pragma: no cover - real device loss
            out = "error: %s" % (exc,)
        with lock:
            report[str(d)] = out

    for d in devices:
        t = threading.Thread(target=run, args=(d,), daemon=True,
                             name="mx-health-probe")
        t.start()
        threads.append((d, t))
    deadline = None if timeout is None else \
        time.monotonic() + float(timeout)
    for d, t in threads:
        t.join(None if deadline is None
               else max(0.0, deadline - time.monotonic()))
        with lock:
            if str(d) not in report:
                report[str(d)] = "error: timeout" + (
                    "" if timeout is None
                    else " (probe still running after %.1fs)"
                         % float(timeout))
    return report


# ---------------------------------------------------------------------------
# restart records (diagnose surface)
# ---------------------------------------------------------------------------

RECENT_RESTARTS = deque(maxlen=32)  # newest-last dicts


def recent_restarts():
    return list(RECENT_RESTARTS)


def _record_restart(kind, step, error, backoff_s=None,
                    restored_step=None):
    rec = {"kind": kind, "step": int(step), "wall_time": time.time(),
           "error": None if error is None else
           "%s: %s" % (type(error).__name__, error),
           "backoff_seconds": backoff_s, "restored_step": restored_step}
    RECENT_RESTARTS.append(rec)
    if telemetry.ENABLED:
        telemetry.RESILIENCE_RESTARTS.labels(kind=kind).inc()
    return rec


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------

def _safe_on_failure(cb, step, exc):
    """Run the user's on_failure callback WITHOUT letting its own bugs
    mask the original training error: a raising callback is logged
    (with the original attached as context) and recovery proceeds on
    the original exception."""
    if cb is None:
        return
    try:
        cb(step, exc)
    except Exception as cb_exc:  # noqa: BLE001 - must not mask `exc`
        cb_exc.__context__ = exc
        _LOG.warning(
            "on_failure callback raised %s: %s — original training "
            "error %s: %s is preserved and still drives recovery",
            type(cb_exc).__name__, cb_exc, type(exc).__name__, exc)


class Supervisor:
    """Resumable, preemption-aware training loop with failure taxonomy.

    ``trainer`` needs ``step(x, y) -> loss``, ``state_dict()`` and
    ``load_state_dict(state)`` (FusedTrainer, PipelineTrainer, and the
    ``GluonStepLoop`` adapter below all qualify).  ``batches`` is
    ``fn(step_index) -> (x, y)`` — the data position is a pure
    function of the step index, so a resume lands on the right batch.

    Parameters
    ----------
    trainer, manager : the step engine and its ``mx.checkpoint``
        manager (``elastic.CheckpointManager`` works).
    checkpoint_every : save cadence in steps.
    max_restarts : restart budget (default ``MXNET_RESTART_BUDGET``).
    restart_window : sliding step window the budget applies over
        (default ``MXNET_RESTART_WINDOW_STEPS``; 0/None = lifetime).
    backoff : a ``Backoff`` (default: env-tuned, jittered).
    on_failure : ``fn(step, exc)`` observer; its own exceptions are
        contained (they never mask the training error).
    health_timeout : wall-clock bound on the post-failure device probe
        (default ``MXNET_HEALTH_TIMEOUT``).
    exit_on_preempt : ``sys.exit(preempt.exit_code())`` after the
        emergency checkpoint instead of returning (what a pod
        entrypoint wants; library callers inspect ``.preempted``).
    restore_on_divergence : roll back to the latest checkpoint when
        mx.monitor reports divergence (grad spike / nonfinite / loss
        NaN); counts against the same restart budget.
    membership : an ``mx.dist.Membership`` arms **dist mode**: the
        supervisor heartbeats its step, polls the world-stop flag at
        every step boundary, and turns any rank's transient failure or
        SIGTERM into a COORDINATED stop — post the flag, stop at the
        boundary, emergency-checkpoint through the (pod) manager, and
        exit with the preempt code so the launcher relaunches the
        whole world.  Local restore-and-retry is disabled (peers
        cannot rejoin a collective this rank replays alone); the
        restart loop moves up to ``tools/launch.py --restarts``.
    """

    def __init__(self, trainer, manager, checkpoint_every=50,
                 max_restarts=None, restart_window=None, backoff=None,
                 on_failure=None, health_timeout=None,
                 exit_on_preempt=False, restore_on_divergence=False,
                 membership=None):
        self._trainer = trainer
        self._manager = manager
        self._every = max(1, int(checkpoint_every))
        self._max_restarts = get_env("MXNET_RESTART_BUDGET", int, 3) \
            if max_restarts is None else int(max_restarts)
        if restart_window is None:
            restart_window = get_env("MXNET_RESTART_WINDOW_STEPS",
                                     int, 0)
        self._window = int(restart_window) or None
        self._backoff = backoff if backoff is not None else Backoff()
        self._on_failure = on_failure
        self._health_timeout = get_env("MXNET_HEALTH_TIMEOUT", float,
                                       60.0) \
            if health_timeout is None else health_timeout
        self._exit_on_preempt = bool(exit_on_preempt)
        self._restore_on_divergence = bool(restore_on_divergence)
        self._membership = membership
        self._divergence_pending = None
        self._state_suspect = False  # failed mid-step, no ckpt to trust
        self.restarts = 0            # transient-failure restarts
        self.divergence_restores = 0
        self.preempted = False
        self.world_stopped = None    # dist mode: the stop flag we obeyed
        self.emergency_checkpoint = None

    # -- resume -------------------------------------------------------------
    def _resume(self):
        """Restore the latest checkpoint into the trainer; returns the
        restored step.  The trainer's live state is the restore
        template (dtype/sharding adoption = restore-with-resharding);
        when its structure diverges from the saved tree — a fresh
        process whose optimizer state is not materialized yet — the
        spec-based restore carries it."""
        template = self._trainer.state_dict()
        try:
            saved_step, state = self._manager.restore(template)
        except MXNetError:
            if template is None:
                raise
            saved_step, state = self._manager.restore(None)
        self._trainer.load_state_dict(state)
        self._state_suspect = False  # fully replaced from durable state
        return saved_step

    def _save(self, step):
        self._manager.save(step, self._trainer.state_dict())

    def _emergency(self, last_done):
        """The preemption endgame: flush an emergency checkpoint
        through the async writer (snapshot + commit + ``wait()``),
        then run the registered shutdown hooks inside whatever grace
        budget remains.  ``last_done`` is the last COMPLETED step —
        the checkpoint tag a resume continues from (+1), exactly like
        the periodic saves.  State marked suspect (a step failed
        mid-mutation with nothing durable to roll back to) is NOT
        saved — persisting corruption as truth is worse than losing
        the partial progress."""
        state = None if self._state_suspect or last_done < 0 \
            else self._trainer.state_dict()
        step = max(0, last_done)
        if state is not None:
            with trace.span("emergency_checkpoint", hist=False,
                            cat="resilience", args={"step": int(step)}):
                self._manager.save_async(step, state)
                self.emergency_checkpoint = self._manager.wait()
            if telemetry.ENABLED:
                telemetry.RESILIENCE_EMERGENCY_SAVES.inc()
        rem = preempt.remaining()
        if rem is not None and rem <= 0:
            _LOG.warning(
                "preemption grace budget exhausted (%.1fs over); "
                "skipping shutdown hooks — the emergency checkpoint "
                "is committed", -rem)
        else:
            preempt.graceful_shutdown()
        _LOG.warning(
            "preemption: emergency checkpoint %s at step %d, exiting "
            "with code %d", self.emergency_checkpoint, step,
            preempt.exit_code())

    # -- divergence hook ----------------------------------------------------
    def _on_divergence(self, extra):
        self._divergence_pending = dict(extra or {})

    # -- the loop -----------------------------------------------------------
    def run(self, batches, num_steps, start_step=0):
        """Drive ``trainer.step`` from ``start_step`` to ``num_steps``;
        returns the per-step loss list for steps executed by THIS
        process.  Transient failures restore-and-resume under the
        budget/backoff policy; fatal ones raise immediately; a pending
        preemption stops the loop at the step boundary."""
        losses = []
        step = start_step
        budget = RestartBudget(self._max_restarts, self._window)
        listener = None
        if self._restore_on_divergence:
            from ..trace import anomaly

            listener = anomaly.on_divergence(self._on_divergence)
        if self._membership is not None \
                and self._membership.generation is None:
            self._membership.join()
        try:
            latest = self._manager.latest_step()
            if latest is not None and latest >= step:
                step = self._resume() + 1
            while step < num_steps:
                if preempt.requested():
                    # dist mode: SIGTERM on THIS host preempts the
                    # whole world — post the flag before saving so
                    # peers reach their own step boundary (or their
                    # collective deadline) and flush the SAME step
                    if self._membership is not None:
                        self.world_stopped = \
                            self._membership.signal_stop(
                                "preempt", step - 1)
                    self.preempted = True
                    self._emergency(step - 1)
                    if self._membership is not None:
                        self._membership.leave("preempt")
                    if self._exit_on_preempt:
                        import sys

                        sys.exit(preempt.exit_code())
                    return losses
                if self._membership is not None:
                    self._membership.note_step(step)
                    stop = self._membership.poll_stop()
                    if stop is not None:
                        return self._obey_world_stop(stop, step - 1,
                                                     losses)
                if self._divergence_pending is not None:
                    info, self._divergence_pending = \
                        self._divergence_pending, None
                    step, losses = self._handle_divergence(
                        info, step, start_step, losses, budget)
                    continue
                try:
                    x, y = batches(step)
                    loss = self._trainer.step(x, y)
                    losses.append(float(loss.asscalar()))
                    # a cleanly completed step leaves consistent state:
                    # safe to checkpoint (periodic or emergency) again
                    self._state_suspect = False
                    if (step + 1) % self._every == 0 \
                            or step == num_steps - 1:
                        self._save(step)
                    step += 1
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as exc:
                    step, losses = self._handle_failure(
                        exc, step, start_step, losses, budget)
                    if step is None:   # dist mode: world stopping
                        return losses
            return losses
        finally:
            if listener is not None:
                from ..trace import anomaly

                anomaly.remove_divergence_listener(listener)

    def _obey_world_stop(self, info, last_done, losses):
        """Dist mode: a peer (or this rank, below) posted the world-
        stop flag.  Stop at the boundary, emergency-checkpoint through
        the pod manager (every obeying rank saves its last completed
        step; the pod marker only lands for a step ALL ranks flushed,
        so restore is consistent by construction), leave membership,
        and exit with the preempt code for the launcher to relaunch."""
        self.preempted = True
        self.world_stopped = dict(info or {})
        _record_restart("world_stop", max(0, last_done), None)
        _LOG.warning(
            "world stop (reason=%s from rank %s at step %s): stopping "
            "at step boundary %d, flushing emergency checkpoint",
            self.world_stopped.get("reason"),
            self.world_stopped.get("rank"),
            self.world_stopped.get("step"), last_done)
        self._emergency(last_done)
        if self._membership is not None:
            self._membership.leave("world_stop")
        if self._exit_on_preempt:
            import sys

            sys.exit(preempt.exit_code())
        return losses

    def _world_failure(self, exc, step, losses):
        """Dist mode transient failure on THIS rank: propagate through
        the stop flag and join the coordinated shutdown.  A failure
        marked state-clean (``DistTimeout``: the collective deadline
        fires before any optimizer state mutates) may still emergency-
        checkpoint the last completed step; anything else is suspect
        and saves nothing — peers' shards plus the pod max-common rule
        keep the restore consistent either way."""
        self.restarts += 1
        if not getattr(exc, "mx_state_clean", False):
            self._state_suspect = True
        info = None
        if self._membership is not None:
            info = self._membership.signal_stop(
                "failure", step - 1,
                error="%s: %s" % (type(exc).__name__, exc))
        return None, self._obey_world_stop(
            info or {"reason": "failure", "rank": None, "step": step - 1},
            step - 1, losses)

    def _handle_failure(self, exc, step, start_step, losses, budget):
        kind = classify(exc)
        _safe_on_failure(self._on_failure, step, exc)
        trace.dump_async("restart", extra={
            "step": int(step), "classified": kind,
            "error": "%s: %s" % (type(exc).__name__, exc)})
        if kind == "transient" and self._membership is not None:
            return self._world_failure(exc, step, losses)
        if kind == "fatal":
            if self._membership is not None:
                # peers must not wait out their collective deadline to
                # learn the world is dead — flag it before raising
                self._membership.signal_stop(
                    "failure", step - 1,
                    error="%s: %s" % (type(exc).__name__, exc))
            _record_restart("fatal", step, exc)
            raise MXNetError(
                "fatal training error at step %d (%s — not retried: "
                "a restart replays the same failure): %s"
                % (step, type(exc).__name__, exc)) from exc
        n = budget.record(step)
        self.restarts += 1
        if budget.exceeded(step):
            _record_restart("budget_exhausted", step, exc)
            raise MXNetError(
                "training failed at step %d after %d restarts%s: %s"
                % (step, n - 1,
                   " within the trailing %d-step window" % self._window
                   if self._window else "", exc)) from exc
        # a pending preemption outranks the SLOW parts of recovery —
        # health probe (up to MXNET_HEALTH_TIMEOUT) and backoff sleep
        # (ceiling 60s, twice the default grace budget) are skipped —
        # but NEVER the restore: a real transient error may have fired
        # mid-update, so the in-memory state is suspect and must not
        # become the emergency checkpoint
        delay = 0.0
        if not preempt.requested():
            health = health_check(timeout=self._health_timeout)
            bad = {k: v for k, v in health.items() if v != "ok"}
            if bad:  # pragma: no cover - real chip loss
                _record_restart("unhealthy", step, exc)
                raise MXNetError(
                    "device(s) unhealthy after failure at step %d: %s"
                    % (step, bad)) from exc
            delay = self._backoff.delay(n - 1)
            if delay > 0:
                if telemetry.ENABLED:
                    telemetry.RESILIENCE_BACKOFF_SECONDS.observe(delay)
                # sleep in slices so a SIGTERM mid-backoff doesn't burn
                # the grace window checkpoint-less
                end = time.monotonic() + delay
                while time.monotonic() < end \
                        and not preempt.requested():
                    time.sleep(min(0.25,
                                   max(0.0, end - time.monotonic())))
        restored = None
        failed_step = step          # the record keeps WHERE it failed
        if self._manager.latest_step() is not None:
            restored = self._resume()
            step = restored + 1
            # drop losses from steps that will be replayed so the
            # returned series has exactly one entry per step
            losses = losses[:max(0, step - start_step)]
        else:
            # retrying from in-memory state: the failed step may have
            # half-mutated it, so it is suspect until the next step
            # completes cleanly — an emergency save in that window
            # would persist corruption as truth.  Marked
            # unconditionally (not only when preemption is already
            # pending): a SIGTERM can land between this poll and the
            # loop-top one.
            self._state_suspect = True
        _record_restart("transient", failed_step, exc, backoff_s=delay,
                        restored_step=restored)
        return step, losses

    def _handle_divergence(self, info, step, start_step, losses,
                           budget):
        if self._manager.latest_step() is None:
            _LOG.warning(
                "divergence reported (%s) but no checkpoint exists "
                "yet; continuing", info.get("kind"))
            return step, losses
        n = budget.record(step)
        if budget.exceeded(step):
            raise MXNetError(
                "training diverged at step %d after %d restore(s)%s "
                "(%s) — rollback alone is not fixing this run"
                % (step, n - 1,
                   " within the trailing %d-step window" % self._window
                   if self._window else "", info.get("kind")))
        restored = self._resume()
        self.divergence_restores += 1
        _record_restart("divergence", step, None,
                        restored_step=restored)
        _LOG.warning(
            "divergence (%s) at step %s: restored checkpoint step %d, "
            "resuming from step %d", info.get("kind"),
            info.get("step", step), restored, restored + 1)
        step = restored + 1
        return step, losses[:max(0, step - start_step)]


# ---------------------------------------------------------------------------
# imperative-trainer adapter
# ---------------------------------------------------------------------------

class GluonStepLoop:
    """Adapt a Gluon ``(block, gluon.Trainer, loss_fn)`` triple to the
    supervisor's trainer protocol — the imperative counterpart of
    FusedTrainer for fault drills: its step path goes through the real
    kvstore ``pushpull_all`` (the ``collective`` injection site) and
    the real multi-tensor update engine."""

    def __init__(self, block, trainer, loss_fn, step_program=None):
        self._block = block
        self._trainer = trainer
        self._loss_fn = loss_fn
        # optional mx.step whole-step captured program: the supervisor
        # then drills the ONE-program path (fused fwd/bwd/allreduce/
        # apply) — a transient at the step_capture site must rewind
        # update counts exactly once before the restore-and-retry
        self._step_program = step_program

    @property
    def block(self):
        return self._block

    @property
    def trainer(self):
        return self._trainer

    def step(self, x, y):
        from .. import autograd
        from .. import ndarray as nd

        x = x if isinstance(x, nd.NDArray) else nd.array(x)
        y = y if isinstance(y, nd.NDArray) else nd.array(y)
        if self._step_program is not None:
            return self._step_program(x, y).mean()
        with autograd.record():
            loss = self._loss_fn(self._block(x), y)
        loss.backward()
        self._trainer.step(x.shape[0])
        return loss.mean()

    def state_dict(self):
        return self._trainer.state_dict()

    def load_state_dict(self, state):
        self._trainer.load_state_dict(state)
