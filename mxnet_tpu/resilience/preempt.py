"""Preemption-aware graceful shutdown.

TPU pods are preempted routinely — the scheduler sends SIGTERM, waits
a grace window, then SIGKILLs.  Before this module nothing in
``mxnet_tpu`` handled SIGTERM at all: a preempted trainer died
mid-step with up to ``checkpoint_every`` steps of work lost and the
serve queue dropped on the floor.

The contract here:

- ``install()`` arms a SIGTERM handler (``MXNET_PREEMPT_INSTALL=1``
  arms it at import).  The handler does the absolute minimum a signal
  context allows — it records the request and the grace deadline
  (``MXNET_PREEMPT_GRACE_SECONDS``); a SECOND SIGTERM hard-exits
  immediately (the operator meant it).
- ``requested()`` / ``preemption_imminent()`` are the polls: the
  supervisor checks at every step boundary and, when set, stops the
  loop, takes an emergency checkpoint through the async writer
  (flush + ``wait()``), runs the registered shutdown hooks (mx.serve
  registers a graceful drain), and exits with the distinct
  ``MXNET_PREEMPT_EXIT_CODE`` so the pod scheduler can tell "clean
  preemption, resume me" from a crash.
- ``request()`` is the same path minus the signal — drills and tests
  trigger preemption programmatically and deterministically.
"""
from __future__ import annotations

import threading
import time

from .. import telemetry, trace
from ..base import get_env

__all__ = ["install", "uninstall", "installed", "request", "requested",
           "preemption_imminent", "remaining", "clear", "exit_code",
           "grace_seconds", "add_shutdown_hook", "remove_shutdown_hook",
           "graceful_shutdown", "state"]

_LOCK = threading.Lock()
_STATE = {
    "requested_at": None,   # time.monotonic() of the request
    "deadline": None,       # requested_at + grace
    "source": None,         # "sigterm" | "api"
    "installed": False,
    "prev_handler": None,
}
# written ONLY by the signal handler (plain dict stores — no locks: the
# handler runs on the main thread between bytecodes and may interrupt a
# frame that HOLDS _LOCK or telemetry's lock; acquiring either there
# would deadlock the process right when it most needs to shut down).
# The polling side absorbs these into _STATE under the lock.
_SIGNAL = {"count": 0, "at": None}
_HOOKS = []                 # [(name, fn)] run by graceful_shutdown


def _absorb_signal():
    """Complete the SIGTERM bookkeeping (lock, telemetry, trace) OUT of
    signal context — called by every poll/state entry point.  The
    grace deadline is anchored at the handler's timestamp, NOT at
    absorb time: the pod scheduler's SIGKILL clock started when the
    signal landed, and a long step between signal and poll must not
    inflate the budget we think we have."""
    if _SIGNAL["at"] is not None:
        request(source="sigterm", at=_SIGNAL["at"])  # first caller wins


_GRACE_OVERRIDE = None      # install(grace=...) beats the env var


def grace_seconds():
    if _GRACE_OVERRIDE is not None:
        return _GRACE_OVERRIDE
    return get_env("MXNET_PREEMPT_GRACE_SECONDS", float, 30.0)


def exit_code():
    """The distinct "clean preemption" exit status (default 85)."""
    return get_env("MXNET_PREEMPT_EXIT_CODE", int, 85)


def request(source="api", grace=None, at=None):
    """Mark preemption imminent: start the grace clock, count it, and
    leave a trace instant.  Idempotent — only the first request sets
    the deadline.  ``at`` back-dates the clock to when the signal
    actually arrived.  Returns the grace deadline (monotonic)."""
    with _LOCK:
        if _STATE["requested_at"] is None:
            now = time.monotonic() if at is None else float(at)
            _STATE["requested_at"] = now
            _STATE["source"] = source
            _STATE["deadline"] = now + (grace_seconds() if grace is None
                                        else float(grace))
            first = True
        else:
            first = False
        deadline = _STATE["deadline"]
    if first:
        if telemetry.ENABLED:
            telemetry.RESILIENCE_PREEMPTIONS.inc()
        trace.instant("preemption_requested", cat="resilience",
                      args={"source": source,
                            "grace_seconds": round(
                                deadline - _STATE["requested_at"], 3)})
    return deadline


def requested():
    _absorb_signal()
    with _LOCK:
        return _STATE["requested_at"] is not None


def preemption_imminent():
    """The supervisor's poll (alias of ``requested`` with the name the
    pod-runtime literature uses)."""
    return requested()


def remaining():
    """Seconds of grace budget left, or None when no preemption is
    pending.  Negative means the budget is already blown — shutdown
    work should be cut short (skip drains, keep the checkpoint)."""
    _absorb_signal()
    with _LOCK:
        if _STATE["deadline"] is None:
            return None
        return _STATE["deadline"] - time.monotonic()


def clear():
    """Reset the pending request (tests / a cancelled preemption)."""
    with _LOCK:
        _STATE["requested_at"] = None
        _STATE["deadline"] = None
        _STATE["source"] = None
    _SIGNAL["count"] = 0
    _SIGNAL["at"] = None


def _handler(signum, frame):  # pragma: no cover - exercised in drills
    # ASYNC-SIGNAL CONTEXT: plain stores and os._exit only.  No locks,
    # no telemetry, no logging — the interrupted main-thread frame may
    # hold any of those locks (the supervisor polls requested() under
    # _LOCK every step), and blocking here would hang the process
    # through the whole grace window, checkpoint-less.
    _SIGNAL["count"] += 1
    if _SIGNAL["count"] >= 2:
        # the scheduler (or operator) is done waiting
        import os

        os._exit(exit_code())
    _SIGNAL["at"] = time.monotonic()


def install(grace=None):
    """Arm the SIGTERM handler (main thread only; returns False when
    that is impossible, e.g. installed from a worker thread).  The
    previous handler is kept and restored by ``uninstall``.

    ``grace`` overrides ``MXNET_PREEMPT_GRACE_SECONDS`` for FUTURE
    requests (a pending request keeps its own deadline) — applied even
    when the handler is already armed, and kept in process state, not
    the environment, so it never leaks into child processes."""
    import signal

    global _GRACE_OVERRIDE
    if grace is not None:
        _GRACE_OVERRIDE = float(grace)
    with _LOCK:
        if _STATE["installed"]:
            return True
    try:
        prev = signal.signal(signal.SIGTERM, _handler)
    except ValueError:  # not the main thread
        return False
    with _LOCK:
        _STATE["installed"] = True
        _STATE["prev_handler"] = prev
    return True


def uninstall():
    import signal

    with _LOCK:
        if not _STATE["installed"]:
            return
        prev = _STATE["prev_handler"]
        _STATE["installed"] = False
        _STATE["prev_handler"] = None
    try:
        signal.signal(signal.SIGTERM,
                      prev if prev is not None else signal.SIG_DFL)
    except ValueError:  # pragma: no cover
        pass


def installed():
    with _LOCK:
        return _STATE["installed"]


def add_shutdown_hook(name, fn):
    """Register work ``graceful_shutdown`` runs (serve drain, loader
    stop, ...).  Hooks run newest-first so the last-started subsystem
    quiesces first.  Re-registering a name replaces the old hook."""
    with _LOCK:
        _HOOKS[:] = [(n, f) for n, f in _HOOKS if n != name]
        _HOOKS.append((name, fn))


def remove_shutdown_hook(name):
    with _LOCK:
        _HOOKS[:] = [(n, f) for n, f in _HOOKS if n != name]


def graceful_shutdown():
    """Run every registered shutdown hook (newest-first), best-effort:
    a failing hook is logged and the rest still run — the emergency
    checkpoint the supervisor already took must not be hostage to a
    slow drain.  Returns ``{name: "ok" | "error: ..."}``."""
    import logging

    with _LOCK:
        hooks = list(reversed(_HOOKS))
    results = {}
    for name, fn in hooks:
        try:
            fn()
            results[name] = "ok"
        except Exception as exc:  # noqa: BLE001 - best-effort by design
            results[name] = "error: %s" % (exc,)
            logging.getLogger("mxnet_tpu.resilience").warning(
                "preemption shutdown hook %r failed: %s", name, exc)
    return results


def state():
    """Snapshot for ``tools/diagnose.py --resilience``."""
    _absorb_signal()
    with _LOCK:
        return {
            "installed": _STATE["installed"],
            "requested": _STATE["requested_at"] is not None,
            "source": _STATE["source"],
            "signals": _SIGNAL["count"],
            "grace_remaining": None if _STATE["deadline"] is None
            else _STATE["deadline"] - time.monotonic(),
            "hooks": [n for n, _ in _HOOKS],
            "exit_code": exit_code(),
        }
