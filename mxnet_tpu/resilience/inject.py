"""Deterministic fault injection — the drill half of mx.resilience.

Failure handling that is only ever exercised by real outages is
failure handling that does not work (the r04–r05 bench windows died to
exactly that).  This module lets every recovery path in the stack be
driven on CPU, deterministically, from a *fault plan*:

- a plan is a list of ``(site, key)`` entries, armed via the
  ``MXNET_FAULTS`` env var or the ``plan()`` API;
- code registers **named injection sites** by calling ``fire(site,
  seq=...)`` at the interesting spots — trainer step launch
  (``trainer_step``), collective ``pushpull_all`` (``collective``),
  checkpoint writer IO (``checkpoint_commit`` at commit entry,
  ``checkpoint_marker`` just before the COMMITTED marker lands),
  compile-cache commit (``compile_commit``), serve batch dispatch
  (``serve_dispatch``; ``serve_poison`` marks individual request ids),
  and streaming reader IO (``data_read``, keyed by batch index —
  ``io`` kind engages the reader's bounded retry loop);
- a fault fires **iff** the plan holds a matching entry for that
  (site, sequence) pair — so every drill replays identically, run
  after run, and an empty plan costs one dict probe per site.

Plan grammar (comma-separated entries)::

    MXNET_FAULTS="site@key[:kind][*count]"

    trainer_step@5              one transient fault at step 5
    collective@*:transient*2    first two collective calls fail
    checkpoint_commit@0:io      first commit attempt raises OSError
                                (the manager's retry loop recovers)
    checkpoint_marker@0:abort   hard-kill (os._exit) right before the
                                COMMITTED marker -> torn checkpoint
    serve_poison@req-7          request id "req-7" poisons any batch
                                it rides in (the bisect drill)

Kinds: ``transient`` (default, ``InjectedFault`` — classified
transient by the supervisor), ``io`` (``InjectedIOError``, an
``OSError`` so retry-with-backoff paths engage), ``fatal``
(``InjectedFault`` the taxonomy refuses to retry), ``abort``
(``os._exit`` — simulates SIGKILL mid-operation; cleanup handlers
never run, exactly like a preempted node).

Every firing is counted in ``resilience_faults_injected_total{site}``
and recorded as a trace instant, so a drill's dump/metrics artifacts
say precisely which faults were injected where.
"""
from __future__ import annotations

import threading

from .. import telemetry, trace
from ..base import MXNetError, get_env

__all__ = ["InjectedFault", "InjectedIOError", "FaultPlan", "SITES",
           "KINDS", "plan", "clear", "active", "armed", "refresh_env",
           "fire", "poisoned", "record_firing", "state",
           "ABORT_EXIT_CODE"]

# the registered site names (fire() accepts others — a drill may probe
# a site added later — but these are the ones wired into the stack)
SITES = ("trainer_step", "collective", "checkpoint_commit",
         "checkpoint_marker", "compile_commit", "serve_dispatch",
         "serve_poison", "serve_cache", "spec_verify", "data_read")
KINDS = ("transient", "io", "fatal", "abort")

# distinct from any real exit status the drills assert on (SIGKILL
# would be -9; preemption uses MXNET_PREEMPT_EXIT_CODE)
ABORT_EXIT_CODE = 77


class InjectedFault(MXNetError):
    """A planned fault.  ``kind`` is ``transient`` or ``fatal`` — the
    supervisor's taxonomy routes on it."""

    def __init__(self, msg, kind="transient", site=None, key=None):
        super().__init__(msg)
        self.kind = kind
        self.site = site
        self.key = key


class InjectedIOError(OSError):
    """A planned IO fault — an ``OSError`` so the existing
    retry-with-backoff paths (checkpoint commit, compile-cache commit)
    handle it exactly like a real storage hiccup."""

    def __init__(self, msg, site=None, key=None):
        super().__init__(msg)
        self.site = site
        self.key = key


class _Entry:
    __slots__ = ("site", "key", "kind", "count", "fired")

    def __init__(self, site, key, kind="transient", count=1):
        if kind not in KINDS:
            raise MXNetError("unknown fault kind %r (one of %s)"
                             % (kind, ", ".join(KINDS)))
        self.site = site
        self.key = str(key)
        self.kind = kind
        # count=None from the grammar means "no explicit *N": one-shot
        # for fault sites, UNLIMITED for serve_poison — a poisoned
        # request stays poisoned for its whole drill (re-checked on
        # every bisect retry and later dispatch); an explicit *N still
        # bounds it.  A stored count of None means unlimited.
        if count is None:
            count = None if site == "serve_poison" else 1
        self.count = None if count is None else int(count)
        self.fired = 0

    def matches(self, site, key):
        if site != self.site:
            return False
        if self.count is not None and self.fired >= self.count:
            return False
        return self.key == "*" or self.key == str(key)

    def describe(self):
        return {"site": self.site, "key": self.key, "kind": self.kind,
                "count": self.count, "fired": self.fired}


class FaultPlan:
    """A parsed, armed set of fault entries (see module grammar)."""

    def __init__(self, entries=()):
        self.entries = list(entries)

    @classmethod
    def parse(cls, spec):
        """``"site@key[:kind][*count],..."`` -> FaultPlan.  Whitespace
        around entries is ignored; an empty spec is an empty plan.

        A trailing ``*<digits>`` ALWAYS parses as the repeat count, so
        a literal key may not end in ``*<digits>`` — pick drill
        request ids accordingly.  The bare wildcard key ``site@*`` is
        unambiguous: the split below requires a non-empty prefix
        before the ``*``."""
        entries = []
        for raw in (spec or "").split(","):
            raw = raw.strip()
            if not raw:
                continue
            if "@" not in raw:
                raise MXNetError(
                    "bad MXNET_FAULTS entry %r: expected "
                    "site@key[:kind][*count]" % raw)
            site, _, rest = raw.partition("@")
            count = None        # no explicit *N: _Entry picks default
            head, star, tail = rest.rpartition("*")
            if star and head and tail.isdigit():
                rest, count = head, int(tail)
            kind = "transient"
            if ":" in rest:
                rest, _, kind = rest.rpartition(":")
            entries.append(_Entry(site.strip(), rest.strip(), kind,
                                  count))
        return cls(entries)

    def take(self, site, key):
        """Consume-and-return the first matching entry (or None).
        Caller holds the module lock."""
        for e in self.entries:
            if e.matches(site, key):
                e.fired += 1
                return e
        return None

    def match(self, site, key):
        """Non-consuming probe (poison checks fire on every retry of a
        bisected batch, so they must not burn a count)."""
        for e in self.entries:
            if e.matches(site, key):
                return e
        return None


_LOCK = threading.Lock()
_PLAN = None          # None = MXNET_FAULTS not read yet
_SEQ = {}             # per-site call counters (for seq=None sites)
# lock-free hot-path flag: None = plan not loaded yet, else
# bool(plan.entries).  fire()/poisoned() read it WITHOUT the lock, so
# an unarmed production process pays one attribute load per site —
# never a lock acquisition on the trainer step or serve dispatch path.
# (Entries can only appear via plan()/refresh_env(), which reset it.)
_ARMED = None


def _load_locked():
    global _PLAN, _ARMED
    if _PLAN is None:
        _PLAN = FaultPlan.parse(get_env("MXNET_FAULTS", str, ""))
        _ARMED = bool(_PLAN.entries)
    return _PLAN


def armed():
    """Cheap is-any-fault-planned probe (see ``_ARMED``)."""
    a = _ARMED
    if a is None:
        with _LOCK:
            a = bool(_load_locked().entries)
    return a


def plan(spec):
    """Arm a fault plan (a grammar string, or a prebuilt FaultPlan).
    Resets every per-site sequence counter so drills replay from a
    clean origin.  Returns the armed plan."""
    global _PLAN, _ARMED
    p = spec if isinstance(spec, FaultPlan) else FaultPlan.parse(spec)
    with _LOCK:
        _PLAN = p
        _ARMED = bool(p.entries)
        _SEQ.clear()
    return p


def clear():
    """Disarm: no faults fire until ``plan()`` or ``refresh_env()``."""
    with _LOCK:
        global _PLAN, _ARMED
        _PLAN = FaultPlan()
        _ARMED = False
        _SEQ.clear()


def refresh_env():
    """Re-read ``MXNET_FAULTS`` (the armed-at-import path reads it
    lazily on first ``fire``; tests that set the env later call
    this)."""
    global _PLAN
    with _LOCK:
        _PLAN = None
        _SEQ.clear()
        return _load_locked()


def active():
    return armed()


def record_firing(site, key=None, consume=False):
    """Count one logical firing (telemetry + trace instant).  ``fire``
    calls this itself after ``take`` already consumed the entry; the
    serve bisect path calls it with ``consume=True`` at the moment a
    poisoned request is isolated, so the plan's ``fired`` bookkeeping
    agrees with the telemetry counter (and retries of the same request
    during one dispatch count once)."""
    if consume:
        with _LOCK:
            e = _load_locked().match(site, key)
            if e is not None:
                e.fired += 1
    if telemetry.ENABLED:
        telemetry.RESILIENCE_FAULTS.labels(site=site).inc()
    trace.instant("fault_injected", cat="resilience",
                  args={"site": site, "key": None if key is None
                        else str(key)})


def fire(site, seq=None):
    """Fire the planned fault for ``(site, seq)`` — a no-op unless the
    armed plan holds a matching live entry.  With ``seq=None`` the
    site's own call counter is used (incremented only while a plan is
    armed, so sequences are deterministic from ``plan()``)."""
    if not armed():                 # lock-free production fast path
        return
    with _LOCK:
        p = _load_locked()
        if not p.entries:
            return
        if seq is None:
            seq = _SEQ.get(site, 0)
            _SEQ[site] = seq + 1
        entry = p.take(site, seq)
    if entry is None:
        return
    record_firing(site, seq)
    msg = ("injected %s fault at site %r (key %s, firing %d/%s)"
           % (entry.kind, site, entry.key, entry.fired,
              entry.count if entry.count is not None else "inf"))
    if entry.kind == "abort":
        import os
        import sys

        sys.stderr.write("mx.resilience: %s — hard exit %d\n"
                         % (msg, ABORT_EXIT_CODE))
        sys.stderr.flush()
        os._exit(ABORT_EXIT_CODE)
    if entry.kind == "io":
        raise InjectedIOError(msg, site=site, key=entry.key)
    raise InjectedFault(msg, kind=entry.kind, site=site, key=entry.key)


def poisoned(request_id):
    """True when the plan marks ``request_id`` as a poison request
    (site ``serve_poison``).  Non-consuming: a poisoned request stays
    poisoned through every bisect retry of its batch."""
    if request_id is None or not armed():
        return False
    with _LOCK:
        p = _load_locked()
        if not p.entries:
            return False
        return p.match("serve_poison", request_id) is not None


def state():
    """Snapshot for ``tools/diagnose.py --resilience``."""
    with _LOCK:
        p = _load_locked()
        return {"active": bool(p.entries),
                "entries": [e.describe() for e in p.entries],
                "seq": dict(_SEQ)}
