"""``mxnet.executor`` compat module (reference python/mxnet/executor.py).

1.x migration scripts do ``from mxnet import executor`` /
``mx.executor.Executor``; the implementation lives with the Symbol
(symbol/__init__.py) since an executor is a bound symbol closure here.
"""
from .symbol import Executor  # noqa: F401

__all__ = ["Executor"]
