"""Runtime telemetry: process-wide metrics registry + exporters.

Always-on, low-overhead observability for the runtime — the layer a full
xplane trace (mx.profiler) is too heavy for.  Counters/Gauges/Histograms
with labels cover compile-cache behaviour (gluon/block.py), engine pushes
(engine.py), host<->device transfer volume (ndarray), collective traffic
(kvstore/collective.py), dataloader stalls (gluon/data/dataloader.py) and
device-memory watermarks (``sample_device_memory`` over
``profiler.memory_info``).

Design constraints:

- Disabled cost is ONE boolean check per instrumentation hook
  (``if telemetry.ENABLED:``) — no dict lookups, no label/string work.
  ``MXNET_TELEMETRY_DISABLE=1`` flips it at import; ``disable()`` /
  ``enable()`` flip it at runtime.
- All mutation goes through one module lock, so metrics are safe to
  update from dataloader worker threads and the engine path.
- Timers use the monotonic clock (``time.perf_counter``); ``span(...)``
  and ``@timed(...)`` additionally feed profiler events when an xplane
  trace is live, so ad-hoc telemetry spans land in the chrome trace too.

Exporters: ``prometheus()`` (text exposition format), ``snapshot()`` /
``dump(path)`` (JSON), ``totals()`` (flat name->value convenience), and
an optional periodic log line driven by MXNET_TELEMETRY_LOG_INTERVAL.
"""
from __future__ import annotations

import json
import logging
import threading
import time

from .base import get_env

__all__ = [
    "ENABLED", "enable", "disable",
    "counter", "gauge", "histogram", "get_metric",
    "span", "timed",
    "snapshot", "totals", "value", "dump", "prometheus", "reset",
    "histogram_quantiles",
    "sample_device_memory", "log_line", "start_logger",
    "DEFAULT_BUCKETS",
]

_LOGGER = logging.getLogger("mxnet_tpu.telemetry")

# single lock for all registry + sample mutation (cheap: held only for
# a float add / list append, never across user code)
_LOCK = threading.Lock()
_REGISTRY = {}  # name -> metric, insertion-ordered

ENABLED = not get_env("MXNET_TELEMETRY_DISABLE", bool, False)

DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def enable():
    """Turn instrumentation hooks back on (module-wide)."""
    global ENABLED
    ENABLED = True


def disable():
    """Turn instrumentation hooks off; metrics keep their current values."""
    global ENABLED
    ENABLED = False


# ---------------------------------------------------------------------------
# metric kinds
# ---------------------------------------------------------------------------

class _CounterChild:
    __slots__ = ("_value",)

    def __init__(self):
        self._value = 0.0

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters can only increase (got %r)" % amount)
        with _LOCK:
            self._value += amount

    @property
    def value(self):
        return self._value


class _GaugeChild:
    __slots__ = ("_value",)

    def __init__(self):
        self._value = 0.0

    def set(self, v):
        with _LOCK:
            self._value = float(v)

    def inc(self, amount=1.0):
        with _LOCK:
            self._value += amount

    def dec(self, amount=1.0):
        with _LOCK:
            self._value -= amount

    @property
    def value(self):
        return self._value


class _HistogramChild:
    __slots__ = ("_buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets):
        self._buckets = buckets
        self._counts = [0] * (len(buckets) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v):
        v = float(v)
        i = 0
        for i, ub in enumerate(self._buckets):
            if v <= ub:
                break
        else:
            i = len(self._buckets)
        with _LOCK:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def read(self):
        """Locked consistent view: (count, sum, cumulative buckets)."""
        with _LOCK:
            count, total = self._count, self._sum
            counts = list(self._counts)
        out, acc = [], 0
        for ub, c in zip(self._buckets, counts):
            acc += c
            out.append((ub, acc))
        out.append((float("inf"), acc + counts[-1]))
        return count, total, out

    def cumulative(self):
        """[(upper_bound, cumulative_count), ...] ending with +Inf."""
        return self.read()[2]


_CHILD_FACTORY = {
    "counter": lambda m: _CounterChild(),
    "gauge": lambda m: _GaugeChild(),
    "histogram": lambda m: _HistogramChild(m.buckets),
}


class Metric:
    """A named metric family; label children are created on demand."""

    def __init__(self, kind, name, help="", labelnames=(), buckets=None):
        self.kind = kind
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS)) \
            if kind == "histogram" else None
        self._children = {}  # labelvalues tuple -> child
        self._default = None if self.labelnames \
            else _CHILD_FACTORY[kind](self)

    def labels(self, *values, **kwargs):
        if not self.labelnames:
            # a shadow () child would duplicate the default sample's
            # (empty-label) series in the prometheus output
            raise ValueError("%s has no labels: use it directly"
                             % self.name)
        if kwargs:
            if values:
                raise ValueError("pass labels positionally or by name, "
                                 "not both")
            if set(kwargs) != set(self.labelnames):
                raise ValueError(
                    "%s takes labels %s, got %s"
                    % (self.name, self.labelnames, sorted(kwargs)))
            values = tuple(kwargs[k] for k in self.labelnames)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError("%s expects labels %s, got %r"
                             % (self.name, self.labelnames, values))
        child = self._children.get(values)
        if child is None:
            with _LOCK:
                child = self._children.setdefault(
                    values, _CHILD_FACTORY[self.kind](self))
        return child

    def _delegate(self):
        if self._default is None:
            raise ValueError("%s has labels %s: call .labels(...) first"
                             % (self.name, self.labelnames))
        return self._default

    # unlabelled convenience surface
    def inc(self, amount=1.0):
        self._delegate().inc(amount)

    def dec(self, amount=1.0):
        self._delegate().dec(amount)

    def set(self, v):
        self._delegate().set(v)

    def observe(self, v):
        self._delegate().observe(v)

    @property
    def value(self):
        return self._delegate().value

    @property
    def count(self):
        return self._delegate().count

    @property
    def sum(self):
        return self._delegate().sum

    def _samples(self):
        """[(labelvalues tuple, child), ...] including the default child.

        The children dict is snapshotted under the lock: exporters (and
        the periodic log thread) iterate while labels() inserts."""
        with _LOCK:
            items = list(self._children.items())
        out = []
        if self._default is not None:
            out.append(((), self._default))
        out.extend(sorted(items))
        return out

    def _reset(self):
        # zero IN PLACE: instrumentation sites hold direct child refs
        # (e.g. TRANSFER_H2D), so replacing children would orphan them
        with _LOCK:
            children = list(self._children.values())
            if self._default is not None:
                children.append(self._default)
            for child in children:
                if self.kind == "histogram":
                    child._counts = [0] * (len(self.buckets) + 1)
                    child._sum = 0.0
                    child._count = 0
                else:
                    child._value = 0.0


def _register(kind, name, help, labelnames, buckets=None):
    # registration is cold-path: always validate under the lock so a
    # racing mis-typed registration raises instead of silently returning
    # a metric of the wrong kind
    with _LOCK:
        m = _REGISTRY.get(name)
        if m is not None:
            if m.kind != kind or m.labelnames != tuple(labelnames):
                raise ValueError(
                    "metric %r already registered as %s%s"
                    % (name, m.kind, m.labelnames))
            if kind == "histogram" and buckets is not None \
                    and tuple(sorted(buckets)) != m.buckets:
                raise ValueError(
                    "histogram %r already registered with buckets %s"
                    % (name, m.buckets))
            return m
        m = Metric(kind, name, help, labelnames, buckets)
        _REGISTRY[name] = m
    return m


def counter(name, help="", labelnames=()):
    """Get-or-create a monotonically increasing counter."""
    return _register("counter", name, help, labelnames)


def gauge(name, help="", labelnames=()):
    """Get-or-create a gauge (set/inc/dec)."""
    return _register("gauge", name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None):
    """Get-or-create a histogram with fixed upper-bound buckets."""
    return _register("histogram", name, help, labelnames, buckets)


def get_metric(name):
    """Look up a registered metric (None if absent)."""
    return _REGISTRY.get(name)


def reset():
    """Zero every registered metric (registrations are kept)."""
    for m in list(_REGISTRY.values()):
        m._reset()


# ---------------------------------------------------------------------------
# timers
# ---------------------------------------------------------------------------

def _feed_profiler(name, start, dur, cat="telemetry", args=None):
    """Land the span in the chrome trace when an xplane trace is live
    (``mx.trace`` spans route through here too, with their own cat and
    trace-id args).

    The running flag is read under ``_events_lock`` — the same lock
    appends take — so a concurrent ``set_state('stop')`` can't
    interleave between the check and the append.  The REAL thread id
    (and name) is recorded at append time so ``profiler.dump`` can put
    serve-scheduler / checkpoint-writer / trainer spans on separate
    Perfetto tracks."""
    from . import profiler

    # unlocked peek first: with no trace live (the steady state) this
    # must stay a boolean read, not a global lock acquisition on every
    # span exit across every thread; the flag is re-checked under the
    # lock so a concurrent set_state('stop') still can't interleave
    # with the append
    if not profiler._state["running"]:
        return
    with profiler._events_lock:
        if profiler._state["running"]:
            t = threading.current_thread()
            ev = {"name": name, "cat": cat, "ts": start, "dur": dur,
                  "tid": t.ident, "tname": t.name}
            if args:
                ev["args"] = args
            profiler._state["events"].append(ev)


class span:
    """Monotonic-clock timing context: observes ``<name>_seconds`` (or the
    given histogram) and feeds a profiler event when a trace is live.

    >>> with telemetry.span("train_step"):
    ...     step()
    """

    __slots__ = ("name", "_hist", "_start")

    def __init__(self, name, hist=None):
        self.name = name
        self._hist = hist
        self._start = None

    def __enter__(self):
        # disabled-at-enter spans stay dead for their whole lifetime:
        # no clock read here, and __exit__ is a single None check (a
        # span that straddles an enable() observes nothing — half a
        # duration would be a lie)
        self._start = time.perf_counter() if ENABLED else None
        return self

    def __exit__(self, *exc):
        if self._start is None or not ENABLED:
            return False
        dur = time.perf_counter() - self._start
        hist = self._hist
        if hist is None:
            hist = histogram(self.name + "_seconds",
                             "duration of %s spans" % self.name)
        hist.observe(dur)
        _feed_profiler(self.name, self._start, dur)
        return False


def timed(name, hist=None):
    """Decorator form of ``span``: time every call of fn."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not ENABLED:
                return fn(*args, **kwargs)
            with span(name, hist):
                return fn(*args, **kwargs)
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def _labels_dict(metric, values):
    return dict(zip(metric.labelnames, values))


def snapshot():
    """JSON-ready view: {name: {type, help, samples: [...]}}.

    Counter/gauge samples: {"labels": {...}, "value": v}; histogram
    samples: {"labels": {...}, "count": n, "sum": s, "buckets": {le: n}}
    with cumulative bucket counts ("+Inf" last).
    """
    out = {}
    for name, m in list(_REGISTRY.items()):
        samples = []
        for values, child in m._samples():
            labels = _labels_dict(m, values)
            if m.kind == "histogram":
                count, total, cum = child.read()
                samples.append({
                    "labels": labels, "count": count, "sum": total,
                    "buckets": {_fmt_le(ub): c for ub, c in cum}})
            else:
                samples.append({"labels": labels, "value": child.value})
        out[name] = {"type": m.kind, "help": m.help, "samples": samples}
    return out


def _bucket_quantile(cum, count, q):
    """Estimate the q-quantile from cumulative bucket counts (linear
    interpolation within the covering bucket, Prometheus
    histogram_quantile style).  Observations in the +Inf overflow
    bucket clamp to the last finite bound — the estimate never invents
    a value beyond what the buckets can resolve."""
    if count <= 0:
        return 0.0
    target = q * count
    lo, prev_c, last_finite = 0.0, 0, 0.0
    for ub, c in cum:
        if ub != float("inf"):
            last_finite = ub
        if c >= target:
            if ub == float("inf"):
                return last_finite
            width = c - prev_c
            if width <= 0:
                return ub
            return lo + (target - prev_c) / width * (ub - lo)
        prev_c = c
        if ub != float("inf"):
            lo = ub
    return last_finite


def _merged_read(metric, match=None):
    """(count, sum, merged cumulative buckets) across the label
    children of a histogram family (all children share the family's
    bucket edges).  ``match`` restricts the merge to children whose
    labels contain it — the per-tenant SLO view reads one tenant's
    samples out of a shared histogram."""
    want = {k: str(v) for k, v in (match or {}).items()}
    reads = [c.read() for values, c in metric._samples()
             if all(_labels_dict(metric, values).get(k) == v
                    for k, v in want.items())]
    count = sum(r[0] for r in reads)
    total = sum(r[1] for r in reads)
    cum = [(ub, sum(r[2][i][1] for r in reads))
           for i, (ub, _) in enumerate(reads[0][2])] if reads else []
    return count, total, cum


def histogram_quantiles(name, qs=(0.5, 0.95, 0.99)):
    """Bucket-estimated quantiles of a histogram family, merged over
    its label children: {q: seconds}.  {} for unknown/empty/non-
    histogram names — SLO-ish latency without scraping Prometheus."""
    m = _REGISTRY.get(name)
    if m is None or m.kind != "histogram":
        return {}
    count, _, cum = _merged_read(m)
    if not count:
        return {}
    return {q: _bucket_quantile(cum, count, q) for q in qs}


def totals(nonzero=False, quantiles=False):
    """Flat {name: summed value} over all label children; histograms
    contribute ``<name>_count`` and ``<name>_sum`` — plus bucket-
    estimated ``_p50``/``_p95``/``_p99`` when ``quantiles`` is set (the
    periodic log line asks for them).  The compact form bench rows and
    the periodic log line carry."""
    out = {}
    for name, m in list(_REGISTRY.items()):
        if m.kind == "histogram":
            count, total, cum = _merged_read(m)
            out[name + "_count"] = count
            out[name + "_sum"] = round(total, 6)
            if quantiles and count:
                for q, label in ((0.5, "_p50"), (0.95, "_p95"),
                                 (0.99, "_p99")):
                    out[name + label] = round(
                        _bucket_quantile(cum, count, q), 6)
        else:
            out[name] = sum(c.value for _, c in m._samples())
    if nonzero:
        out = {k: v for k, v in out.items() if v}
    return out


def value(name, labels=None):
    """Sum of a counter/gauge's samples whose labels contain ``labels``."""
    m = _REGISTRY.get(name)
    if m is None:
        return 0.0
    want = {k: str(v) for k, v in (labels or {}).items()}
    tot = 0.0
    for values, child in m._samples():
        have = _labels_dict(m, values)
        if all(have.get(k) == v for k, v in want.items()):
            tot += child.value if m.kind != "histogram" else child.count
    return tot


def dump(path):
    """Write the JSON snapshot to ``path``; returns the path."""
    with open(path, "w") as f:
        json.dump({"time": time.time(), "enabled": ENABLED,
                   "metrics": snapshot()}, f, indent=2, sort_keys=True)
    return path


def _fmt_le(ub):
    return "+Inf" if ub == float("inf") else repr(float(ub))


def _esc(v):
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _esc_help(v):
    # HELP text escapes only backslash and newline (the exposition
    # format spec) — quotes stay literal, unlike label values
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _labelstr(metric, values, extra=()):
    pairs = list(zip(metric.labelnames, values)) + list(extra)
    if not pairs:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _esc(v)) for k, v in pairs)


def prometheus():
    """Prometheus text exposition format (one # HELP/# TYPE pair plus
    sample lines per registered metric)."""
    lines = []
    for name, m in list(_REGISTRY.items()):
        lines.append("# HELP %s %s" % (name, _esc_help(m.help or name)))
        lines.append("# TYPE %s %s" % (name, m.kind))
        for values, child in m._samples():
            if m.kind == "histogram":
                count, total, cum = child.read()
                for ub, c in cum:
                    lines.append("%s_bucket%s %d" % (
                        name, _labelstr(m, values, [("le", _fmt_le(ub))]),
                        c))
                lines.append("%s_sum%s %s"
                             % (name, _labelstr(m, values), repr(total)))
                lines.append("%s_count%s %d"
                             % (name, _labelstr(m, values), count))
            else:
                lines.append("%s%s %s" % (name, _labelstr(m, values),
                                          repr(float(child.value))))
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# device-memory sampler
# ---------------------------------------------------------------------------

def sample_device_memory(device=None):
    """Refresh ``device_memory_bytes`` gauges from profiler.memory_info()
    (PJRT memory_stats; CPU backends report nothing).  Returns the raw
    report for convenience."""
    if not ENABLED:
        return {}
    from . import profiler

    try:
        report = profiler.memory_info(device)
    except Exception:  # backend down: telemetry must never raise
        return {}
    for dev, stats in report.items():
        for stat, v in stats.items():
            DEVICE_MEMORY.labels(device=dev, stat=stat).set(v)
    return report


# ---------------------------------------------------------------------------
# periodic log line
# ---------------------------------------------------------------------------

_logger_started = False


def log_line():
    """One compact 'telemetry k=v ...' line over the nonzero totals
    (histograms carry their bucket-estimated p50/p95/p99).  Registered
    SLOs are evaluated first so their state/burn gauges are fresh in
    the same line."""
    try:
        from .obs import slo_engine as _slo

        if _slo.registered():
            _slo.evaluate()
    except Exception:  # noqa: BLE001 - the log line must never fail
        pass
    tot = totals(nonzero=True, quantiles=True)
    body = " ".join(
        "%s=%s" % (k, ("%d" % v) if float(v).is_integer() else
                   ("%.6g" % v))
        for k, v in sorted(tot.items()))
    return "telemetry " + (body or "(all zero)")


def _log_loop(interval):
    while True:
        time.sleep(interval)
        try:
            if ENABLED:
                sample_device_memory()
                _LOGGER.info(log_line())
        except Exception:  # noqa: BLE001 - the log thread must survive
            _LOGGER.exception("telemetry log tick failed")


def start_logger(interval=None):
    """Start the periodic telemetry log thread (idempotent).  With no
    argument, reads MXNET_TELEMETRY_LOG_INTERVAL (seconds; 0 = off)."""
    global _logger_started
    if interval is None:
        interval = get_env("MXNET_TELEMETRY_LOG_INTERVAL", float, 0.0)
    if not interval or interval <= 0 or _logger_started:
        return False
    t = threading.Thread(target=_log_loop, args=(float(interval),),
                         daemon=True, name="mxnet-telemetry-log")
    t.start()
    _logger_started = True
    return True


# ---------------------------------------------------------------------------
# canonical framework metrics (registered at import so every exporter
# emits a stable, documented set — see README "Telemetry & observability")
# ---------------------------------------------------------------------------

CACHEDOP_BUILD = counter(
    "cachedop_build_total",
    "hybridize cache compiles (one jit trace per new signature)",
    ("block",))
CACHEDOP_HIT = counter(
    "cachedop_hit_total", "hybridize cache hits", ("block",))
CACHEDOP_RECOMPILE = counter(
    "cachedop_recompile_total",
    "cache builds that added a signature to an already-warm block "
    "(shape/dtype/mode churn)", ("block",))
CACHEDOP_BUILD_SECONDS = histogram(
    "cachedop_build_seconds", "hybridize trace+compile latency")
ENGINE_PUSH = counter(
    "engine_push_total", "ops pushed through the engine facade")
ENGINE_NAIVE_WAIT = counter(
    "engine_naive_wait_total",
    "blocking waits forced by NaiveEngine mode")
TRANSFER_BYTES = counter(
    "transfer_bytes_total", "host<->device transfer volume",
    ("direction",))
TRANSFER_D2H = TRANSFER_BYTES.labels(direction="d2h")
TRANSFER_H2D = TRANSFER_BYTES.labels(direction="h2d")
COLLECTIVE_CALLS = counter(
    "collective_calls_total", "collective programs dispatched", ("op",))
COLLECTIVE_BYTES = counter(
    "collective_bytes_total", "bytes moved by collectives", ("op",))
COLLECTIVE_SECONDS = histogram(
    "collective_seconds", "collective dispatch+assembly latency")
ALLREDUCE_BUCKET_FILL = histogram(
    "allreduce_bucket_fill",
    "fill fraction of each fused all-reduce bucket relative to "
    "MXNET_KVSTORE_BUCKET_BYTES (>1 = one oversized array)",
    buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0, 1.5, 2.0))
# imperative Trainer multi-tensor update engine (optimizer/
# multi_tensor.py): one fused, buffer-donated program per parameter
# group per step; eager per-parameter updates are the fallback path
TRAINER_FUSED_GROUPS = gauge(
    "trainer_fused_groups",
    "multi-tensor update groups in the last imperative Trainer step")
TRAINER_FUSED_APPLY = counter(
    "trainer_fused_apply_total",
    "fused multi-tensor update programs launched", ("optimizer",))
TRAINER_FUSED_BUILDS = counter(
    "trainer_fused_builds_total",
    "multi-tensor group program builds (trace + compile)",
    ("optimizer",))
TRAINER_EAGER_UPDATES = counter(
    "trainer_eager_updates_total",
    "per-parameter eager optimizer updates (multi-tensor fallback)",
    ("reason",))
TRAINER_UPDATE_SECONDS = histogram(
    "trainer_update_seconds",
    "imperative Trainer optimizer-apply dispatch latency per step")
DATALOADER_WAIT_SECONDS = histogram(
    "dataloader_batch_wait_seconds",
    "time the training loop blocked waiting for the next batch")
DEVICE_MEMORY = gauge(
    "device_memory_bytes", "PJRT device memory stats "
    "(sample_device_memory refreshes)", ("device", "stat"))
# mx.checkpoint (checkpoint/manager.py + writer.py): snapshot is the
# only critical-path phase of an async save; serialize/commit run on
# the background writer
CHECKPOINT_SNAPSHOT_SECONDS = histogram(
    "checkpoint_snapshot_seconds",
    "device->host snapshot time (critical path of an async save)")
CHECKPOINT_SERIALIZE_SECONDS = histogram(
    "checkpoint_serialize_seconds",
    "background shard serialize+durable-write (streamed) time")
CHECKPOINT_COMMIT_SECONDS = histogram(
    "checkpoint_commit_seconds",
    "background manifest/marker write + atomic-publish time")
CHECKPOINT_BYTES = counter(
    "checkpoint_bytes_total", "checkpoint shard bytes moved",
    ("direction",))
CHECKPOINT_QUEUE_DEPTH = gauge(
    "checkpoint_async_queue_depth",
    "async saves snapshotted but not yet committed")
CHECKPOINT_RETRIES = counter(
    "checkpoint_retries_total",
    "commit attempts retried after a transient I/O error")
CHECKPOINT_SAVES = counter(
    "checkpoint_saves_total", "checkpoint commits by outcome",
    ("result",))
CHECKPOINT_RESTORES = counter(
    "checkpoint_restores_total", "checkpoint restore calls")
# mx.serve (serve/): dynamic-batching inference serving.  Queue wait is
# the time a request sat in the BatchQueue before its micro-batch was
# dispatched; pad waste is the zero-fill the bucket table forced.
SERVE_REQUESTS = counter(
    "serve_requests_total", "serving requests by outcome "
    "(ok/rejected/timeout/error/cancelled/quarantined/poisoned)",
    ("result",))
SERVE_REQUEST_SECONDS = histogram(
    "serve_request_seconds",
    "end-to-end request latency (enqueue -> result set)")
SERVE_QUEUE_WAIT_SECONDS = histogram(
    "serve_queue_wait_seconds",
    "time a request waited in the batch queue before dispatch")
SERVE_QUEUE_DEPTH = gauge(
    "serve_queue_depth", "requests currently waiting in the batch queue")
SERVE_BATCHES = counter(
    "serve_batches_total", "micro-batches dispatched to the model runner")
SERVE_BATCH_SIZE = histogram(
    "serve_batch_size", "requests coalesced per dispatched micro-batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
SERVE_PAD_ELEMENTS = counter(
    "serve_pad_elements_total",
    "zero elements added by bucket padding (batch + shape fill)")
SERVE_PAD_FRACTION = histogram(
    "serve_pad_fraction",
    "padded/total element fraction per dispatched micro-batch",
    buckets=(0.01, 0.05, 0.1, 0.2, 0.3, 0.5, 0.7, 0.9))
SERVE_COMPILES = counter(
    "serve_compile_total",
    "hybridize compiles triggered by serving, by bucket "
    "(steady state: one per bucket, all during warm-up)", ("bucket",))
SERVE_SWAPS = counter(
    "serve_model_swaps_total", "hot model swaps (atomic runner "
    "replacement pointing at a new checkpoint step)")
# mx.compile (compile/): persistent compilation cache + AOT warm-start.
# A hit means a stored XLA executable was loaded and the compile was
# skipped; a miss means the lookup ran but nothing usable was stored.
COMPILE_CACHE_HIT = counter(
    "compile_cache_hit_total",
    "persistent compile-cache artifact loads (XLA compile skipped)")
COMPILE_CACHE_MISS = counter(
    "compile_cache_miss_total",
    "persistent compile-cache lookups with no usable artifact "
    "(fresh compile follows, then a commit)")
COMPILE_CACHE_COMMIT = counter(
    "compile_cache_commit_total",
    "compiled executables durably committed to the persistent cache")
COMPILE_CACHE_EVICT = counter(
    "compile_cache_evict_total",
    "cache entries evicted by the LRU size cap")
COMPILE_CACHE_QUARANTINE = counter(
    "compile_cache_quarantine_total",
    "corrupt cache entries quarantined (renamed *.corrupt, never "
    "loaded again)")
COMPILE_CACHE_FALLBACK = counter(
    "compile_cache_fallback_total",
    "AOT executable calls that failed and fell back to the in-memory "
    "jit path (aval drift etc.)")
COMPILE_CACHE_LOAD_SECONDS = histogram(
    "compile_cache_load_seconds",
    "artifact read + checksum-verify latency")
COMPILE_CACHE_COMMIT_SECONDS = histogram(
    "compile_cache_commit_seconds",
    "artifact serialize + durable-commit latency")
# mx.trace (trace/): flight-recorder dumps and watchdog activity —
# reason is manual / crash / exit / slow_step / deadline_burst /
# divergence / hang / dry_run (export.py), scope names the watch that
# stalled (watchdog.py)
TRACE_DUMPS = counter(
    "trace_dumps_total",
    "flight-recorder dumps written, by trigger reason", ("reason",))
TRACE_WATCHDOG_FIRES = counter(
    "trace_watchdog_fires_total",
    "hang-watchdog reports (no progress past the scope timeout)",
    ("scope",))
# mx.monitor (monitor/): on-device training-health numerics.  One
# fused stat reduction program per multi-tensor parameter group per
# step (grad/weight L2 norm, max|x|, nonfinite counts); values reach
# the gauges through the async host-fetch ring, so a lag of a step or
# two behind the live device state is expected.
MONITOR_STAT_BUILDS = counter(
    "monitor_stat_builds_total",
    "stat reduction program builds (trace + compile; steady state: "
    "one per parameter group, zero per-step retraces)")
MONITOR_STAT_PROGRAMS = counter(
    "monitor_stat_programs_total",
    "stat reduction programs dispatched (groups x observed steps)")
MONITOR_GRAD_NORM = gauge(
    "monitor_grad_norm", "last observed per-group gradient L2 norm",
    ("group",))
MONITOR_WEIGHT_NORM = gauge(
    "monitor_weight_norm", "last observed per-group weight L2 norm",
    ("group",))
MONITOR_GRAD_MAX = gauge(
    "monitor_grad_max_abs", "last observed per-group max |grad|",
    ("group",))
MONITOR_WEIGHT_MAX = gauge(
    "monitor_weight_max_abs", "last observed per-group max |weight|",
    ("group",))
MONITOR_GRAD_GLOBAL_NORM = gauge(
    "monitor_grad_global_norm",
    "last observed global gradient L2 norm (sqrt of the per-group "
    "squared-norm sum)")
MONITOR_GRAD_GLOBAL_NORM_HIST = histogram(
    "monitor_grad_global_norm_hist",
    "distribution of the global gradient L2 norm over observed steps",
    buckets=(0.001, 0.01, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 50.0, 100.0,
             1000.0))
MONITOR_NONFINITE = counter(
    "monitor_nonfinite_total",
    "nonfinite (NaN/Inf) elements observed, saturating at ~2^24 per "
    "program (f32 on-device count)", ("kind", "group"))
MONITOR_NONFINITE_STEPS = counter(
    "monitor_nonfinite_steps_total",
    "observed steps with at least one nonfinite gradient element")
MONITOR_SKIPPED_STEPS = counter(
    "monitor_skipped_steps_total",
    "trainer steps skipped whole by the nonfinite sentinel "
    "(policy=skip_step; params/optimizer state untouched)")
MONITOR_SENTINEL_TRIPS = counter(
    "monitor_sentinel_trips_total",
    "nonfinite sentinel trips by the policy in force", ("policy",))
MONITOR_DROPS = counter(
    "monitor_dropped_total",
    "stat entries displaced from the bounded host-fetch ring before "
    "the publisher drained them")
MONITOR_FETCH_SECONDS = histogram(
    "monitor_fetch_seconds",
    "device->host stat vector fetch latency (synchronous only when "
    "the sentinel policy needs the value to gate the step)")
SERVE_NONFINITE_OUTPUTS = counter(
    "serve_nonfinite_outputs_total",
    "nonfinite (NaN/Inf) elements in served model outputs "
    "(mx.monitor output guard; surfaced at /statz)")
SERVE_NONFINITE_BATCHES = counter(
    "serve_nonfinite_batches_total",
    "dispatched micro-batches containing at least one nonfinite "
    "output element")
# mx.step (step/): whole-program training-step capture — forward,
# loss, backward, bucketed allreduce, fused optimizer apply and the
# monitor stat reductions traced into ONE donated XLA program per
# step.  The stitched imperative path stays the always-correct
# fallback; every degradation is counted by reason, never a lost step.
STEP_CAPTURE_BUILDS = counter(
    "step_capture_builds_total",
    "whole-step captured program builds (trace + compile; steady "
    "state: one per (input-signature, optimizer-hparams, monitor "
    "mode) — zero per-step retraces)")
STEP_CAPTURE_STEPS = counter(
    "step_capture_steps_total",
    "training steps executed through mx.step, by path "
    "(captured = one whole-step XLA program; stitched = the "
    "imperative fwd/bwd/allreduce/apply sequence)", ("path",))
STEP_CAPTURE_FALLBACKS = counter(
    "step_capture_fallback_total",
    "captured-step degradations to the stitched path, by reason "
    "(capture/compile/dispatch failure, kill switch, unsupported "
    "trainer shape) — the step is still applied", ("reason",))
STEP_PROGRAM_SECONDS = histogram(
    "step_program_seconds",
    "captured whole-step program host latency per step (slot eval + "
    "dispatch + writeback; the program itself runs async)")
# mx.shard (shard/): global-mesh SPMD training with ZeRO-1/2/3
# cross-replica weight-update sharding.  The gauges record the LIVE
# per-device residency after mesh placement — the memory contract the
# bench rows and acceptance tests bound (state ~1/dp for zero>=1,
# params ~1/dp for zero=3).
SHARD_DEVICE_BYTES = gauge(
    "shard_device_bytes",
    "bytes resident on ONE device after mx.shard mesh placement, by "
    "array kind (params / optimizer_state)", ("kind",))
SHARD_ZERO_LEVEL = gauge(
    "shard_zero_level",
    "ZeRO weight-update sharding level of the most recently placed "
    "captured step program (0 = replicated data-parallel)")
SHARD_COLLECTIVE_BYTES = counter(
    "shard_collective_bytes_total",
    "priced wire bytes of mesh collectives issued by captured "
    "programs, by mesh axis (dp / mdl) and collective op — the "
    "per-axis comms bill the first live TPU window calibrates "
    "against measured step time", ("axis", "op"))
SHARD_TP_MODE = gauge(
    "shard_tp_mode",
    "tensor-parallel execution mode of the most recently placed "
    "captured step program (0 = gather [bit-exact storage sharding], "
    "1 = compute [Megatron sharded matmuls])")
# mx.resilience (resilience/): deterministic fault injection,
# preemption handling, and the hardened restart supervisor — plus the
# serve-side graceful-degradation counters (bisect/poison/breakers).
RESILIENCE_FAULTS = counter(
    "resilience_faults_injected_total",
    "planned faults fired, by injection site (MXNET_FAULTS / "
    "resilience.plan())", ("site",))
RESILIENCE_RESTARTS = counter(
    "resilience_restarts_total",
    "supervisor recovery events by kind (transient / divergence / "
    "fatal / budget_exhausted / unhealthy)", ("kind",))
RESILIENCE_BACKOFF_SECONDS = histogram(
    "resilience_backoff_seconds",
    "jittered exponential backoff slept between restarts")
RESILIENCE_PREEMPTIONS = counter(
    "resilience_preemptions_total",
    "preemption requests observed (SIGTERM or resilience.request())")
RESILIENCE_EMERGENCY_SAVES = counter(
    "resilience_emergency_saves_total",
    "emergency checkpoints flushed during preemption shutdown")
SERVE_POISON = counter(
    "serve_poison_requests_total",
    "requests whose failure was isolated by bisect retry while their "
    "batch-mates were served independently")
SERVE_BISECT_SPLITS = counter(
    "serve_bisect_splits_total",
    "failed micro-batches split in half for retry (poison isolation)")
SERVE_BREAKER_STATE = gauge(
    "serve_breaker_state",
    "per-bucket circuit breaker state (0=closed 1=half-open 2=open)",
    ("bucket",))
SERVE_BREAKER_TRIPS = counter(
    "serve_breaker_trips_total",
    "circuit breaker openings (bucket quarantined after repeated "
    "dispatch failures)", ("bucket",))
# mx.serve.decode (serve/decode.py + kvcache.py): paged KV-cache +
# continuous batching for autoregressive serving.  One decode-step
# program per (batch-bucket, page-config) runs every iteration over
# whichever sequences are live; buckets label compiles like the
# vision path's serve_compile_total.
SERVE_DECODE_TOKENS = counter(
    "serve_decode_tokens_total", "tokens generated by the decode loop")
SERVE_DECODE_STEPS = counter(
    "serve_decode_steps_total",
    "continuous-batching decode iterations dispatched")
SERVE_DECODE_PREFILLS = counter(
    "serve_decode_prefills_total",
    "sequences prefilled through the prompt bucket path")
SERVE_DECODE_BATCH = histogram(
    "serve_decode_batch_size",
    "live sequences per decode iteration (varies step to step as "
    "sequences join and leave the running batch)",
    buckets=(1, 2, 4, 8, 16, 32, 64))
SERVE_DECODE_LIVE = gauge(
    "serve_decode_live_sequences",
    "sequences currently decoding in the running batch")
SERVE_DECODE_WAITING = gauge(
    "serve_decode_waiting_sequences",
    "sequences queued for admission (slots or KV pages exhausted)")
SERVE_DECODE_TTFT_SECONDS = histogram(
    "serve_decode_ttft_seconds",
    "time to first token: submit -> the prefill-produced token, by "
    "prefix-cache outcome (hit / partial / miss)",
    ("cache",))
SERVE_DECODE_TOKEN_SECONDS = histogram(
    "serve_decode_token_seconds",
    "per-token decode latency (one continuous-batching iteration)")
SERVE_DECODE_COMPILES = counter(
    "serve_decode_compile_total",
    "decode/prefill program builds by bucket (steady state: at most "
    "one per bucket, all during warm-up; mx.compile restores count 0)",
    ("bucket",))
SERVE_DECODE_EVICTIONS = counter(
    "serve_decode_evictions_total",
    "sequences evicted from the running batch, by reason (finished / "
    "timeout / poisoned / error / quarantined / cancelled)",
    ("reason",))
SERVE_KV_PAGES_IN_USE = gauge(
    "serve_kv_pages_in_use",
    "KV-cache pool pages currently reserved by live sequences")
SERVE_KV_PAGES_HIGH_WATER = gauge(
    "serve_kv_pages_high_water",
    "high-water mark of reserved KV-cache pool pages")
# mx.serve.cache (serve/cache.py): the radix prefix cache — identical
# prompt prefixes prefill once per replica, not once per request.
SERVE_PREFIX_LOOKUPS = counter(
    "serve_prefix_lookups_total",
    "prefix-cache admissions by outcome (hit = every cacheable prompt "
    "block matched, partial = some, miss = none)",
    ("result",))
SERVE_PREFIX_HIT_TOKENS = counter(
    "serve_prefix_hit_tokens_total",
    "prompt tokens served from cached prefix pages (prefill work "
    "avoided)")
SERVE_PREFIX_SHARED_PAGES = gauge(
    "serve_prefix_shared_pages",
    "KV pool pages in the shared refcounted segment (prefix trie + "
    "live readers)")
SERVE_PREFIX_EVICTIONS = counter(
    "serve_prefix_evictions_total",
    "prefix trie nodes dropped (LRU pool pressure, corrupt-drill "
    "invalidation, or clear)")
SERVE_DECODE_PREFILL_TOKENS = counter(
    "serve_decode_prefill_tokens_total",
    "prompt tokens actually run through a prefill/chunk program (the "
    "uncached suffix only; the fleet drill asserts one full prefill "
    "per shared prompt fleet-wide)")
# mx.tenant (tenant/): multi-tenant serving — batched LoRA adapter
# multiplexing, WFQ admission, per-tenant quotas/isolation.  The
# tenant label is the registered tenant name; base (un-tenanted)
# traffic never touches these families.
TENANT_REQUESTS = counter(
    "tenant_requests_total",
    "tenant-attributed serving requests by outcome "
    "(ok/rejected/timeout/error/cancelled/quarantined/poisoned)",
    ("tenant", "result"))
TENANT_TTFT_SECONDS = histogram(
    "tenant_ttft_seconds",
    "time to first token per tenant (the per-tenant SLO feed)",
    ("tenant",))
TENANT_TOKENS = counter(
    "tenant_tokens_total", "tokens emitted per tenant", ("tenant",))
TENANT_QUOTA_REJECTS = counter(
    "tenant_quota_rejects_total",
    "submissions rejected by a per-tenant quota, by reason "
    "(queue / pages) — per-tenant 503s, never head-of-line blocking",
    ("tenant", "reason"))
TENANT_WFQ_PICKS = counter(
    "tenant_wfq_picks_total",
    "admissions granted by the weighted-fair-queueing picker",
    ("tenant",))
TENANT_ADAPTER_SWAPS = counter(
    "tenant_adapter_swaps_total",
    "adapter bank slot swaps (hot load/unload; compile count stays "
    "flat — slot content is data, not program)")
TENANT_ADAPTER_POISON = counter(
    "tenant_adapter_poison_total",
    "nonfinite evictions attributed to a tenant's adapter (feeds the "
    "per-adapter breaker that quarantines ONLY that slot)",
    ("tenant",))
TENANT_SLOTS = gauge(
    "tenant_adapter_slots",
    "adapter bank capacity of the serving process")
TENANT_ADAPTERS_RESIDENT = gauge(
    "tenant_adapters_resident",
    "adapter slots currently holding a loaded adapter")
# mx.serve.spec (serve/spec.py): speculative decoding — draft-propose,
# target-verify, greedy acceptance (bit-identical to single-step).
SERVE_SPEC_ROUNDS = counter(
    "serve_spec_rounds_total",
    "speculative rounds reaching the verify dispatch")
SERVE_SPEC_PROPOSED = counter(
    "serve_spec_proposed_total",
    "draft tokens proposed to the target verifier")
SERVE_SPEC_ACCEPTED = counter(
    "serve_spec_accepted_total",
    "draft tokens accepted by greedy verification (accepted/proposed "
    "is the acceptance rate; accepted tokens cost no extra target "
    "step)")
SERVE_SPEC_FALLBACKS = counter(
    "serve_spec_fallbacks_total",
    "sequences degraded to non-speculative decode, by reason "
    "(draft_pool / draft_prefill / draft_nonfinite / draft_error / "
    "draft_lost / injected)",
    ("reason",))
# mx.dist (dist/): coordinated multi-host fault tolerance —
# collective deadlines, membership, pod-consistent checkpoints.
DIST_COLLECTIVE_TIMEOUTS = counter(
    "dist_collective_timeouts_total",
    "collectives that missed MXNET_DIST_COLLECTIVE_TIMEOUT (a peer "
    "rank unreachable), by site", ("site",))
DIST_WORLD_STOPS = counter(
    "dist_world_stops_total",
    "coordinated world-stop flags this rank posted first, by reason "
    "(failure / preempt / drill)", ("reason",))
DIST_POD_COMMITS = counter(
    "dist_pod_commits_total",
    "pod-level checkpoint barrier outcomes (ok = POD marker "
    "published after all ranks acked; timeout = torn pod commit, "
    "step unselectable at restore)", ("result",))
DIST_LEAVES = counter(
    "dist_member_leaves_total",
    "clean membership departures by reason", ("reason",))
# mx.autotune (autotune/): self-tuning kernels, buckets, and flags —
# measured micro-bench search with a bitwise numerics guard, winners
# persisted in the env-fingerprinted TuningStore next to the compile
# cache.  Every degrade-to-default path is counted so a tuned fleet
# that silently fell back to hand-set literals is visible.
AUTOTUNE_LOOKUPS = counter(
    "autotune_lookup_total",
    "build-time tuned-config lookups by site and result (tuned = a "
    "stored winner was served; default = hand-set literal)",
    ("site", "result"))
AUTOTUNE_MEASURE = counter(
    "autotune_measure_total",
    "candidate configs measured by the search harness / idle tuners "
    "(a warm store means a fresh process re-measures NOTHING)",
    ("site",))
AUTOTUNE_REJECT = counter(
    "autotune_reject_total",
    "candidates rejected by the measure guards (numerics = output "
    "not bit-identical to the default config's; shape; nonfinite; "
    "error)", ("site", "reason"))
AUTOTUNE_FALLBACK = counter(
    "autotune_fallback_total",
    "degrades to the hand-set default by reason (store_unavailable / "
    "store_corrupt / store_error / store_write / invalid_config / "
    "measure_error / serve_idle / ...)", ("reason",))
AUTOTUNE_STORE_COMMITS = counter(
    "autotune_store_commits_total",
    "tuning records durably committed to the TuningStore")
AUTOTUNE_STORE_QUARANTINE = counter(
    "autotune_store_quarantine_total",
    "corrupt/torn tuning records parked at *.corrupt (never trusted "
    "again; lookups degraded to defaults)")
AUTOTUNE_TUNE_SECONDS = histogram(
    "autotune_tune_seconds",
    "wall time of one tune() search (default + all candidates)",
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0))
# mx.data (data/): sharded streaming input pipeline.  The ring gauges
# are the H3 health signal: steady state is occupancy ~ depth and a
# flat stall counter — a climbing stall count means reads/decode (not
# H2D) bound the pipeline, so raise MXNET_DATA_WORKERS first.  The
# loop-blocked time itself lands in dataloader_batch_wait_seconds,
# shared with the classic DataLoader.
DATA_RING_DEPTH = gauge(
    "data_ring_depth",
    "configured prefetch ring depth (batches staged ahead; "
    "MXNET_DATA_PREFETCH or the data_prefetch autotune site)")
DATA_RING_OCCUPANCY = gauge(
    "data_ring_occupancy",
    "device-staged batches currently waiting in the prefetch ring")
DATA_RING_STALLS = counter(
    "data_ring_stalls_total",
    "times the training loop arrived at an EMPTY prefetch ring "
    "(the reader/decode stage fell behind the step program)")
DATA_READ_SECONDS = histogram(
    "data_read_seconds",
    "shard record-read time per batch (worker-side, after retries)")
DATA_DECODE_SECONDS = histogram(
    "data_decode_seconds",
    "decode + batchify time per batch (worker-side)")
DATA_STAGE_SECONDS = histogram(
    "data_stage_seconds",
    "host batch -> device/mesh staging dispatch time (the transfer "
    "itself runs async under PJRT)")
DATA_BATCHES = counter(
    "data_batches_total", "batches staged through the prefetch ring")
DATA_RECORDS = counter(
    "data_records_total", "records read + decoded by reader workers")
DATA_READ_RETRIES = counter(
    "data_read_retries_total",
    "reader IO attempts retried after an OSError (incl. injected "
    "data_read io faults)")
DATA_RESUMES = counter(
    "data_resumes_total",
    "mid-epoch cursor restores (checkpoint resume of the stream)")
# mx.obs (obs/): the fleet-wide observability plane — cross-rank
# snapshot publishing over the membership KV, straggler detection,
# SLO burn rates, and per-step attribution.  Publish failures are the
# "fleet view degraded to local-only" signal.
OBS_PUBLISHES = counter(
    "obs_publish_total",
    "per-rank obs payloads published into the membership KV")
OBS_PUBLISH_FAILURES = counter(
    "obs_publish_failures_total",
    "obs payload publishes that failed (dead/partitioned KV; the "
    "fleet view degrades to local-only until it recovers)")
OBS_STRAGGLERS = counter(
    "obs_stragglers_total",
    "straggler episodes flagged per rank (step p50 above "
    "MXNET_OBS_STRAGGLER_FACTOR x the fleet median)", ("rank",))
OBS_SLO_STATE = gauge(
    "obs_slo_state",
    "per-objective SLO state (0=OK 1=WARN 2=PAGE, multi-window "
    "burn-rate evaluation)", ("slo",))
OBS_SLO_BURN = gauge(
    "obs_slo_burn_rate",
    "error-budget burn rate per objective and window (1.0 = burning "
    "exactly the budget)", ("slo", "window"))
OBS_STEP_SECONDS = histogram(
    "obs_step_seconds",
    "training-step wall time as seen by the obs cadence hook",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0, 60.0))
OBS_ATTRIB_RECORDS = counter(
    "obs_attribution_records_total",
    "per-step attribution records written (JSONL stream when "
    "MXNET_OBS_ATTRIBUTION is set)")
OBS_FLEET_RANKS = gauge(
    "obs_fleet_ranks",
    "ranks visible in the last fleet-view refresh (1 + local_only "
    "means the membership KV is unreachable)")
# mx.fleet (fleet/): the multi-replica serving fleet — KV-backed
# service discovery, the load-aware router front-end, prefill/decode
# page handoff, and zero-drop failover.
FLEET_PUBLISHES = counter(
    "fleet_publish_total",
    "replica discovery records published into the membership KV "
    "(heartbeat-piggybacked, rate-limited)")
FLEET_PUBLISH_FAILURES = counter(
    "fleet_publish_failures_total",
    "discovery record publishes that failed (dead/partitioned KV; "
    "the replica ages out of the router's view until it recovers)")
FLEET_REQUESTS = counter(
    "fleet_router_requests_total",
    "router-fronted requests by outcome (ok / rejected = whole-fleet "
    "saturation or no routable replica / failed / poisoned)",
    ("result",))
FLEET_DISPATCHES = counter(
    "fleet_router_dispatch_total",
    "upstream dispatch attempts by pool plane (micro / prefill / "
    "decode; retries count again)", ("plane",))
FLEET_AFFINITY_HITS = counter(
    "fleet_prefix_affinity_total",
    "decode dispatches routed by prefix-cache affinity (the prompt's "
    "first block was already cached on the chosen replica)")
FLEET_ADAPTER_AFFINITY = counter(
    "fleet_adapter_affinity_total",
    "decode dispatches routed by tenant-adapter residency (the "
    "tenant's adapter was already resident on the chosen replica)")
FLEET_FAILOVERS = counter(
    "fleet_failover_total",
    "mid-request re-routes after a replica death or connection "
    "failure (the zero-drop replay path)")
FLEET_HANDOFFS = counter(
    "fleet_handoff_total",
    "prefill->decode KV page handoffs by result (ok / "
    "checksum_mismatch / error)", ("result",))
FLEET_HANDOFF_BYTES = histogram(
    "fleet_handoff_bytes",
    "serialized page-handoff blob size (pages + cursor + sampler "
    "state, one checksummed blob)",
    buckets=(1024, 4096, 16384, 65536, 262144, 1048576, 4194304,
             16777216))
FLEET_ROUTER_OVERHEAD_SECONDS = histogram(
    "fleet_router_overhead_seconds",
    "router-added time per request (refresh + replica pick + "
    "bookkeeping, excluding upstream serving time)",
    buckets=(1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1))
FLEET_ROUTER_REQUEST_SECONDS = histogram(
    "fleet_router_request_seconds",
    "end-to-end latency of router-fronted requests (the fleet SLO "
    "objective's feed)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0, 10.0, 30.0))
FLEET_REPLICAS = gauge(
    "fleet_replicas_live",
    "fresh, non-draining replicas in the router's last discovery "
    "refresh")
FLEET_ROLLOUTS = counter(
    "fleet_rollout_replicas_total",
    "replicas drained and swapped by fleet.rollout() (one at a time, "
    "riding Server's graceful drain)")
FLEET_POISON_VERDICTS = counter(
    "fleet_poison_verdicts_total",
    "poison verdicts published to the KV (first writer wins; every "
    "router stops retrying the sequence fleet-wide)")

start_logger()
